"""Compile-surface prover: the static side of bench.py's
``jit_recompiles == 0`` gate.

Every headline number since PR 6 assumes the jit cache is BOUNDED: a
fixed set of entry points, each compiled once per (shape bucket,
static key). That invariant was only enforced at runtime — a recompile
bug shipped silently until someone ran the right bench arm. These four
whole-program rules prove the bound statically, riding the
`core.Program` call graph (the same graph every manifest rule uses):

- ``unbucketed-shape`` — in functions reachable from a jit-feeding
  entry (any function that calls a jitted callable or a jit-program
  factory), an array whose dimension derives from a raw data-dependent
  int (``len(...)`` and arithmetic over it) may not ESCAPE toward the
  device path: assigned to an attribute, passed to a jitted call, fed
  to ``device_put``, or built through ``jnp`` directly. Every distinct
  raw shape is one more compiled program; sizes must route through a
  registered bucket function (``bucket_size`` over the ladder of
  ``*_BUCKETS``, anything returning one, or a hand-rolled sizer the
  module registers via a ``NTA_BUCKET_FNS`` manifest). Locally
  consumed host arrays (masks, tallies) stay quiet — a raw shape is
  only a compile key once it can reach the device.

- ``static-key-drift`` — call sites of jitted functions must pass
  STABLE static args: config objects, names, constants, bools. An
  ad-hoc per-eval key — an f-string, a ``str(...)``/``%``-format
  build, a computed number, a tuple holding computed elements — is
  one-compile-per-eval. ``build_placement_config`` (scheduler/tpu.py)
  is the sanctioned factory; opaque calls stay quiet so routing
  through it (or any constructor) is always clean. Unhashable
  literals (list/dict/set) are purity's ``jit-unhashable-static``.

- ``unregistered-jit`` — every ``jax.jit``-compiled entry point
  (decorated def, ``x = jax.jit(f)`` wrap, or a jit call inside a
  program factory) and every ``functools.lru_cache`` compile cache in
  ``ops//kernels//models//parallel/`` must appear in the
  ``NTA_JIT_ACCOUNTED`` manifest (ops/binpack.py), which mirrors the
  runtime ``jit_cache_size()`` accounting — an unaccounted entry
  point blinds the bench recompile gate exactly the way the PR 7
  SARIF rule-list omission blinded CI. Inert when no analyzed module
  declares the manifest (fixture subsets). The manifest<->runtime
  agreement is itself tested (tests/test_compile_surface.py).

- ``donation-unsafe-read`` — a buffer passed in a donated position
  (``donate_argnums``/``donate_argnames``) of a jitted callable is
  dead after the call; any later read in the caller is a
  use-after-free the moment the backend actually reuses the buffer.
  The real tree is donation-free by construction today (PR 6
  deliberately does not donate resident parents — the registry-empty
  TN self-check encodes that); the rule is the pre-laid rail for
  ROADMAP item 3's fused cohort programs with donated buffers.

All four run in the PROGRAM pass so findings carry `Finding.related`
witness chains (entry -> ... -> site for reachability, def/call sites
for call-site rules) and share the tree-digest cache under
RULESET_VERSION.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Module, Program
from .purity import _call_name, _is_jit_expr, _root_name

RULE_UNBUCKETED = "unbucketed-shape"
RULE_KEY_DRIFT = "static-key-drift"
RULE_UNREGISTERED = "unregistered-jit"
RULE_DONATION = "donation-unsafe-read"

# Module-level manifests (collected by core.Program like every NTA_*):
# the jit entry points the runtime cache accounting covers, and
# hand-rolled bucket/pad sizers beyond the bucket_size family.
JIT_MANIFEST = "NTA_JIT_ACCOUNTED"
BUCKET_MANIFEST = "NTA_BUCKET_FNS"

# Where unregistered-jit enforces: the dirs jit_cache_size() accounts.
JIT_SCOPE_MARKERS = ("/ops/", "/kernels/", "/models/", "/parallel/")
# Where unbucketed-shape enforces: the device-feeding path.
SHAPE_SCOPE_MARKERS = ("/ops/", "/kernels/", "/models/", "/parallel/",
                       "/scheduler/", "/dispatch/", "/defrag/",
                       "/gang/", "/migrate/")

# The root of the sanctioned sizer family; NTA_BUCKET_FNS and the
# returns-a-bucketizer closure extend it (topo_group_pad, _k_bucket).
BASE_BUCKET_FNS = ("bucket_size",)
# Array constructors whose first arg / shape= kwarg is a shape.
SHAPE_CTORS = {"zeros", "ones", "empty", "full", "arange"}
# Host->device boundary calls: a dirty array passed here IS on the
# compile surface, no further escape needed.
DEVICE_XFER_NAMES = {"device_put"}
DEVICE_ROOTS = {"jnp"}


class JitCallable:
    """One jitted callable visible at call sites: a decorated def or a
    module-level ``x = jax.jit(f, ...)`` wrap."""

    __slots__ = ("name", "rel", "line", "params", "statics", "donated")

    def __init__(self, name: str, rel: str, line: int,
                 params: List[str], statics: Set[str],
                 donated: Set[str]):
        self.name = name
        self.rel = rel
        self.line = line
        self.params = params
        self.statics = statics
        self.donated = donated


class JitEntryPoint:
    """One accountable compile cache: the module-level symbol that owns
    a jit (or lru_cache) site — the def itself, the enclosing factory
    for a nested ``jax.jit(...)`` call, or the assignment target of a
    module-level wrap."""

    __slots__ = ("rel", "name", "line", "kind")

    def __init__(self, rel: str, name: str, line: int, kind: str):
        self.rel = rel
        self.name = name
        self.line = line
        self.kind = kind  # "jit" | "lru_cache"


def _donate_from_call(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    """(donated positional indices, donated param names) declared on a
    jit(...) / partial(jax.jit, ...) expression."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        vals: List[ast.AST] = []
        if isinstance(kw.value, (ast.Tuple, ast.List)):
            vals = list(kw.value.elts)
        elif isinstance(kw.value, ast.Constant):
            vals = [kw.value]
        if kw.arg == "donate_argnums":
            for el in vals:
                if isinstance(el, ast.Constant) and isinstance(
                        el.value, int):
                    nums.add(el.value)
        elif kw.arg == "donate_argnames":
            for el in vals:
                if isinstance(el, ast.Constant) and isinstance(
                        el.value, str):
                    names.add(el.value)
    return nums, names


def _jit_spec(dec: ast.AST):
    """(statics, donate_nums, donate_names) when `dec` is a
    jit-wrapping expression, else None."""
    statics = _is_jit_expr(dec)
    if statics is None:
        return None
    nums: Set[int] = set()
    names: Set[str] = set()
    if isinstance(dec, ast.Call):
        nums, names = _donate_from_call(dec)
    return statics, nums, names


def _fn_params(fn: ast.AST) -> List[str]:
    args = fn.args
    return [a.arg for a in args.posonlyargs + args.args]


def _is_lru_expr(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        dec = dec.func
    return _call_name(dec) == "lru_cache" or (
        isinstance(dec, ast.Name) and dec.id == "lru_cache")


def _top_level_owner(mod: Module, node: ast.AST) -> Tuple[str, int]:
    """(accountable name, line) of the module-level statement that owns
    `node`: a nested jit inside a factory is accounted to the factory
    (shard.py's ``sharded_base_delta``), a module-level wrap to its
    assignment target."""
    top = node
    cur = node
    while cur is not None:
        parent = mod.parents.get(cur)
        if isinstance(parent, ast.Module):
            top = cur
            break
        cur = parent
    if isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return top.name, top.lineno
    if isinstance(top, ast.ClassDef):
        return mod.symbol_of(node), getattr(node, "lineno", top.lineno)
    if isinstance(top, ast.Assign):
        for tgt in top.targets:
            if isinstance(tgt, ast.Name):
                return tgt.id, top.lineno
    return mod.symbol_of(node), getattr(node, "lineno", 0)


def scan_jit_callables(program: Program) -> Dict[str, JitCallable]:
    """Bare name -> JitCallable over every analyzed module: decorated
    defs (including nested ones) and module-level ``x = jax.jit(f)``
    wraps whose wrapped def is local. Call sites in this codebase
    import these directly, so bare-name keying matches purity's
    registry."""
    out: Dict[str, JitCallable] = {}
    for mod in program.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    spec = _jit_spec(dec)
                    if spec is None:
                        continue
                    statics, nums, names = spec
                    params = _fn_params(node)
                    donated = set(names)
                    donated.update(params[i] for i in nums
                                   if i < len(params))
                    out[node.name] = JitCallable(
                        node.name, mod.rel, node.lineno, params,
                        statics, donated)
                    break
            elif isinstance(node, ast.Assign):
                if not (isinstance(node.value, ast.Call)
                        and _is_jit_expr(node.value) is not None
                        and node.value.args):
                    continue
                wrapped = node.value.args[0]
                if not isinstance(wrapped, ast.Name):
                    continue
                fn = program.functions.get((mod.rel, wrapped.id))
                if fn is None:
                    continue
                statics, nums, names = _jit_spec(node.value)
                params = _fn_params(fn)
                donated = set(names)
                donated.update(params[i] for i in nums
                               if i < len(params))
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = JitCallable(
                            tgt.id, mod.rel, node.lineno, params,
                            statics, donated)
    return out


def scan_jit_entry_points(mod: Module) -> List[JitEntryPoint]:
    """Every accountable compile cache declared in `mod`: jit-decorated
    defs, jit Call sites that are not decorators (module-level wraps,
    factory-nested compiles), and lru_cache-decorated defs. De-duped
    per accountable name (a factory compiling once per build() is one
    cache)."""
    decorator_calls = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                for sub in ast.walk(dec):
                    decorator_calls.add(id(sub))
    seen: Dict[str, JitEntryPoint] = {}
    for node in ast.walk(mod.tree):
        entry: Optional[JitEntryPoint] = None
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            kind = None
            for dec in node.decorator_list:
                if _is_jit_expr(dec) is not None:
                    kind = "jit"
                    break
                if _is_lru_expr(dec):
                    kind = "lru_cache"
                    break
            if kind is not None:
                name, line = _top_level_owner(mod, node)
                entry = JitEntryPoint(mod.rel, name, line, kind)
        elif (isinstance(node, ast.Call) and id(node) not in
                decorator_calls and _is_jit_expr(node) is not None):
            name, line = _top_level_owner(mod, node)
            entry = JitEntryPoint(mod.rel, name, node.lineno, "jit")
        if entry is not None and entry.name not in seen:
            seen[entry.name] = entry
    return [seen[k] for k in sorted(seen)]


def _in_scope(rel: str, markers) -> bool:
    return any(m in "/" + rel for m in markers)


# ------------------------------------------------- unregistered-jit


def _check_unregistered(program: Program,
                        findings: List[Finding]) -> None:
    declared: Set[str] = set()
    manifest_sites: List[str] = []
    for rel, entries in sorted(
            program.manifests.get(JIT_MANIFEST, {}).items()):
        declared.update(entries)
        line = program.manifest_lines.get(JIT_MANIFEST, {}).get(rel, 0)
        manifest_sites.append(f"{rel}:{line}")
    if not declared:
        return  # no manifest in the analyzed set: rule is inert
    for mod in program.modules:
        if not _in_scope(mod.rel, JIT_SCOPE_MARKERS):
            continue
        for ep in scan_jit_entry_points(mod):
            if ep.name in declared:
                continue
            what = ("compile cache 'functools.lru_cache'"
                    if ep.kind == "lru_cache" else "jit entry point")
            findings.append(Finding(
                RULE_UNREGISTERED, mod.rel, ep.line, 0,
                f"{what} '{ep.name}' is absent from the "
                f"{JIT_MANIFEST} manifest — jit_cache_size() cannot "
                f"account it and the bench recompile gate is blind to "
                f"it; register it (and its runtime accounting) in "
                f"ops/binpack.py", ep.name,
                related=list(manifest_sites)))


# ------------------------------------------------- unbucketed-shape


def _bucket_functions(program: Program) -> Set[str]:
    """Sanctioned sizer names: bucket_size, NTA_BUCKET_FNS manifest
    entries, and (to a fixed point) any function with a return that is
    a call to an already-sanctioned sizer (topo_group_pad, _k_bucket)."""
    names: Set[str] = set(BASE_BUCKET_FNS)
    for entries in program.manifests.get(BUCKET_MANIFEST, {}).values():
        names.update(entries)
    changed = True
    while changed:
        changed = False
        for (_rel, qual), fn in program.functions.items():
            name = qual.split(".")[-1]
            if name in names:
                continue
            for node in ast.walk(fn):
                if (isinstance(node, ast.Return)
                        and isinstance(node.value, ast.Call)
                        and _call_name(node.value.func) in names):
                    names.add(name)
                    changed = True
                    break
    return names


class _ShapeTaint:
    """Per-function taint over data-dependent ints and the arrays they
    size. `len(...)` (outside a sanctioned sizer call) is the dirty
    source; names assigned from dirty expressions stay dirty; a
    bucketizer call sanitizes its whole subtree. IfExp TESTS are
    excluded — ``pad if rows else BUCKETS[0]`` branches on a dirty
    count without sizing anything by it."""

    def __init__(self, fn: ast.AST, bucket_fns: Set[str]):
        self.bucket_fns = bucket_fns
        self.dirty_ints: Set[str] = set()
        self.dirty_arrays: Set[str] = set()
        self._fixed_point(fn)

    def _fixed_point(self, fn: ast.AST) -> None:
        changed = True
        while changed:
            changed = False
            for stmt in ast.walk(fn):
                if isinstance(stmt, ast.Assign):
                    targets = stmt.targets
                elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                    targets = [stmt.target]
                else:
                    continue
                value = getattr(stmt, "value", None)
                if value is None:
                    continue
                dirty_int = self.int_dirty(value)
                dirty_arr = self.array_dirty(value)
                for tgt in targets:
                    if not isinstance(tgt, ast.Name):
                        continue
                    if dirty_int and tgt.id not in self.dirty_ints:
                        self.dirty_ints.add(tgt.id)
                        changed = True
                    if dirty_arr and tgt.id not in self.dirty_arrays:
                        self.dirty_arrays.add(tgt.id)
                        changed = True

    def _walk(self, expr: ast.AST):
        """Walk pruning sanitized subtrees: bucketizer calls, IfExp
        tests, nested defs/lambdas."""
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.Lambda, ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                continue
            if (isinstance(node, ast.Call)
                    and _call_name(node.func) in self.bucket_fns):
                continue
            yield node
            if isinstance(node, ast.IfExp):
                stack.extend((node.body, node.orelse))
                continue
            stack.extend(ast.iter_child_nodes(node))

    def int_dirty(self, expr: ast.AST) -> bool:
        """True when `expr` carries a raw data-dependent int."""
        for node in self._walk(expr):
            if (isinstance(node, ast.Call)
                    and _call_name(node.func) == "len"):
                return True
            if (isinstance(node, ast.Name)
                    and node.id in self.dirty_ints):
                return True
        return False

    def dirty_shape_ctor(self, call: ast.Call) -> bool:
        """True when `call` is an array constructor sized by a dirty
        int (first positional arg or shape= kwarg)."""
        if _call_name(call.func) not in SHAPE_CTORS:
            return False
        shape_args = list(call.args[:1])
        shape_args += [kw.value for kw in call.keywords
                       if kw.arg == "shape"]
        return any(self.int_dirty(a) for a in shape_args)

    def array_dirty(self, expr: ast.AST) -> bool:
        """True when `expr` yields an array sized by a dirty int: a
        dirty-shape ctor, a dirty array name, its .copy()/slices."""
        if isinstance(expr, ast.Call):
            if self.dirty_shape_ctor(expr):
                return True
            if (isinstance(expr.func, ast.Attribute)
                    and _root_name(expr.func) in self.dirty_arrays):
                return True
            return False
        if isinstance(expr, ast.Name):
            return expr.id in self.dirty_arrays
        if isinstance(expr, ast.Subscript):
            return self.array_dirty(expr.value)
        if isinstance(expr, ast.IfExp):
            return (self.array_dirty(expr.body)
                    or self.array_dirty(expr.orelse))
        return False

    def first_dirty_site(self, expr: ast.AST) -> Optional[ast.AST]:
        """The node to report: an embedded dirty-shape ctor, or a
        dirty array/int reference."""
        for node in self._walk(expr):
            if isinstance(node, ast.Call) and self.dirty_shape_ctor(node):
                return node
            if isinstance(node, ast.Name) and (
                    node.id in self.dirty_arrays):
                return node
        return None


def _check_fn_shapes(mod: Module, qual: str, fn: ast.AST,
                     bucket_fns: Set[str],
                     jit_names: Set[str], note: str,
                     related: List[str],
                     findings: List[Finding]) -> None:
    taint = _ShapeTaint(fn, bucket_fns)

    def emit(node: ast.AST, how: str) -> None:
        findings.append(Finding(
            RULE_UNBUCKETED, mod.rel, node.lineno, node.col_offset,
            f"array sized by a raw data-dependent int (len(...)) "
            f"{how} on a jit-feeding path{note}; route the size "
            f"through a registered bucket function (bucket_size / "
            f"{BUCKET_MANIFEST}) — every distinct shape is one more "
            f"compiled program", qual, related=list(related)))

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            # attribute stores escape the function toward the device
            # path (resident bases, matrix fields)
            if (any(isinstance(t, ast.Attribute) for t in node.targets)
                    and taint.array_dirty(node.value)):
                site = taint.first_dirty_site(node.value)
                emit(site if site is not None else node.value,
                     "stored to an attribute")
        elif isinstance(node, ast.Call):
            fname = _call_name(node.func)
            root = _root_name(node.func)
            if root in DEVICE_ROOTS and taint.dirty_shape_ctor(node):
                emit(node, "built on device")
                continue
            is_sink = (fname in jit_names
                       or fname in DEVICE_XFER_NAMES
                       or (root in DEVICE_ROOTS
                           and fname in ("asarray", "array")))
            if not is_sink:
                continue
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                site = taint.first_dirty_site(arg)
                if site is not None and (taint.array_dirty(arg)
                                         or taint.int_dirty(arg)):
                    emit(site, f"passed to '{fname}'")


def _check_unbucketed(program: Program,
                      callables: Dict[str, JitCallable],
                      findings: List[Finding]) -> None:
    if not callables:
        return
    bucket_fns = _bucket_functions(program)
    jit_names = set(callables)
    jit_def_keys = {(c.rel, c.name) for c in callables.values()}
    entries = []
    for key, fn in program.functions.items():
        if key in jit_def_keys:
            continue  # the jitted body itself traces; purity owns it
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and _call_name(node.func) in jit_names):
                entries.append(key)
                break
    if not entries:
        return
    via = program.reachable_with_paths(sorted(entries))
    for key in sorted(via):
        rel, qual = key
        if not _in_scope(rel, SHAPE_SCOPE_MARKERS):
            continue
        if key in jit_def_keys or qual.split(".")[-1] in bucket_fns:
            continue
        mod = program.by_rel.get(rel)
        if mod is None:
            continue
        note, related = program.witness_info(via, key)
        _check_fn_shapes(mod, qual, program.functions[key], bucket_fns,
                         jit_names, note, related, findings)


# ------------------------------------------------- static-key-drift

_ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv,
              ast.Pow, ast.Mod)
_STRING_BUILDERS = {"str", "repr", "format", "hex", "oct", "chr",
                    "join"}


def _constant_only(expr: ast.AST) -> bool:
    return all(isinstance(n, (ast.Constant, ast.expr_context,
                              ast.operator, ast.unaryop, ast.BinOp,
                              ast.UnaryOp, ast.Tuple))
               for n in ast.walk(expr))


def _drift_reason(expr: ast.AST) -> Optional[str]:
    """Why `expr` mints a fresh compile key per call, or None when it
    is a stable static (name, constant, attribute, config factory —
    opaque calls are sanctioned so build_placement_config is always
    clean)."""
    if isinstance(expr, ast.JoinedStr):
        return "an f-string (a fresh key per call)"
    if isinstance(expr, ast.Call):
        if _call_name(expr.func) in _STRING_BUILDERS:
            return f"a per-call '{_call_name(expr.func)}(...)' build"
        return None
    if isinstance(expr, ast.BinOp):
        if _constant_only(expr):
            return None  # folded once, stable
        if (isinstance(expr.op, ast.Mod)
                and isinstance(expr.left, ast.Constant)
                and isinstance(expr.left.value, str)):
            return "a %-formatted string (a fresh key per call)"
        if isinstance(expr.op, _ARITH_OPS):
            return ("a computed value (one compile per distinct "
                    "result)")
        return None
    if isinstance(expr, ast.Tuple):
        for el in expr.elts:
            r = _drift_reason(el)
            if r is not None:
                return f"a fresh tuple holding {r}"
        return None
    if isinstance(expr, ast.IfExp):
        return _drift_reason(expr.body) or _drift_reason(expr.orelse)
    return None


def _check_key_drift(program: Program,
                     callables: Dict[str, JitCallable],
                     findings: List[Finding]) -> None:
    for mod in program.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            info = callables.get(_call_name(node.func) or "")
            if info is None or not info.statics:
                continue
            related = [f"{info.rel}:{info.line}"]
            checks: List[Tuple[str, ast.AST]] = []
            for i, arg in enumerate(node.args):
                if (i < len(info.params)
                        and info.params[i] in info.statics):
                    checks.append((info.params[i], arg))
            for kw in node.keywords:
                if kw.arg in info.statics:
                    checks.append((kw.arg, kw.value))
            for pname, arg in checks:
                reason = _drift_reason(arg)
                if reason is None:
                    continue
                findings.append(Finding(
                    RULE_KEY_DRIFT, mod.rel, arg.lineno,
                    arg.col_offset,
                    f"static arg '{pname}' of jitted '{info.name}' is "
                    f"{reason} — one compile per eval; derive statics "
                    f"from the declared config surface "
                    f"(build_placement_config / PlacementConfig "
                    f"fields)", mod.symbol_of(node),
                    related=related))


# --------------------------------------------- donation-unsafe-read


def _chain_text(expr: ast.AST) -> Optional[str]:
    """Stable text for a Name / dotted-attribute buffer reference."""
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _check_donation(program: Program,
                    callables: Dict[str, JitCallable],
                    findings: List[Finding]) -> None:
    donating = {n: c for n, c in callables.items() if c.donated}
    if not donating:
        return
    for key, fn in sorted(program.functions.items()):
        rel, qual = key
        mod = program.by_rel.get(rel)
        if mod is None:
            continue
        # (buffer text, call end line, jit def site, call site)
        donated_bufs: List[Tuple[str, int, str, str]] = []
        store_lines: Dict[str, List[int]] = {}
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.For)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    text = _chain_text(tgt)
                    if text is not None:
                        store_lines.setdefault(text, []).append(
                            node.lineno)
            if not isinstance(node, ast.Call):
                continue
            info = donating.get(_call_name(node.func) or "")
            if info is None:
                continue
            bound: List[Tuple[str, ast.AST]] = []
            for i, arg in enumerate(node.args):
                if i < len(info.params):
                    bound.append((info.params[i], arg))
            for kw in node.keywords:
                if kw.arg:
                    bound.append((kw.arg, kw.value))
            for pname, arg in bound:
                if pname not in info.donated:
                    continue
                text = _chain_text(arg)
                if text is None:
                    continue
                donated_bufs.append((
                    text, getattr(node, "end_lineno", node.lineno),
                    f"{info.rel}:{info.line}",
                    f"{mod.rel}:{node.lineno}"))
        if not donated_bufs:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, (ast.Name, ast.Attribute)):
                continue
            if not isinstance(getattr(node, "ctx", None), ast.Load):
                continue
            text = _chain_text(node)
            if text is None:
                continue
            for buf, end, def_site, call_site in donated_bufs:
                if text != buf or node.lineno <= end:
                    continue
                call_line = int(call_site.rsplit(":", 1)[1])
                rebound = any(call_line <= s <= node.lineno
                              for s in store_lines.get(buf, ()))
                if rebound:
                    continue
                findings.append(Finding(
                    RULE_DONATION, mod.rel, node.lineno,
                    node.col_offset,
                    f"read of '{buf}' after it was donated at "
                    f"{call_site} — a donated buffer is dead the "
                    f"moment the jitted call runs; copy before "
                    f"donating or drop the read", qual,
                    related=[def_site, call_site]))
                break


# ----------------------------------------------------------- driver


def program_check(program: Program) -> List[Finding]:
    """All four compile-surface rules over one Program."""
    findings: List[Finding] = []
    callables = scan_jit_callables(program)
    _check_unregistered(program, findings)
    _check_unbucketed(program, callables, findings)
    _check_key_drift(program, callables, findings)
    _check_donation(program, callables, findings)
    return findings
