"""Device-residency checker: the full-matrix re-ship must not creep
back.

The device-resident design (models/resident.py) exists because every
dense batch used to re-ship the whole ``[N, R]`` node matrix to the
device before placing — BENCH_r08 measured that round-trip at 84% of
the e2e p99. The fix keeps the matrix resident and scatters small row
deltas; the regression mode is silent: a ``jax.device_put`` (or a
``device_resident()`` upload) creeping into a steady-state dispatch or
scheduler path still *works*, it just ships 10-100x the bytes per
batch and nobody notices until the tail blows up again.

One rule:

- ``full-matrix-reship`` (``dispatch/``, ``scheduler/``, ``models/``):
  any host->device transfer call — ``jax.device_put`` /
  ``device_put`` / ``device_resident`` — outside the functions a
  module declares in its rebuild manifest::

      NTA_REBUILD_ENTRYPOINTS = ("PlacementBatcher._build_device_base",)

  The manifest names the ONE sanctioned full-upload path (the rebuild
  safety net + first-touch upload); everything else on the steady
  state must ride the delta/cached paths. Modules without a manifest
  allow NO transfer calls at all in the scoped dirs. Escape hatch, as
  everywhere: ``# nta: disable=full-matrix-reship`` with a reason.

``parallel/mesh.py``'s sharding helpers are deliberately out of scope:
they are infrastructure the manifest functions call, not a dispatch
path of their own. ``parallel/shard.py`` (the explicit shard_map
programs) and ``models/classes.py`` (the compression plane's
class-expansion helpers) ARE in scope, with a ZERO baseline: their
whole design is that no transfer lives there, and the scope keeps an
expansion helper from smuggling a ``device_put`` into the hot path.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, Module

RULE_RESHIP = "full-matrix-reship"

SCOPE_MARKERS = ("/dispatch/", "/scheduler/", "/models/", "/kernels/",
                 "/gang/", "/parallel/shard")

REBUILD_MANIFEST = "NTA_REBUILD_ENTRYPOINTS"
# Call names that move host arrays onto the device. `device_put`
# matches both `jax.device_put(...)` and a bare imported `device_put`;
# `device_resident` is ops/binpack.py's jitted-identity upload.
TRANSFER_ATTRS = {"device_put"}
TRANSFER_NAMES = {"device_put", "device_resident"}


def _in_scope(rel_path: str) -> bool:
    p = "/" + rel_path
    return any(m in p for m in SCOPE_MARKERS)


def manifest_entries(mod: Module) -> List[str]:
    """The module's declared rebuild manifest (public: the static-
    analysis suite's uniqueness gate walks every scoped module and
    asserts the union stays the ONE sanctioned full-upload path)."""
    return _rebuild_manifest(mod)


def _rebuild_manifest(mod: Module) -> List[str]:
    out: List[str] = []
    for node in mod.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and tgt.id == REBUILD_MANIFEST:
                if isinstance(node.value, (ast.Tuple, ast.List)):
                    for el in node.value.elts:
                        if isinstance(el, ast.Constant) and isinstance(
                                el.value, str):
                            out.append(el.value)
    return out


def _is_transfer_call(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr in TRANSFER_ATTRS
    if isinstance(func, ast.Name):
        return func.id in TRANSFER_NAMES
    return False


def check(mod: Module) -> List[Finding]:
    if not _in_scope(mod.rel):
        return []
    allowed = set(_rebuild_manifest(mod))
    findings: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not _is_transfer_call(node):
            continue
        qual = mod.symbol_of(node)
        if qual in allowed:
            continue
        findings.append(Finding(
            RULE_RESHIP, mod.rel, node.lineno, node.col_offset,
            f"host->device transfer outside the rebuild manifest "
            f"({REBUILD_MANIFEST}) — steady-state dispatch/scheduler "
            f"paths must ride the delta/cached resident-base paths; a "
            f"full re-ship here regresses silently (10-100x bytes/"
            f"batch, BENCH_r08's 524ms tail)", qual))
    return findings
