"""Lock-discipline checker.

Three rules:

- ``guarded-by`` — an attribute whose ``__init__`` assignment carries a
  ``# guarded-by: <lock>`` comment may only be read or written while
  that lock is held (lexically inside a ``with self.<lock>`` /
  ``with self.<cond-on-that-lock>`` block). Constructor assignments are
  exempt (single-threaded by construction).

- ``lock-blocking-call`` — no blocking call (``time.sleep``,
  ``.result()``, ``urlopen``, ``block_until_ready``, ``submit_plan``,
  ``.join()``, foreign ``.wait()``) lexically inside a with-lock body.
  ``cond.wait(...)`` on a condition WHOSE OWN LOCK is held is exempt —
  a condition wait releases the lock, it cannot convoy other holders.

- ``dispatcher-blocking-call`` — functions reachable from the
  dispatcher-thread entrypoints a module declares in
  ``NTA_DISPATCHER_ENTRYPOINTS = ("Class.method", ...)`` must contain
  no blocking call at all. The only exemption is a BOUNDED
  ``cond.wait(timeout)`` on a condition whose lock is held — that is
  the dispatcher's scheduling primitive, not a foreign dependency.
  Reachability is WHOLE-PROGRAM (core.Program): ``self.m()``,
  module-level ``f()``, imported functions, module-attribute calls and
  constructor/typed-attribute calls are all followed across modules —
  a blocking call hidden two modules deep behind a ``utils`` helper is
  the dispatcher's problem, not the helper's. References handed to
  thread pools or ``Thread(target=...)`` run on OTHER threads and are
  not followed — that is exactly the sanctioned fix for a finding.

Locks are recognized from ``threading.Lock()/RLock()/Condition()``
construction: module-level names and ``self.<attr>`` assignments in
``__init__``. ``Condition(self._lock)`` aliases the condition to its
lock, so holding either name satisfies a guard on the other.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Module, Program

RULE_GUARDED = "guarded-by"
RULE_LOCK_BLOCKING = "lock-blocking-call"
RULE_DISPATCHER_BLOCKING = "dispatcher-blocking-call"

# Attribute names whose call is blocking regardless of receiver.
BLOCKING_ATTRS = {"block_until_ready", "result", "urlopen",
                  "submit_plan", "sleep", "join", "wait"}
# Bare-name calls that are blocking.
BLOCKING_NAMES = {"urlopen", "sleep"}

# The profiled wrappers (nomad_tpu/profile/locks.py) are drop-in
# threading primitives: ProfiledCondition(self._lock, "site") aliases
# to its backing lock exactly like Condition(self._lock), so guarded-by
# contracts, the deadlock detector and the dispatcher rule all hold
# over instrumented call sites unchanged.
_LOCK_CTORS = {"Lock", "RLock", "ProfiledLock", "ProfiledRLock"}
_COND_CTORS = {"Condition", "ProfiledCondition"}

# Canonical lock id: ("self", attr) for instance locks (per class),
# ("mod", name) for module-level locks.
LockId = Tuple[str, str]


class _ClassInfo:
    def __init__(self, name: str):
        self.name = name
        # attr -> canonical LockId of the lock itself (conds resolve to
        # their backing lock).
        self.locks: Dict[str, LockId] = {}
        self.conds: Set[str] = set()  # attrs that are Condition objects
        self.guarded: Dict[str, LockId] = {}  # attr -> required lock


def _ctor_kind(call: ast.Call) -> Optional[str]:
    """'lock' / 'cond' when `call` constructs a threading primitive —
    matched on the constructor NAME so both `threading.Lock()` and
    `__import__("threading").Lock()` register."""
    func = call.func
    name = None
    if isinstance(func, ast.Attribute):
        name = func.attr
    elif isinstance(func, ast.Name):
        name = func.id
    if name in _LOCK_CTORS:
        return "lock"
    if name in _COND_CTORS:
        return "cond"
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _ModuleIndex:
    """Pass 1: lock registry, guarded attrs, and the per-module
    function table. (Dispatcher reachability moved to the
    whole-program graph — core.Program — in PR 7.)"""

    def __init__(self, mod: Module):
        self.mod = mod
        self.module_locks: Dict[str, LockId] = {}  # name -> LockId
        self.module_conds: Set[str] = set()
        self.classes: Dict[str, _ClassInfo] = {}
        # qualname -> FunctionDef for every def (methods qualified as
        # Class.method, module funcs bare).
        self.functions: Dict[str, ast.FunctionDef] = {}
        self._build()

    def _build(self) -> None:
        tree = self.mod.tree
        for node in tree.body:
            if isinstance(node, ast.Assign):
                self._module_assign(node)
            elif isinstance(node, ast.ClassDef):
                self._scan_class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node

    def _module_assign(self, node: ast.Assign) -> None:
        if not isinstance(node.value, ast.Call):
            return
        kind = _ctor_kind(node.value)
        if kind is None:
            return
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                self.module_locks[tgt.id] = ("mod", tgt.id)
                if kind == "cond":
                    self.module_conds.add(tgt.id)

    def _scan_class(self, cls: ast.ClassDef) -> None:
        info = _ClassInfo(cls.name)
        self.classes[cls.name] = info
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{cls.name}.{node.name}"
                self.functions[qual] = node
                if node.name == "__init__":
                    self._scan_init(info, node)

    def _scan_init(self, info: _ClassInfo, init: ast.FunctionDef) -> None:
        for stmt in ast.walk(init):
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            value = stmt.value
            for tgt in targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                if isinstance(value, ast.Call):
                    kind = _ctor_kind(value)
                    if kind == "lock":
                        info.locks[attr] = ("self", attr)
                        continue
                    if kind == "cond":
                        info.conds.add(attr)
                        # Condition(self.X) aliases to X; bare
                        # Condition() backs its own lock.
                        backing = None
                        if value.args:
                            backing = _self_attr(value.args[0])
                        if backing is not None:
                            info.locks[attr] = ("self", backing)
                            info.locks.setdefault(
                                backing, ("self", backing))
                        else:
                            info.locks[attr] = ("self", attr)
                        continue
                guard = self.mod.guarded_comment(stmt.lineno)
                if guard is not None:
                    if guard in info.locks:
                        info.guarded[attr] = info.locks[guard]
                    elif guard in self.module_locks:
                        info.guarded[attr] = self.module_locks[guard]
                    else:
                        # Forward reference: the lock may be declared
                        # later in __init__; resolve best-effort to a
                        # self attr.
                        info.guarded[attr] = ("self", guard)
        # Second pass: guards that referenced a lock declared later.
        for attr, lock in list(info.guarded.items()):
            kind, name = lock
            if kind == "self" and name in info.locks:
                info.guarded[attr] = info.locks[name]

    # ------------------------------------------------------ resolution

    def resolve_lock_expr(self, expr: ast.AST,
                          cls: Optional[str]) -> Optional[LockId]:
        """LockId for a with-target / wait-receiver expression, if it
        names a registered lock or condition."""
        attr = _self_attr(expr)
        if attr is not None and cls is not None:
            info = self.classes.get(cls)
            if info is not None and attr in info.locks:
                return info.locks[attr]
            return None
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return self.module_locks[expr.id]
        return None

    def is_condition(self, expr: ast.AST, cls: Optional[str]) -> bool:
        attr = _self_attr(expr)
        if attr is not None and cls is not None:
            info = self.classes.get(cls)
            return info is not None and attr in info.conds
        if isinstance(expr, ast.Name):
            return expr.id in self.module_conds
        return False


class _FunctionWalker:
    """Pass 2: walk one function's statements tracking held locks."""

    def __init__(self, index: _ModuleIndex, mod: Module, qual: str,
                 fn: ast.FunctionDef, dispatcher: bool,
                 findings: List[Finding], emit_lock_rules: bool = True,
                 entry_note: str = "", related=None):
        self.index = index
        self.mod = mod
        self.qual = qual
        self.cls = qual.split(".")[0] if "." in qual else None
        self.method = qual.split(".")[-1]
        self.fn = fn
        self.dispatcher = dispatcher
        self.findings = findings
        self.emit_lock_rules = emit_lock_rules
        self.entry_note = entry_note
        self.related = related

    def run(self) -> None:
        self._stmts(self.fn.body, frozenset())

    # ------------------------------------------------------- traversal

    def _stmts(self, body: List[ast.stmt], held: frozenset) -> None:
        for stmt in body:
            self._stmt(stmt, held)

    def _stmt(self, stmt: ast.stmt, held: frozenset) -> None:
        if isinstance(stmt, ast.With):
            acquired = set()
            for item in stmt.items:
                self._expr(item.context_expr, held)
                lock = self.index.resolve_lock_expr(
                    item.context_expr, self.cls)
                if lock is not None:
                    acquired.add(lock)
            self._stmts(stmt.body, held | frozenset(acquired))
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested defs run later, on whatever thread calls them:
            # locks held HERE are not held THERE.
            self._stmts(stmt.body, frozenset())
        elif isinstance(stmt, ast.ClassDef):
            pass
        elif isinstance(stmt, (ast.If, ast.While)):
            self._expr(stmt.test, held)
            self._stmts(stmt.body, held)
            self._stmts(stmt.orelse, held)
        elif isinstance(stmt, ast.For):
            self._expr(stmt.iter, held)
            self._expr(stmt.target, held)
            self._stmts(stmt.body, held)
            self._stmts(stmt.orelse, held)
        elif isinstance(stmt, ast.Try):
            self._stmts(stmt.body, held)
            for h in stmt.handlers:
                self._stmts(h.body, held)
            self._stmts(stmt.orelse, held)
            self._stmts(stmt.finalbody, held)
        else:
            for child in ast.iter_child_nodes(stmt):
                self._expr(child, held)

    def _expr(self, node: ast.AST, held: frozenset) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._check_call(sub, held)
            elif isinstance(sub, ast.Attribute):
                self._check_guarded(sub, held)

    # ---------------------------------------------------------- checks

    def _check_guarded(self, node: ast.Attribute, held: frozenset) -> None:
        attr = _self_attr(node)
        if attr is None or self.cls is None:
            return
        info = self.index.classes.get(self.cls)
        if info is None or attr not in info.guarded:
            return
        if self.method == "__init__":
            return  # construction is single-threaded
        lock = info.guarded[attr]
        if lock in held:
            return
        self.findings.append(Finding(
            RULE_GUARDED, self.mod.rel, node.lineno, node.col_offset,
            f"attribute '{attr}' is guarded by "
            f"'{lock[1]}' but accessed without it",
            self.qual))

    def _check_call(self, call: ast.Call, held: frozenset) -> None:
        func = call.func
        name = None
        receiver = None
        if isinstance(func, ast.Attribute):
            name = func.attr
            receiver = func.value
        elif isinstance(func, ast.Name):
            name = func.id
        blocking = (
            (isinstance(func, ast.Attribute) and name in BLOCKING_ATTRS)
            or (isinstance(func, ast.Name) and name in BLOCKING_NAMES)
        )
        if not blocking:
            return

        # cond.wait on a condition whose own lock is held: releases the
        # lock while parked — never a convoy. Bounded (has a timeout
        # arg) additionally satisfies the dispatcher rule.
        own_cond_wait = False
        bounded = bool(call.args or call.keywords)
        if name == "wait" and receiver is not None:
            lock = self.index.resolve_lock_expr(receiver, self.cls)
            if (lock is not None
                    and self.index.is_condition(receiver, self.cls)):
                if lock in held:
                    own_cond_wait = True
                elif not held and self.method in ("wait", "wait_for"):
                    # Condition-wrapper delegation: a method literally
                    # named wait/wait_for parking on its OWN condition
                    # attribute IS the scheduling primitive
                    # (ProfiledCondition.wait) — its caller holds the
                    # backing lock by Condition contract, exactly like
                    # a direct cond.wait inside `with lock:`. Only
                    # with NOTHING else held: a wait method parking
                    # while holding a DIFFERENT lock is exactly the
                    # convoy the blocking rule exists to catch.
                    own_cond_wait = True

        if held and not own_cond_wait and self.emit_lock_rules:
            self.findings.append(Finding(
                RULE_LOCK_BLOCKING, self.mod.rel, call.lineno,
                call.col_offset,
                f"blocking call '{name}' inside a with-lock body "
                f"(holding {', '.join(sorted(l[1] for l in held))})",
                self.qual))
        if self.dispatcher and not (own_cond_wait and bounded):
            self.findings.append(Finding(
                RULE_DISPATCHER_BLOCKING, self.mod.rel, call.lineno,
                call.col_offset,
                f"blocking call '{name}' reachable from dispatcher "
                f"entrypoint (manifest NTA_DISPATCHER_ENTRYPOINTS"
                f"{self.entry_note}); move it to a stage thread",
                self.qual, related=self.related))


def check(mod: Module) -> List[Finding]:
    """Local lock-discipline rules (guarded-by, lock-blocking-call).
    The dispatcher rule moved to program_check: it is a reachability
    rule and reachability is whole-program now."""
    index = _ModuleIndex(mod)
    findings: List[Finding] = []
    for qual, fn in index.functions.items():
        _FunctionWalker(index, mod, qual, fn, dispatcher=False,
                        findings=findings).run()
    return findings


def program_check(program: Program) -> List[Finding]:
    """dispatcher-blocking-call over the whole-program call graph:
    every function reachable (cross-module) from any module's
    NTA_DISPATCHER_ENTRYPOINTS manifest is walked with the dispatcher
    rule armed. The finding lands where the blocking call lives — a
    helper in utils/ that sleeps is flagged in utils/, with the
    entry chain in the message and `related`."""
    entries = program.manifest_entries("NTA_DISPATCHER_ENTRYPOINTS")
    if not entries:
        return []
    via = program.reachable_with_paths(entries)
    findings: List[Finding] = []
    indexes: Dict[str, _ModuleIndex] = {}
    for key in sorted(via):
        rel, qual = key
        mod = program.by_rel.get(rel)
        if mod is None:
            continue
        index = indexes.get(rel)
        if index is None:
            index = indexes[rel] = _ModuleIndex(mod)
        fn = program.functions[key]
        note, related = program.witness_info(via, key)
        _FunctionWalker(index, mod, qual, fn, dispatcher=True,
                        findings=findings, emit_lock_rules=False,
                        entry_note=note, related=related).run()
    return findings
