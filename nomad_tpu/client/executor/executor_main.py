"""Standalone task executor process.

Run directly by path (import-safe: module level is only defs — the
client imports FileRotator from here; nothing executes outside the
__main__ guard):

    python executor_main.py <spec.json>

Responsibilities (reference client/driver/executor/executor.go):
  - launch the task command in its own session (process group)
  - capture stdout/stderr through size-based rotating log files
    (reference client/driver/logging/rotator.go)
  - apply resource limits in the child (reference executor_linux.go
    applies cgroup limits; here rlimits, cgroups when root)
  - serve a control RPC (wait/stats/signal/kill/shutdown) over a unix
    socket so the client agent can detach/reattach
    (reference executor_plugin.go)
  - persist a state file with the exit result so a reattaching client
    can recover the outcome even after this process exits

This file is intentionally stdlib-only and self-contained: it is
executed by path with a bare interpreter, so it must not import
nomad_tpu (and transitively jax).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import socketserver
import sys
import threading
import time

IDLE_EXIT_SECONDS = 300.0


class FileRotator:
    """Size-rotated log writer: <base>.0, <base>.1, ... keeping at most
    max_files, rotating at max_bytes (reference logging/rotator.go)."""

    def __init__(self, log_dir: str, base: str, max_files: int, max_bytes: int):
        self.log_dir = log_dir
        self.base = base
        self.max_files = max(1, max_files)
        self.max_bytes = max(1, max_bytes)
        self._lock = threading.Lock()
        self._idx = self._latest_index()
        self._fh = open(self._path(self._idx), "ab")
        self._written = self._fh.tell()

    def _path(self, idx: int) -> str:
        return os.path.join(self.log_dir, f"{self.base}.{idx}")

    def _latest_index(self) -> int:
        latest = 0
        prefix = self.base + "."
        try:
            names = os.listdir(self.log_dir)
        except OSError:
            return 0
        for name in names:
            if name.startswith(prefix):
                suffix = name[len(prefix):]
                if suffix.isdigit():
                    latest = max(latest, int(suffix))
        return latest

    def write(self, data: bytes) -> None:
        with self._lock:
            while data:
                room = self.max_bytes - self._written
                if room <= 0:
                    self._rotate_locked()
                    room = self.max_bytes
                chunk, data = data[:room], data[room:]
                self._fh.write(chunk)
                self._fh.flush()
                self._written += len(chunk)

    def _rotate_locked(self) -> None:
        self._fh.close()
        self._idx += 1
        self._fh = open(self._path(self._idx), "ab")
        self._written = 0
        # prune oldest beyond max_files
        oldest_keep = self._idx - self.max_files + 1
        prefix = self.base + "."
        for name in os.listdir(self.log_dir):
            if name.startswith(prefix) and name[len(prefix):].isdigit():
                if int(name[len(prefix):]) < oldest_keep:
                    try:
                        os.unlink(os.path.join(self.log_dir, name))
                    except OSError:
                        pass

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except OSError:
                pass


class Executor:
    def __init__(self, spec: dict):
        self.spec = spec
        self.state_path = spec["state_path"]
        self.sock_path = spec["sock_path"]
        self.done = threading.Event()
        self.result: dict = {}
        self.proc = None
        self.last_activity = time.monotonic()
        self._kill_lock = threading.Lock()
        self._rotators = []

    # -- state file ----------------------------------------------------

    def write_state(self, extra: dict | None = None) -> None:
        state = {
            "executor_pid": os.getpid(),
            "sock_path": self.sock_path,
            "task": self.spec.get("task_name", ""),
            "child_pid": self.proc.pid if self.proc else 0,
            "started_at": self.spec.get("_started_at", 0.0),
        }
        if extra:
            state.update(extra)
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self.state_path)

    # -- child lifecycle ----------------------------------------------

    def launch(self) -> None:
        import subprocess

        spec = self.spec
        argv = [spec["command"]] + [str(a) for a in spec.get("args", [])]
        env = dict(spec.get("env") or {})
        max_files = int(spec.get("max_files", 10))
        max_bytes = int(spec.get("max_file_size_mb", 10)) * 1024 * 1024
        task = spec.get("task_name", "task")
        out_rot = FileRotator(spec["log_dir"], f"{task}.stdout", max_files, max_bytes)
        err_rot = FileRotator(spec["log_dir"], f"{task}.stderr", max_files, max_bytes)
        self._rotators = [out_rot, err_rot]

        rlimit_as = spec.get("rlimit_as")
        chroot = spec.get("chroot") or None

        def preexec():
            if rlimit_as:
                import resource

                try:
                    resource.setrlimit(resource.RLIMIT_AS, (rlimit_as, rlimit_as))
                except (ValueError, OSError):
                    pass
            if chroot:
                try:
                    os.chroot(chroot)
                    os.chdir("/")
                except OSError:
                    pass

        self.spec["_started_at"] = time.time()
        self.proc = subprocess.Popen(
            argv,
            cwd=spec.get("cwd") or None,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            start_new_session=True,
            preexec_fn=preexec,
        )
        self._maybe_cgroup(self.proc.pid)
        threading.Thread(
            target=self._pump, args=(self.proc.stdout, out_rot), daemon=True
        ).start()
        threading.Thread(
            target=self._pump, args=(self.proc.stderr, err_rot), daemon=True
        ).start()
        threading.Thread(target=self._reap, daemon=True).start()
        self.write_state()

    def _maybe_cgroup(self, pid: int) -> None:
        """Best-effort cgroup-v2 memory/cpu limits when running as root
        (reference executor_linux.go:48 uses libcontainer cgroups)."""
        spec = self.spec
        if os.geteuid() != 0 or not os.path.isdir("/sys/fs/cgroup"):
            return
        mem_mb = spec.get("memory_mb") or 0
        cpu_shares = spec.get("cpu_shares") or 0
        if not mem_mb and not cpu_shares:
            return
        cg = f"/sys/fs/cgroup/nomad-tpu-{os.getpid()}"
        try:
            os.makedirs(cg, exist_ok=True)
            if mem_mb:
                with open(os.path.join(cg, "memory.max"), "w") as f:
                    f.write(str(int(mem_mb) * 1024 * 1024))
            if cpu_shares:
                with open(os.path.join(cg, "cpu.weight"), "w") as f:
                    # map MHz shares into cgroup2 weight range [1,10000]
                    f.write(str(max(1, min(10000, int(cpu_shares)))))
            with open(os.path.join(cg, "cgroup.procs"), "w") as f:
                f.write(str(pid))
            self.spec["_cgroup"] = cg
        except OSError:
            pass

    def _pump(self, pipe, rotator: FileRotator) -> None:
        try:
            # read1: return as soon as bytes are available — plain
            # read(n) would buffer a partially-filled chunk until EOF,
            # hiding live output from the log-tailing APIs.
            for chunk in iter(lambda: pipe.read1(65536), b""):
                rotator.write(chunk)
        except (OSError, ValueError):
            pass

    def _reap(self) -> None:
        code = self.proc.wait()
        if code < 0:
            self.result = {"exit_code": 0, "signal": -code, "error": ""}
        else:
            self.result = {"exit_code": code, "signal": 0, "error": ""}
        time.sleep(0.05)  # let pumps drain
        for r in self._rotators:
            r.close()
        cg = self.spec.get("_cgroup")
        if cg:
            try:
                os.rmdir(cg)
            except OSError:
                pass
        self.write_state({"result": self.result, "exited_at": time.time()})
        self.done.set()

    # -- RPC methods ---------------------------------------------------

    def rpc_ping(self, req: dict) -> dict:
        return {"ok": True, "child_pid": self.proc.pid}

    def rpc_wait(self, req: dict) -> dict:
        timeout = req.get("timeout")
        if self.done.wait(timeout):
            return {"done": True, "result": self.result}
        return {"done": False}

    def rpc_stats(self, req: dict) -> dict:
        """RSS + cpu ticks summed over the child's process group
        (reference executor.go pid-scan resource usage)."""
        rss = 0
        ticks = 0
        pids = []
        if self.proc and not self.done.is_set():
            pgid = self.proc.pid
            try:
                for entry in os.listdir("/proc"):
                    if not entry.isdigit():
                        continue
                    try:
                        with open(f"/proc/{entry}/stat") as f:
                            parts = f.read().rsplit(")", 1)[1].split()
                        if int(parts[2]) != pgid:  # field 5: pgrp
                            continue
                        pids.append(int(entry))
                        ticks += int(parts[11]) + int(parts[12])  # utime+stime
                        rss += int(parts[21]) * os.sysconf("SC_PAGE_SIZE")
                    except (OSError, IndexError, ValueError):
                        continue
            except OSError:
                pass
        return {"rss_bytes": rss, "cpu_ticks": ticks, "pids": pids}

    def rpc_signal(self, req: dict) -> dict:
        signum = int(req.get("signum", signal.SIGTERM))
        try:
            os.killpg(self.proc.pid, signum)
            return {"ok": True}
        except OSError as e:
            return {"ok": False, "error": str(e)}

    def rpc_kill(self, req: dict) -> dict:
        # The lock covers only the signal sends; the done-event waits
        # happen OUTSIDE it, so a second killer (or a status RPC taking
        # the lock) never convoys behind a full grace period. Both
        # escalation steps re-check done under the lock, and a
        # double-SIGKILL of a dead process group is a caught OSError.
        timeout = float(req.get("timeout", 5.0))
        with self._kill_lock:
            if not self.done.is_set():
                try:
                    os.killpg(self.proc.pid, signal.SIGINT)
                except OSError:
                    pass
        if not self.done.wait(timeout):
            with self._kill_lock:
                if not self.done.is_set():
                    try:
                        os.killpg(self.proc.pid, signal.SIGKILL)
                    except OSError:
                        try:
                            self.proc.kill()
                        except OSError:
                            pass
            self.done.wait(5.0)
        return {"done": self.done.is_set(), "result": self.result}

    def _exit_now(self) -> None:
        try:
            os.unlink(self.sock_path)  # sockets in tempdir must not leak
        except OSError:
            pass
        os._exit(0)

    def rpc_shutdown(self, req: dict) -> dict:
        if not self.done.is_set():
            self.rpc_kill({"timeout": req.get("timeout", 5.0)})

        def _exit():
            time.sleep(0.1)
            self._exit_now()

        threading.Thread(target=_exit, daemon=True).start()
        return {"ok": True}

    def dispatch(self, req: dict) -> dict:
        self.last_activity = time.monotonic()
        method = req.get("method", "")
        fn = getattr(self, f"rpc_{method}", None)
        if fn is None:
            return {"error": f"unknown method {method!r}"}
        try:
            return fn(req)
        except Exception as e:  # noqa: BLE001 - report RPC errors to caller
            return {"error": str(e)}


def serve(ex: Executor) -> None:
    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            while True:
                line = self.rfile.readline()
                if not line:
                    return
                try:
                    req = json.loads(line)
                except ValueError:
                    return
                resp = ex.dispatch(req)
                try:
                    self.wfile.write(json.dumps(resp).encode() + b"\n")
                except (BrokenPipeError, OSError):
                    return

    class Server(socketserver.ThreadingUnixStreamServer):
        daemon_threads = True

    if os.path.exists(ex.sock_path):
        os.unlink(ex.sock_path)
    srv = Server(ex.sock_path, Handler)

    def idle_watch():
        while True:
            time.sleep(10.0)
            if ex.done.is_set() and (
                time.monotonic() - ex.last_activity > IDLE_EXIT_SECONDS
            ):
                ex._exit_now()

    threading.Thread(target=idle_watch, daemon=True).start()
    srv.serve_forever(poll_interval=0.5)


def main() -> int:
    spec_path = sys.argv[1]
    with open(spec_path) as f:
        spec = json.load(f)
    # The spec holds the task environment (possibly credentials); it has
    # served its purpose once loaded.
    try:
        os.unlink(spec_path)
    except OSError:
        pass
    # Detach from the client's session so a client restart/kill never
    # propagates to the task (reference: go-plugin subprocess survives
    # because drivers reattach by pid).
    try:
        os.setsid()
    except OSError:
        pass
    signal.signal(signal.SIGINT, signal.SIG_IGN)
    signal.signal(signal.SIGTERM, signal.SIG_IGN)
    ex = Executor(spec)
    try:
        ex.launch()
    except Exception as e:  # noqa: BLE001 - startup failure goes to state file
        ex.result = {"exit_code": -1, "signal": 0, "error": str(e)}
        ex.done.set()
        try:
            ex.write_state({"result": ex.result, "exited_at": time.time()})
        except OSError:
            pass
        # Still serve the socket briefly so the launching driver reads
        # the failure instead of a connection error.
        threading.Thread(target=serve, args=(ex,), daemon=True).start()
        time.sleep(2.0)
        return 1
    serve(ex)
    return 0


if __name__ == "__main__":
    sys.exit(main())
