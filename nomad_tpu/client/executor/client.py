"""Client-side executor control: spawn, RPC handle, reattach.

Reference: client/driver/executor_plugin.go (ExecutorRPC wrapper) and
client/driver/plugins.go:31 (PluginReattachConfig persisted in the
driver handle id so a restarted client can reattach).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
from typing import Optional

from ...structs import Task
from ..drivers.base import DriverHandle, TaskContext, WaitResult

EXECUTOR_MAIN = os.path.join(os.path.dirname(__file__), "executor_main.py")
HANDLE_PREFIX = "executor:"


class ExecutorClient:
    """Newline-JSON RPC over the executor's unix socket. One socket
    connection per concurrent call site; calls on a connection are
    serialized."""

    def __init__(self, sock_path: str):
        self.sock_path = sock_path
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._file = None

    def _connect(self) -> None:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(self.sock_path)
        self._sock = s
        self._file = s.makefile("rwb")

    def call(self, method: str, *, _timeout: Optional[float] = None, **kw) -> dict:
        """One RPC round-trip; _timeout bounds the socket wait."""
        with self._lock:
            if self._sock is None:
                self._connect()
            self._sock.settimeout(_timeout)
            req = dict(kw)
            req["method"] = method
            try:
                self._file.write(json.dumps(req).encode() + b"\n")
                self._file.flush()
                line = self._file.readline()
            except (OSError, ValueError):
                self.close()
                raise
            if not line:
                self.close()
                raise ConnectionError("executor closed connection")
            return json.loads(line)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._file = None


class ExecutorHandle(DriverHandle):
    """DriverHandle backed by the out-of-process executor."""

    def __init__(self, task_name: str, sock_path: str, state_path: str,
                 executor_pid: int, child_pid: int):
        self.task_name = task_name
        self.sock_path = sock_path
        self.state_path = state_path
        self.executor_pid = executor_pid
        self.child_pid = child_pid
        # Persistent connection for the wait loop ONLY. Control calls
        # (kill/signal/stats) use their own connections: wait RPCs block
        # up to max_kill_timeout holding the connection lock, and a
        # kill() queued behind one would wait out the very timeout it is
        # supposed to cut short (the executor serves connections
        # concurrently — ThreadingUnixStreamServer).
        self._client = ExecutorClient(sock_path)
        self._result: Optional[WaitResult] = None

    def _oneshot(self, method: str, *, _timeout: Optional[float], **kw) -> dict:
        client = ExecutorClient(self.sock_path)
        try:
            return client.call(method, _timeout=_timeout, **kw)
        finally:
            client.close()

    # -- identity ------------------------------------------------------

    def id(self) -> str:
        return HANDLE_PREFIX + json.dumps(
            {
                "task": self.task_name,
                "sock": self.sock_path,
                "state": self.state_path,
                "executor_pid": self.executor_pid,
                "child_pid": self.child_pid,
            },
            sort_keys=True,
        )

    def pid(self) -> Optional[int]:
        return self.child_pid or None

    # -- state-file fallback -------------------------------------------

    def _result_from_state_file(self) -> Optional[WaitResult]:
        try:
            with open(self.state_path) as f:
                state = json.load(f)
        except (OSError, ValueError):
            return None
        res = state.get("result")
        if res is None:
            return None
        return WaitResult(
            exit_code=res.get("exit_code", -1),
            signal=res.get("signal", 0),
            error=res.get("error", ""),
        )

    def _executor_alive(self) -> bool:
        if not self.executor_pid:
            return False
        try:
            os.kill(self.executor_pid, 0)
            return True
        except OSError:
            return False

    # -- DriverHandle --------------------------------------------------

    def wait(self, timeout: Optional[float] = None) -> Optional[WaitResult]:
        if self._result is not None:
            return self._result
        try:
            resp = self._client.call(
                "wait", timeout=timeout,
                _timeout=(timeout + 5.0) if timeout is not None else None,
            )
            if resp.get("done"):
                r = resp["result"]
                self._result = WaitResult(
                    exit_code=r.get("exit_code", -1),
                    signal=r.get("signal", 0),
                    error=r.get("error", ""),
                )
                return self._result
            return None
        except (OSError, ValueError, ConnectionError):
            # Executor gone: recover from its state file, else report lost.
            res = self._result_from_state_file()
            if res is not None:
                self._result = res
                return res
            if not self._executor_alive():
                # The supervisor died without recording an exit. Its
                # child (own session) may still be running: reap it
                # before reporting the task dead, or a restart would run
                # a second copy alongside the orphan.
                if self.child_pid and self._pid_is_session_leader(self.child_pid):
                    try:
                        os.killpg(self.child_pid, signal.SIGKILL)
                    except OSError:
                        pass
                self._result = WaitResult(exit_code=-1, error="executor exited unexpectedly")
                return self._result
            return None

    @staticmethod
    def _pid_is_session_leader(pid: int) -> bool:
        """Guard against recycled pids: our executor and child are both
        session leaders (setsid), so a pid whose pgrp differs was reused
        by some unrelated process and must not be signalled."""
        try:
            with open(f"/proc/{pid}/stat") as f:
                parts = f.read().rsplit(")", 1)[1].split()
            return int(parts[2]) == pid  # field 5: pgrp
        except (OSError, IndexError, ValueError):
            return False

    def kill(self, kill_timeout: float = 5.0) -> None:
        try:
            self._oneshot("kill", timeout=kill_timeout,
                          _timeout=kill_timeout + 10.0)
            self._oneshot("shutdown", _timeout=5.0)
        except (OSError, ValueError, ConnectionError):
            # RPC unavailable. If the task's exit is already on record
            # there is nothing to kill — signalling the stored pids
            # would hit whatever process recycled them.
            if self._result is not None or self._result_from_state_file() is not None:
                return
            # SIGKILL for the executor too: it ignores SIGINT/SIGTERM by
            # design (it must survive client shutdown signals).
            for pid in (self.child_pid, self.executor_pid):
                if pid and self._pid_is_session_leader(pid):
                    try:
                        os.killpg(pid, signal.SIGKILL)
                    except OSError:
                        try:
                            os.kill(pid, signal.SIGKILL)
                        except OSError:
                            pass
        finally:
            self._client.close()
            # If the executor is gone its socket file lingers (it only
            # unlinks on clean exit): sweep it.
            if not self._executor_alive():
                try:
                    os.unlink(self.sock_path)
                except OSError:
                    pass

    def signal(self, signum: int) -> None:
        self._oneshot("signal", signum=signum, _timeout=10.0)

    def stats(self) -> dict:
        try:
            return self._oneshot("stats", _timeout=5.0)
        except (OSError, ValueError, ConnectionError):
            return {}


def _paths(ctx: TaskContext, task_name: str):
    # Unique per launch attempt: a restarted task must never find the
    # previous attempt's still-alive executor (or its recorded exit
    # result) at the same path. Reattach uses the exact paths stored in
    # the handle id, so uniqueness costs nothing.
    import uuid

    nonce = uuid.uuid4().hex[:8]
    base = os.path.join(
        ctx.task_dir, os.pardir, f".executor-{task_name}-{nonce}"
    )
    base = os.path.abspath(base)
    # AF_UNIX socket paths are capped at ~108 bytes; alloc dirs easily
    # exceed that, so the socket lives in the system tempdir (the
    # handle id records it anyway).
    import tempfile

    sock = os.path.join(tempfile.gettempdir(), f"nomad-exec-{nonce}.sock")
    return sock, base + ".state", base + ".spec"


def launch_executor(ctx: TaskContext, task: Task, *, rlimit_as: Optional[int] = None,
                    chroot: Optional[str] = None) -> ExecutorHandle:
    """Spawn the executor process for a task and wait for it to come up."""
    cfg = task.config or {}
    command = cfg.get("command")
    if not command:
        raise ValueError(f"missing command for task {task.name!r}")
    env = dict(os.environ)
    env.update(ctx.env)
    log_cfg = task.log_config
    sock_path, state_path, spec_path = _paths(ctx, task.name)
    spec = {
        "task_name": task.name,
        "command": command,
        "args": [str(a) for a in cfg.get("args", [])],
        "env": env,
        "cwd": ctx.task_root or ctx.task_dir,
        "log_dir": ctx.log_dir,
        "max_files": log_cfg.max_files if log_cfg else 10,
        "max_file_size_mb": log_cfg.max_file_size_mb if log_cfg else 10,
        "sock_path": sock_path,
        "state_path": state_path,
        "rlimit_as": rlimit_as,
        "chroot": chroot,
        "memory_mb": task.resources.memory_mb if task.resources else 0,
        "cpu_shares": task.resources.cpu if task.resources else 0,
    }
    # 0600 and deleted by the executor once loaded: the spec carries the
    # task environment (which may hold credentials).
    fd = os.open(spec_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        json.dump(spec, f)

    proc = subprocess.Popen(
        [sys.executable, EXECUTOR_MAIN, spec_path],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,
        close_fds=True,
    )
    # The executor daemonizes itself (setsid); wait for its socket
    # under jittered backoff (utils/backoff.py): fast first probes for
    # the common sub-100ms startup, widening toward 250ms so a burst of
    # concurrent launches doesn't poll-storm the filesystem. Generous
    # deadline: a burst of concurrent task starts forks many executors
    # from a large parent (the agent may hold a TPU runtime), and under
    # that load 15s was observed to miss on real hardware.
    from ...utils.backoff import Backoff

    bo = Backoff(base=0.01, factor=1.5, max_delay=0.25, deadline=60.0)
    first = True
    last_err: Optional[Exception] = None
    while first or bo.sleep():
        first = False
        if os.path.exists(sock_path):
            client = ExecutorClient(sock_path)
            try:
                resp = client.call("ping", _timeout=5.0)
                child_pid = resp.get("child_pid", 0)
                handle = ExecutorHandle(task.name, sock_path, state_path,
                                        proc.pid, child_pid)
                # Launch may have failed inside the executor: surface it.
                res = handle._result_from_state_file()
                if res is not None and res.error:
                    raise RuntimeError(f"executor launch failed: {res.error}")
                return handle
            except (OSError, ValueError, ConnectionError) as e:
                last_err = e
                client.close()
        if proc.poll() is not None:
            # Executor died before serving; check state file for reason.
            try:
                with open(state_path) as f:
                    res = json.load(f).get("result") or {}
                raise RuntimeError(
                    f"executor failed: {res.get('error') or 'exited'}"
                )
            except (OSError, ValueError):
                raise RuntimeError("executor exited before serving") from last_err
    # Reap the slow starter: without this a retry would race a second
    # copy of the task against the one this executor eventually starts.
    # The executor and its child each run setsid, so kill both groups.
    pids = [proc.pid]
    try:
        with open(state_path) as f:
            pids.append(json.load(f).get("child_pid", 0))
    except (OSError, ValueError):
        pass
    for pid in pids:
        if pid:
            try:
                os.killpg(pid, signal.SIGKILL)
            except OSError:
                try:
                    os.kill(pid, signal.SIGKILL)
                except OSError:
                    pass
    raise TimeoutError(f"executor for {task.name!r} did not start") from last_err


def reattach_executor(handle_id: str) -> Optional[ExecutorHandle]:
    """Rebuild a handle from a persisted id after client restart.

    Returns None when the task is unrecoverable (no executor and no
    state file) — reference task_runner.go:189 marks such tasks lost.
    """
    if not handle_id.startswith(HANDLE_PREFIX):
        return None
    try:
        blob = json.loads(handle_id[len(HANDLE_PREFIX):])
    except ValueError:
        return None
    handle = ExecutorHandle(
        blob.get("task", ""), blob.get("sock", ""), blob.get("state", ""),
        blob.get("executor_pid", 0), blob.get("child_pid", 0),
    )
    try:
        handle._client.call("ping", _timeout=5.0)
        return handle
    except (OSError, ValueError, ConnectionError):
        pass
    # Executor gone: a recorded exit result still makes a usable handle.
    if handle._result_from_state_file() is not None:
        return handle
    return None
