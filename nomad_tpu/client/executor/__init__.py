"""Out-of-process task executor.

Reference: client/driver/executor/ + executor_plugin.go — tasks run
under a separate `nomad executor` process spawned via go-plugin so the
client can restart without killing tasks; the driver handle persists a
reattach config (plugins.go:31 PluginReattachConfig).

Here the executor is a self-contained stdlib-only script
(executor_main.py) launched directly by path, serving newline-JSON RPC
over a unix domain socket. The handle id is a JSON reattach blob
(socket path + state file + pids); after a client restart the driver
re-opens the socket, or — if the executor already exited — recovers the
exit result from the executor's state file.
"""

from .client import (
    ExecutorHandle,
    launch_executor,
    reattach_executor,
)

__all__ = ["ExecutorHandle", "launch_executor", "reattach_executor"]
