"""Syslog collector: container logs land in the task's rotated files.

Reference: client/driver/logging/universal_collector.go:207 — docker
has no stdout/stderr pipes to the client, so the driver points the
container's syslog log-driver at a local collector, which parses the
RFC3164/5424-ish frames docker emits and writes them into the task's
`<task>.stdout.N` / `<task>.stderr.N` rotated logs by severity (the
reference maps severity the same way, syslog_parser.go).
"""

from __future__ import annotations

import re
import socketserver
import threading

from .executor.executor_main import FileRotator

# <PRI>rest — PRI = facility*8 + severity; severity <= 4 (err/warn and
# worse) routes to stderr, the rest to stdout.
_PRI_RE = re.compile(rb"^<(\d{1,3})>")
# docker's RFC3164 header is "MMM dd hh:mm:ss host tag[pid]: " — strip
# everything through the EARLIEST "tag[pid]: " (non-greedy, bounded so
# a message that merely contains "[n]: " deep inside stays intact)
_HEADER_RE = re.compile(rb"^.{0,200}?\[\d+\]:\s?")

STDERR_MAX_SEVERITY = 4


class SyslogCollector:
    """One TCP syslog listener per docker task."""

    def __init__(self, log_dir: str, task_name: str, max_files: int,
                 max_bytes: int, port: int = 0):
        self.stdout = FileRotator(log_dir, f"{task_name}.stdout",
                                  max_files, max_bytes)
        self.stderr = FileRotator(log_dir, f"{task_name}.stderr",
                                  max_files, max_bytes)
        collector = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                # docker's tcp syslog framing is newline-delimited
                for line in self.rfile:
                    if collector._stopped:
                        return
                    collector._ingest(line.rstrip(b"\r\n"))

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        try:
            self._server = Server(("127.0.0.1", port), Handler)
        except OSError:
            # Bind failed (fixed-port rebind race): release the rotator
            # fds opened above before surfacing the error.
            self.stdout.close()
            self.stderr.close()
            raise
        self.addr = "tcp://127.0.0.1:%d" % self._server.server_address[1]
        self._stopped = False
        self._stop_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"syslog-{task_name}")
        self._thread.start()

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def _ingest(self, line: bytes) -> None:
        severity = 6  # info
        m = _PRI_RE.match(line)
        if m:
            severity = int(m.group(1)) % 8
            line = line[m.end():]
        line = _HEADER_RE.sub(b"", line, count=1)
        out = (self.stderr if severity <= STDERR_MAX_SEVERITY
               else self.stdout)
        try:
            out.write(line + b"\n")
        except ValueError:
            pass  # stop() closed the rotator under a draining handler

    def stop(self) -> None:
        # Idempotent: both the container-exit waiter and kill() stop it.
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
        self._server.shutdown()
        self._server.server_close()
        try:
            self.stdout.close()
            self.stderr.close()
        except OSError:
            pass  # already closed / rotator fd gone: shutdown-only path
