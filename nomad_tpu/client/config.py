"""Client agent configuration.

Reference: client/config/config.go (drivers whitelist, reserved
resources, node class/meta, state/alloc dirs).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..structs import Resources


@dataclass
class ClientConfig:
    state_dir: str = ""  # persisted client state (restored on restart)
    alloc_dir: str = ""  # root of per-allocation directories
    servers: List[str] = field(default_factory=list)  # server HTTP addrs
    region: str = "global"
    datacenter: str = "dc1"
    node_name: str = ""
    node_class: str = ""
    meta: Dict[str, str] = field(default_factory=dict)
    options: Dict[str, str] = field(default_factory=dict)
    reserved: Optional[Resources] = None
    # Only fingerprint/enable these drivers if set ("driver.whitelist").
    driver_whitelist: List[str] = field(default_factory=list)
    max_kill_timeout: float = 30.0
    # How often client state is persisted (client.go:65).
    save_interval: float = 60.0
    # Dev mode: shorter intervals, temp dirs.
    dev_mode: bool = False
    # Consul agent address ("host:port") for service registration,
    # fingerprinting, and server discovery (client.go:1762); an
    # in-process api object can be injected instead for tests.
    consul_addr: str = ""
    consul_api: Optional[object] = None
    # Catalog service name nomad servers register under.
    consul_service: str = "nomad"
    # Override the fingerprinted network link speed in mbits
    # (client config network_speed).
    network_speed: int = 0
    # TLS client context for https:// server addresses (agent tls
    # block; presents the node cert and verifies the server chain).
    ssl_context: Optional[object] = None
    # This agent's advertised HTTP endpoint ("http://host:port"),
    # published on the node so peers can pull sticky-disk snapshots
    # from it (client.go:1481 migrates via the old node's HTTPAddr).
    http_addr: str = ""
    # Host path -> chroot-relative destination map embedded into exec
    # chroots (None = allocdir.CHROOT_ENV defaults). An OPERATOR
    # setting, like the reference's client-config chroot_env
    # (client/config/config.go ChrootEnv): job submitters must not
    # choose which host paths get hardlinked into their root — the
    # exec driver rejects chroot_env in task config.
    chroot_env: Optional[Dict[str, str]] = None
