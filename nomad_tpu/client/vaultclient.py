"""Client-side vault token manager.

Reference: client/vaultclient/vaultclient.go:717 — tokens are derived
*through the server* (Node.DeriveVaultToken, nomad/node_endpoint.go:940)
so clients never hold vault credentials of their own, and a renewal
heap keeps derived tokens alive at half-TTL cadence. Renewal failure is
reported to the task runner, which applies the task's vault
change_mode (restart/signal/noop).
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple


class VaultClient:
    """Derives tokens via the server API and renews them until stopped."""

    def __init__(self, api, node_id: str, secret_id: str = ""):
        self.api = api
        self.node_id = node_id
        self.secret_id = secret_id
        self.logger = logging.getLogger("nomad_tpu.client.vault")
        self._lock = threading.Lock()
        # (next_renew_monotonic, seq, token, lease_expiry, on_fail)
        self._heap: list = []
        self._seq = 0
        # Tombstones only for the token whose renewal is in flight
        # outside the lock; heap entries are removed directly.
        self._stopped_tokens: set = set()
        self._inflight: Optional[str] = None
        self._stop = threading.Event()
        self._wake = threading.Condition(self._lock)
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ derive

    def derive_token(
        self, alloc_id: str, tasks: List[str]
    ) -> Tuple[Dict[str, str], float]:
        """One server round-trip for all of an alloc's vault tasks.
        Returns ({task: token}, ttl_seconds)."""
        out, _ = self.api.put(
            f"/v1/node/{self.node_id}/derive-vault",
            {
                "secret_id": self.secret_id,
                "alloc_id": alloc_id,
                "tasks": tasks,
            },
        )
        return out["tasks"], float(out.get("ttl", 3600.0))

    # ----------------------------------------------------------- renewal

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._renew_loop, name="vault-renew", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._wake:
            self._wake.notify()

    def renew_token(
        self, token: str, ttl: float,
        on_fail: Optional[Callable[[str], None]] = None,
        renew_now: bool = False,
    ) -> None:
        """Schedule periodic renewal at half-TTL (vaultclient.go renewal
        heap). renew_now renews immediately — used for tokens recovered
        from disk whose true remaining lease is unknown; the first
        successful renewal reports the real TTL."""
        with self._wake:
            self._stopped_tokens.discard(token)
            self._seq += 1
            due = time.monotonic() if renew_now else time.monotonic() + ttl / 2.0
            heapq.heappush(
                self._heap,
                (due, self._seq, token,
                 time.monotonic() + ttl, on_fail or (lambda e: None)),
            )
            self._wake.notify()
        self.start()

    def stop_renew_token(self, token: str) -> None:
        with self._wake:
            before = len(self._heap)
            self._heap = [e for e in self._heap if e[2] != token]
            if len(self._heap) != before:
                heapq.heapify(self._heap)
            elif token == self._inflight:
                # The loop popped it and is renewing outside the lock: a
                # tombstone stops the re-push. Tokens with no heap entry
                # and no in-flight renewal need nothing — adding them
                # here would leak tombstones forever.
                self._stopped_tokens.add(token)

    RETRY_INTERVAL = 15.0

    def _renew_loop(self) -> None:
        while not self._stop.is_set():
            with self._wake:
                while not self._heap and not self._stop.is_set():
                    self._wake.wait(1.0)
                if self._stop.is_set():
                    return
                due, seq, token, expiry, on_fail = self._heap[0]
                now = time.monotonic()
                if due > now:
                    self._wake.wait(min(due - now, 1.0))
                    continue
                heapq.heappop(self._heap)
                self._inflight = token
            try:
                out, _ = self.api.put("/v1/vault/renew", {"token": token})
                ttl = float(out["ttl"])
            except Exception as e:  # noqa: BLE001 — report, don't die
                if time.monotonic() < expiry:
                    # Transient failure with lease time left: retry
                    # until the lease actually runs out (vaultclient.go
                    # renews with backoff; one blip must not restart a
                    # healthy task).
                    self.logger.warning(
                        "vault renewal failed, will retry: %s", e
                    )
                    with self._wake:
                        if not self._finish_inflight(token):
                            self._seq += 1
                            heapq.heappush(
                                self._heap,
                                (time.monotonic() + self.RETRY_INTERVAL,
                                 self._seq, token, expiry, on_fail),
                            )
                    continue
                self.logger.warning("vault token lease expired: %s", e)
                with self._wake:
                    stopped = self._finish_inflight(token)
                if not stopped:
                    try:
                        on_fail(str(e))
                    except Exception:  # noqa: BLE001
                        self.logger.exception("vault renewal failure handler")
                continue
            with self._wake:
                if not self._finish_inflight(token):
                    self._seq += 1
                    heapq.heappush(
                        self._heap,
                        (time.monotonic() + ttl / 2.0, self._seq, token,
                         time.monotonic() + ttl, on_fail),
                    )

    def _finish_inflight(self, token: str) -> bool:
        """Clear in-flight state; True if the token was stopped mid-renewal
        (caller must drop it instead of re-scheduling). Lock held."""
        self._inflight = None
        if token in self._stopped_tokens:
            self._stopped_tokens.discard(token)
            return True
        return False
