"""Node fingerprinting: fill attributes/resources from the host.

Reference: client/fingerprint/ (registry fingerprint.go:38-76; arch,
cpu + MHz, memory, storage, host, network, cgroup, consul, vault,
env_aws, env_gce). Reads /proc and os APIs — no third-party deps; the
cloud-metadata fingerprints take an injectable fetcher so tests run
without a metadata service.
"""

from __future__ import annotations

import json
import multiprocessing
import os
import platform
import shutil
import socket
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional

from ..structs import NetworkResource, Node, Resources


def fingerprint_arch(node: Node) -> bool:
    node.attributes["cpu.arch"] = platform.machine()
    node.attributes["arch"] = platform.machine()
    return True


def fingerprint_cpu(node: Node) -> bool:
    cores = multiprocessing.cpu_count()
    node.attributes["cpu.numcores"] = str(cores)
    mhz = 1000.0
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    mhz = float(line.split(":")[1])
                    break
    except (OSError, ValueError):
        pass
    node.attributes["cpu.frequency"] = str(int(mhz))
    total = int(cores * mhz)
    node.attributes["cpu.totalcompute"] = str(total)
    if node.resources.cpu == 0:
        node.resources.cpu = total
    return True


def fingerprint_memory(node: Node) -> bool:
    total_mb = 1024
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total_mb = int(line.split()[1]) // 1024
                    break
    except (OSError, ValueError, IndexError):
        pass
    node.attributes["memory.totalbytes"] = str(total_mb * 1024 * 1024)
    if node.resources.memory_mb == 0:
        node.resources.memory_mb = total_mb
    return True


def fingerprint_storage(node: Node) -> bool:
    path = node.attributes.get("unique.storage.volume", "/")
    try:
        usage = shutil.disk_usage(path)
        free_mb = usage.free // (1024 * 1024)
    except OSError:
        free_mb = 1024
    node.attributes["unique.storage.bytesfree"] = str(free_mb * 1024 * 1024)
    if node.resources.disk_mb == 0:
        node.resources.disk_mb = free_mb
    return True


def fingerprint_host(node: Node) -> bool:
    node.attributes["kernel.name"] = platform.system().lower()
    node.attributes["kernel.version"] = platform.release()
    node.attributes["os.name"] = platform.system().lower()
    node.attributes["os.version"] = platform.version()
    node.attributes["unique.hostname"] = socket.gethostname()
    if not node.name:
        node.name = socket.gethostname()
    return True


def fingerprint_network(node: Node) -> bool:
    ip = "127.0.0.1"
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
    except OSError:
        pass
    node.attributes["unique.network.ip-address"] = ip
    if not node.resources.networks:
        node.resources.networks = [
            NetworkResource(device="eth0", cidr=f"{ip}/32", ip=ip, mbits=1000)
        ]
    return True


def fingerprint_cgroup(node: Node) -> bool:
    """Detect a mounted cgroup hierarchy (cgroup_linux.go); drivers that
    need resource isolation gate on unique.cgroup.mountpoint."""
    if platform.system() != "Linux":
        return False
    mountpoint = ""
    try:
        with open("/proc/mounts") as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 3 and parts[2] in ("cgroup", "cgroup2"):
                    mountpoint = os.path.dirname(parts[1]) \
                        if parts[2] == "cgroup" else parts[1]
                    break
    except OSError:
        return False
    if not mountpoint:
        return False
    node.attributes["unique.cgroup.mountpoint"] = mountpoint
    return True


def fingerprint_vault(node: Node, vault_client=None) -> bool:
    """Advertise vault availability (fingerprint/vault.go): attributes
    come from the client's vault token source when configured."""
    if vault_client is None:
        return False
    node.attributes["vault.accessible"] = "true"
    version = getattr(vault_client, "version", "")
    if version:
        node.attributes["vault.version"] = version
    return True


def fingerprint_consul(node: Node, consul_api) -> bool:
    """Attributes from the local consul agent (fingerprint/consul.go):
    version, datacenter, server mode, unique node name."""
    try:
        info = consul_api.self_info()
    except Exception:  # noqa: BLE001 - consul down: not available
        # Stale consul attributes are cleared so constraints don't match
        # a dead agent (the reference clears on periodic re-run).
        for key in list(node.attributes):
            if key.startswith("consul.") or key == "unique.consul.name":
                del node.attributes[key]
        node.links.pop("consul", None)
        return False
    cfg = info.get("Config") or {}
    node.attributes["consul.version"] = str(cfg.get("Version", ""))
    node.attributes["consul.revision"] = str(cfg.get("Revision", ""))
    node.attributes["consul.server"] = str(bool(cfg.get("Server"))).lower()
    node.attributes["consul.datacenter"] = str(cfg.get("Datacenter", ""))
    node.attributes["unique.consul.name"] = str(cfg.get("NodeName", ""))
    node.links["consul"] = (f"{node.attributes['consul.datacenter']}."
                            f"{node.attributes['unique.consul.name']}")
    return True


MetadataFetcher = Callable[[str], Optional[str]]


def _http_fetcher(base: str, headers: Dict[str, str]) -> MetadataFetcher:
    def fetch(path: str) -> Optional[str]:
        req = urllib.request.Request(base + path, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=0.4) as resp:
                return resp.read().decode()
        except (urllib.error.URLError, OSError, ValueError):
            return None

    return fetch


AWS_METADATA = "http://169.254.169.254/latest/meta-data/"
GCE_METADATA = "http://169.254.169.254/computeMetadata/v1/instance/"

_AWS_KEYS = {
    "ami-id": "platform.aws.ami-id",
    "instance-id": "unique.platform.aws.instance-id",
    "instance-type": "platform.aws.instance-type",
    "local-hostname": "unique.platform.aws.local-hostname",
    "local-ipv4": "unique.platform.aws.local-ipv4",
    "placement/availability-zone": "platform.aws.placement.availability-zone",
}


def fingerprint_env_aws(node: Node,
                        fetch: Optional[MetadataFetcher] = None) -> bool:
    """EC2 metadata attributes (fingerprint/env_aws.go). Off unless the
    metadata service answers (or a fetcher is injected)."""
    if fetch is None:
        if not os.environ.get("NOMAD_TPU_FINGERPRINT_AWS"):
            return False  # don't probe link-local addrs by default
        fetch = _http_fetcher(AWS_METADATA, {})
    found = False
    for path, attr in _AWS_KEYS.items():
        val = fetch(path)
        if val:
            node.attributes[attr] = val.strip()
            found = True
    if not found:
        return False
    node.attributes["platform.aws"] = "true"
    ip = node.attributes.get("unique.platform.aws.local-ipv4", "")
    if ip and not node.resources.networks:
        node.resources.networks = [
            NetworkResource(device="eth0", cidr=f"{ip}/32", ip=ip, mbits=1000)
        ]
    return True


_GCE_KEYS = {
    "id": "unique.platform.gce.id",
    "hostname": "unique.platform.gce.hostname",
    "zone": "platform.gce.zone",
    "machine-type": "platform.gce.machine-type",
    "network-interfaces/0/ip": "unique.platform.gce.network.ip",
}


def fingerprint_env_gce(node: Node,
                        fetch: Optional[MetadataFetcher] = None) -> bool:
    """GCE metadata attributes (fingerprint/env_gce.go)."""
    if fetch is None:
        if not os.environ.get("NOMAD_TPU_FINGERPRINT_GCE"):
            return False
        fetch = _http_fetcher(GCE_METADATA, {"Metadata-Flavor": "Google"})
    found = False
    for path, attr in _GCE_KEYS.items():
        val = fetch(path)
        if val:
            # zone/machine-type come back as full resource paths
            node.attributes[attr] = val.strip().rsplit("/", 1)[-1]
            found = True
    if not found:
        return False
    node.attributes["platform.gce"] = "true"
    tags = fetch("tags")
    if tags:
        try:
            for tag in json.loads(tags):
                node.attributes[f"platform.gce.tag.{tag}"] = "true"
        except ValueError:
            pass
    return True


BUILTIN_FINGERPRINTS: List[Callable[[Node], bool]] = [
    fingerprint_arch,
    fingerprint_cpu,
    fingerprint_memory,
    fingerprint_storage,
    fingerprint_host,
    fingerprint_network,
    fingerprint_cgroup,
    fingerprint_env_aws,
    fingerprint_env_gce,
]


def fingerprint_node(node: Node) -> List[str]:
    """Run all fingerprints; returns the list that applied."""
    if node.resources is None:
        node.resources = Resources()
    applied = []
    for fp in BUILTIN_FINGERPRINTS:
        if fp(node):
            applied.append(fp.__name__.removeprefix("fingerprint_"))
    return applied
