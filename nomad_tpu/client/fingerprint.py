"""Node fingerprinting: fill attributes/resources from the host.

Reference: client/fingerprint/ (registry fingerprint.go:38-76; arch,
cpu + MHz, memory, storage, host, network). Reads /proc and os APIs —
no third-party deps.
"""

from __future__ import annotations

import multiprocessing
import os
import platform
import shutil
import socket
from typing import Callable, Dict, List

from ..structs import NetworkResource, Node, Resources


def fingerprint_arch(node: Node) -> bool:
    node.attributes["cpu.arch"] = platform.machine()
    node.attributes["arch"] = platform.machine()
    return True


def fingerprint_cpu(node: Node) -> bool:
    cores = multiprocessing.cpu_count()
    node.attributes["cpu.numcores"] = str(cores)
    mhz = 1000.0
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    mhz = float(line.split(":")[1])
                    break
    except (OSError, ValueError):
        pass
    node.attributes["cpu.frequency"] = str(int(mhz))
    total = int(cores * mhz)
    node.attributes["cpu.totalcompute"] = str(total)
    if node.resources.cpu == 0:
        node.resources.cpu = total
    return True


def fingerprint_memory(node: Node) -> bool:
    total_mb = 1024
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total_mb = int(line.split()[1]) // 1024
                    break
    except (OSError, ValueError, IndexError):
        pass
    node.attributes["memory.totalbytes"] = str(total_mb * 1024 * 1024)
    if node.resources.memory_mb == 0:
        node.resources.memory_mb = total_mb
    return True


def fingerprint_storage(node: Node) -> bool:
    path = node.attributes.get("unique.storage.volume", "/")
    try:
        usage = shutil.disk_usage(path)
        free_mb = usage.free // (1024 * 1024)
    except OSError:
        free_mb = 1024
    node.attributes["unique.storage.bytesfree"] = str(free_mb * 1024 * 1024)
    if node.resources.disk_mb == 0:
        node.resources.disk_mb = free_mb
    return True


def fingerprint_host(node: Node) -> bool:
    node.attributes["kernel.name"] = platform.system().lower()
    node.attributes["kernel.version"] = platform.release()
    node.attributes["os.name"] = platform.system().lower()
    node.attributes["os.version"] = platform.version()
    node.attributes["unique.hostname"] = socket.gethostname()
    if not node.name:
        node.name = socket.gethostname()
    return True


def fingerprint_network(node: Node) -> bool:
    ip = "127.0.0.1"
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
    except OSError:
        pass
    node.attributes["unique.network.ip-address"] = ip
    if not node.resources.networks:
        node.resources.networks = [
            NetworkResource(device="eth0", cidr=f"{ip}/32", ip=ip, mbits=1000)
        ]
    return True


BUILTIN_FINGERPRINTS: List[Callable[[Node], bool]] = [
    fingerprint_arch,
    fingerprint_cpu,
    fingerprint_memory,
    fingerprint_storage,
    fingerprint_host,
    fingerprint_network,
]


def fingerprint_node(node: Node) -> List[str]:
    """Run all fingerprints; returns the list that applied."""
    if node.resources is None:
        node.resources = Resources()
    applied = []
    for fp in BUILTIN_FINGERPRINTS:
        if fp(node):
            applied.append(fp.__name__.removeprefix("fingerprint_"))
    return applied
