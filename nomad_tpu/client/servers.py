"""Ranked server list with failure-driven rotation.

Reference: client/serverlist.go — the client keeps every known server
endpoint ranked by observed failures; RPCs go to the front, a failed
endpoint is demoted (failures++ then re-sort), and `set_servers`
installs a fresh (shuffled) set from config, heartbeat responses, or
consul discovery while preserving failure counts of endpoints it keeps.
"""

from __future__ import annotations

import random
import threading
from typing import List, Optional


class ServerList:
    def __init__(self, servers: Optional[List[str]] = None):
        self._lock = threading.Lock()
        self._failures = {}
        self._servers: List[str] = []
        if servers:
            self.set_servers(servers)

    def set_servers(self, servers: List[str]) -> None:
        with self._lock:
            fresh = list(dict.fromkeys(servers))  # dedupe, keep order
            random.shuffle(fresh)
            self._failures = {
                s: self._failures.get(s, 0) for s in fresh
            }
            self._servers = sorted(fresh, key=self._failures.__getitem__)

    def all(self) -> List[str]:
        with self._lock:
            return list(self._servers)

    def get(self) -> Optional[str]:
        """Best (least-failed) server, or None when empty."""
        with self._lock:
            return self._servers[0] if self._servers else None

    def notify_failure(self, server: str) -> None:
        """Demote a server after a failed RPC (serverlist.go
        failServer)."""
        with self._lock:
            if server not in self._failures:
                return
            self._failures[server] += 1
            self._servers.sort(key=self._failures.__getitem__)

    def notify_success(self, server: str) -> None:
        """A working endpoint resets its failure count so a past blip
        doesn't permanently demote it."""
        with self._lock:
            if server in self._failures:
                self._failures[server] = 0
                self._servers.sort(key=self._failures.__getitem__)

    def __len__(self) -> int:
        with self._lock:
            return len(self._servers)
