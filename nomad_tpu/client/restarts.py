"""Restart policy tracker.

Reference: client/restarts.go:221 — a budget of `attempts` restarts per
`interval`; on exhaustion mode 'fail' stops the task, mode 'delay'
waits out the remainder of the interval and resets the budget.
"""

from __future__ import annotations

import random
import time
from typing import Tuple

from ..structs import RestartPolicy, consts

# Decision outcomes
NO_RESTART = "no-restart"
RESTART = "restart"

JITTER_FRACTION = 0.25  # client/restarts.go jitter


class RestartTracker:
    def __init__(self, policy: RestartPolicy, job_type: str):
        self.policy = policy
        self.batch = job_type == consts.JOB_TYPE_BATCH
        self.count = 0
        self.start_time = time.time()

    def _jitter(self, base: float) -> float:
        return base + random.random() * JITTER_FRACTION * base

    def next_restart(self, exit_successful: bool) -> Tuple[str, float]:
        """Decide what happens after a task exit: (decision, wait)."""
        # Service tasks always restart on success-exit too (they should
        # never exit); batch tasks that succeed are done.
        if self.batch and exit_successful:
            return NO_RESTART, 0.0

        now = time.time()
        if self.policy.interval and now - self.start_time > self.policy.interval:
            self.count = 0
            self.start_time = now

        self.count += 1
        # attempts=0 means never restart (restarts.go: count > Attempts
        # exhausts the budget).
        if self.count <= self.policy.attempts:
            return RESTART, self._jitter(self.policy.delay)

        if self.policy.mode == consts.RESTART_POLICY_MODE_FAIL:
            return NO_RESTART, 0.0
        # delay mode: wait out the interval, then start a fresh budget.
        remaining = max(
            (self.start_time + self.policy.interval) - now, self.policy.delay
        )
        self.count = 0
        self.start_time = now + remaining
        return RESTART, self._jitter(remaining)
