"""qemu driver: run a VM image under the out-of-process executor.

Reference: client/driver/qemu.go:418 — fingerprint shells
`qemu-system-x86_64 --version` (qemu.go:77-100); Start builds the qemu
command line with -m (memory MB), -smp, the image path, optional KVM
accelerator, and user-net port forwards from port_map (qemu.go:120-230),
then runs it under the executor. Config keys: image_path, accelerator,
graceful_shutdown (ignored pre-0.5), port_map, args.
"""

from __future__ import annotations

import os
import re
import shutil
import subprocess
from dataclasses import replace
from typing import Optional

from ...structs import Node, Task
from .fields import Field, FieldSchema
from .base import Driver, DriverHandle, TaskContext, register_driver

QEMU_BIN = "qemu-system-x86_64"


def _qemu_version(qemu: str) -> Optional[str]:
    try:
        proc = subprocess.run(
            [qemu, "--version"], capture_output=True, text=True, timeout=10.0
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    m = re.search(r"version ([\d.]+)", proc.stdout)
    return m.group(1) if m else "unknown"


@register_driver
class QemuDriver(Driver):
    name = "qemu"

    def fingerprint(self, node: Node) -> bool:
        qemu = shutil.which(QEMU_BIN)
        version = _qemu_version(qemu) if qemu else None
        if version is None:
            node.attributes.pop("driver.qemu", None)
            return False
        node.attributes["driver.qemu"] = "1"
        node.attributes["driver.qemu.version"] = version
        return True

    config_schema = FieldSchema({
        "image_path": Field("string", required=True),
        "accelerator": Field("string"),
        "graceful_shutdown": Field("bool"),
        "port_map": Field("map"),
        "args": Field("list"),
    })


    def start(self, ctx: TaskContext, task: Task) -> DriverHandle:
        from ..executor import launch_executor

        qemu = shutil.which(QEMU_BIN)
        if not qemu:
            raise RuntimeError(f"{QEMU_BIN} not found")
        cfg = task.config or {}
        image = cfg.get("image_path")
        if not image:
            raise ValueError(f"qemu task {task.name!r} missing 'image_path'")
        if not os.path.isabs(image):
            image = os.path.join(ctx.task_root or ctx.task_dir, image)

        mem_mb = (task.resources.memory_mb if task.resources else 0) or 512
        argv = ["-machine", "type=pc,accel=" + (cfg.get("accelerator") or "tcg"),
                "-name", task.name,
                "-m", f"{mem_mb}M",
                "-drive", f"file={image}",
                "-nographic"]
        # User-net port forwards (qemu.go:193-213): port_map entries are
        # {network label: guest port}; the HOST side comes from the
        # task's ALLOCATED port carrying that label, tcp and udp both —
        # e.g. hostfwd=tcp::22000-:22 for a dynamic "ssh" port mapped
        # to guest 22.
        allocated = {}
        for net in ctx.networks:
            for p in list(net.reserved_ports) + list(net.dynamic_ports):
                allocated[p.label] = p.value
        forwards = []
        for label, guest in (cfg.get("port_map") or {}).items():
            host = allocated.get(str(label))
            if host is None:
                raise ValueError(
                    f"qemu port_map references unknown port label "
                    f"{label!r} (allocated: {sorted(allocated)})")
            for proto in ("tcp", "udp"):
                forwards.append(f"hostfwd={proto}::{host}-:{int(guest)}")
        if forwards:
            argv += ["-netdev", "user,id=user.0," + ",".join(forwards),
                     "-device", "virtio-net,netdev=user.0"]
        argv += [str(a) for a in cfg.get("args", [])]

        exec_task = replace(task, config={"command": qemu, "args": argv})
        return launch_executor(ctx, exec_task)

    def open(self, ctx: TaskContext, handle_id: str) -> Optional[DriverHandle]:
        from ..executor import reattach_executor

        return reattach_executor(handle_id)
