"""Mock driver: configurable fake for tests.

Reference: client/driver/mock_driver.go:215 — config keys run_for /
exit_code / start_error let tests script task behavior.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ...structs import Node, Task
from ...utils.ids import generate_uuid
from .fields import Field, FieldSchema
from .base import Driver, DriverHandle, TaskContext, WaitResult, register_driver


class MockHandle(DriverHandle):
    def __init__(self, handle_id: str, run_for: float, exit_code: int):
        self._id = handle_id
        self.exit_code = exit_code
        self._done = threading.Event()
        self._result: Optional[WaitResult] = None
        self._timer = threading.Timer(run_for, self._finish)
        self._timer.daemon = True
        self._timer.start()

    def _finish(self) -> None:
        self._result = WaitResult(exit_code=self.exit_code)
        self._done.set()

    def id(self) -> str:
        return self._id

    def wait(self, timeout: Optional[float] = None) -> Optional[WaitResult]:
        if not self._done.wait(timeout):
            return None
        return self._result

    def kill(self, kill_timeout: float = 5.0) -> None:
        self._timer.cancel()
        self._result = WaitResult(exit_code=0, signal=9)
        self._done.set()


_live_handles = {}


@register_driver
class MockDriver(Driver):
    name = "mock_driver"

    config_schema = FieldSchema({
        "run_for": Field("float"),
        "exit_code": Field("int"),
        "start_error": Field("string"),
    })


    def fingerprint(self, node: Node) -> bool:
        node.attributes["driver.mock_driver"] = "1"
        return True

    def start(self, ctx: TaskContext, task: Task) -> DriverHandle:
        cfg = task.config or {}
        if cfg.get("start_error"):
            raise RuntimeError(str(cfg["start_error"]))
        handle = MockHandle(
            generate_uuid(),
            float(cfg.get("run_for", 1e9)),
            int(cfg.get("exit_code", 0)),
        )
        _live_handles[handle.id()] = handle
        return handle

    def open(self, ctx: TaskContext, handle_id: str) -> Optional[DriverHandle]:
        return _live_handles.get(handle_id)
