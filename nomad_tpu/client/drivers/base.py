"""Driver interfaces and registry.

Reference: client/driver/driver.go:49 (Driver), :103 (DriverHandle).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Type

from ...structs import Node, Task


@dataclass
class TaskContext:
    alloc_id: str = ""
    alloc_dir: str = ""  # alloc shared dir
    task_dir: str = ""  # this task's local/ dir (NOMAD_TASK_DIR)
    task_root: str = ""  # this task's root dir (contains local/, secrets/);
    # the task working dir, and what artifact/template relative paths
    # resolve against (reference: alloc_dir.go task dir layout)
    log_dir: str = ""
    env: Dict[str, str] = field(default_factory=dict)
    # The ALLOCATED networks for this task (alloc.task_resources, not
    # the ask): drivers publish these ports (docker.go:521-577).
    networks: list = field(default_factory=list)
    max_kill_timeout: float = 30.0
    # task log rotation budget (structs LogConfig), so drivers that
    # rebuild log plumbing on reattach honor the configured limits
    log_max_files: int = 10
    log_max_file_size_mb: int = 10
    # Agent-config chroot embed map (ClientConfig.chroot_env; None =
    # allocdir.CHROOT_ENV defaults). Operator-owned — never sourced
    # from task config.
    chroot_env: Optional[Dict[str, str]] = None
    # Callback that embeds the chroot toolchain into this task's dir
    # AND records the embedded subtrees in agent-owned AllocDir state
    # (the disk watcher's prune list). Wired by TaskRunner; a bare
    # context (tests) leaves it None and drivers fall back to the
    # module-level embed without accounting.
    embed_chroot: Optional[object] = None


@dataclass
class WaitResult:
    exit_code: int = 0
    signal: int = 0
    error: str = ""

    def successful(self) -> bool:
        return self.exit_code == 0 and self.signal == 0 and not self.error


class DriverHandle:
    """A running task instance."""

    def id(self) -> str:
        """Opaque handle id persisted for reattach after client restart
        (task_runner.go:189)."""
        raise NotImplementedError

    def pid(self) -> Optional[int]:
        """OS pid for resource-usage sampling; None for virtual tasks."""
        return None

    def wait(self, timeout: Optional[float] = None) -> Optional[WaitResult]:
        """Block for task exit; None on timeout."""
        raise NotImplementedError

    def kill(self, kill_timeout: float = 5.0) -> None:
        raise NotImplementedError

    def signal(self, signum: int) -> None:
        """Deliver a signal to the task (template change_mode=signal)."""
        raise NotImplementedError

    def update(self, task: Task) -> None:
        pass


class Driver:
    name = ""

    def fingerprint(self, node: Node) -> bool:
        """Advertise availability via `driver.<name>` attributes."""
        raise NotImplementedError

    def start(self, ctx: TaskContext, task: Task) -> DriverHandle:
        raise NotImplementedError

    def open(self, ctx: TaskContext, handle_id: str) -> Optional[DriverHandle]:
        """Reattach to a live task after client restart; None if gone."""
        return None

    #: Declared config schema (helper/fields analog); None disables the
    #: generic check. Subclasses may extend validate_config with
    #: driver-specific rules on top.
    config_schema = None

    def validate_config(self, task: Task) -> None:
        if self.config_schema is not None:
            errors = self.config_schema.validate(
                task.config, where=f"{self.name} config")
            if errors:
                raise ValueError("; ".join(errors))


DRIVER_REGISTRY: Dict[str, Type[Driver]] = {}


def register_driver(cls: Type[Driver]) -> Type[Driver]:
    DRIVER_REGISTRY[cls.name] = cls
    return cls


def new_driver(name: str) -> Driver:
    cls = DRIVER_REGISTRY.get(name)
    if cls is None:
        raise ValueError(f"unknown driver {name!r}")
    return cls()
