"""Task drivers.

Reference: client/driver/ — Driver/DriverHandle interfaces
(driver.go:49,103), fingerprint-based availability advertised as
`driver.<name>` node attributes.
"""

from .base import Driver, DriverHandle, TaskContext, DRIVER_REGISTRY, new_driver
from .mock import MockDriver
from .raw_exec import RawExecDriver
from .exec_driver import ExecDriver
from .docker import DockerDriver
from .java import JavaDriver
from .qemu import QemuDriver
from .rkt import RktDriver

__all__ = [
    "Driver",
    "DriverHandle",
    "TaskContext",
    "DRIVER_REGISTRY",
    "new_driver",
    "MockDriver",
    "RawExecDriver",
    "ExecDriver",
    "DockerDriver",
    "JavaDriver",
    "QemuDriver",
    "RktDriver",
]
