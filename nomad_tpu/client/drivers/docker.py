"""docker driver: run tasks as containers.

Reference: client/driver/docker.go (1156 LoC) — fingerprint probes the
docker endpoint and advertises `driver.docker` + `driver.docker.version`
(docker.go:324-360); Start pulls the image if missing, creates a
container with cpu shares / memory limits, binds the alloc and task
dirs, maps ports, then starts it; the handle survives client restarts
by container id (docker.go Open). Kill = stop with a grace period.

TPU-native stance: the container runtime stays an external supervisor
(like the reference's dockerd); we drive it through the `docker` CLI so
the driver is a thin, restart-safe shim. The binary is resolved at
fingerprint time and the driver is absent when docker is not installed
or not responding, exactly like the reference's endpoint probe.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import threading
import time
from typing import List, Optional

from ...structs import Node, Task
from .fields import Field, FieldSchema
from .base import Driver, DriverHandle, TaskContext, WaitResult, register_driver


def _docker_bin() -> Optional[str]:
    return shutil.which(os.environ.get("NOMAD_DOCKER_BIN", "docker"))


def _run(args: List[str], timeout: float = 60.0) -> subprocess.CompletedProcess:
    return subprocess.run(
        args, capture_output=True, text=True, timeout=timeout
    )


class DockerHandle(DriverHandle):
    """Handle keyed by container id — reattachable across restarts."""

    def __init__(self, docker: str, container_id: str, task_name: str,
                 syslog=None, syslog_port: int = 0):
        self.docker = docker
        self.container_id = container_id
        self.task_name = task_name
        self.syslog = syslog  # log collector; dies with this client
        # Persisted even when a rebind failed, so a LATER restart can
        # still recover log collection on the port the container uses.
        self.syslog_port = syslog.port if syslog is not None else syslog_port
        self._result: Optional[WaitResult] = None
        self._done = threading.Event()
        self._waiter = threading.Thread(target=self._wait_container, daemon=True)
        self._waiter.start()

    def _wait_container(self) -> None:
        # `docker wait` blocks until the container exits and prints the
        # exit code — the same long-poll the reference does over the API.
        try:
            proc = subprocess.run(
                [self.docker, "wait", self.container_id],
                capture_output=True, text=True,
            )
            if proc.returncode == 0:
                self._result = WaitResult(exit_code=int(proc.stdout.strip()))
            else:
                self._result = WaitResult(
                    exit_code=-1, error=proc.stderr.strip() or "docker wait failed"
                )
        except (OSError, ValueError) as e:
            self._result = WaitResult(exit_code=-1, error=str(e))
        # Reap the exited container: every (re)start creates a uniquely
        # named one, so without this a crash-looping task leaks a dead
        # container per restart.
        try:
            _run([self.docker, "rm", self.container_id], timeout=30.0)
        except (OSError, subprocess.TimeoutExpired):
            pass
        # The container is gone: release its log collector (a normally
        # exiting task never goes through kill()).
        if self.syslog is not None:
            self.syslog.stop()
        self._done.set()

    def id(self) -> str:
        # The collector's port rides in the id so a restarted client
        # can rebind it (the container keeps logging to that port).
        return (f"docker:{self.container_id}:{self.syslog_port}:"
                f"{self.task_name}")

    def pid(self) -> Optional[int]:
        try:
            proc = _run([self.docker, "inspect", "-f", "{{.State.Pid}}",
                         self.container_id], timeout=10.0)
            if proc.returncode == 0:
                pid = int(proc.stdout.strip())
                return pid or None
        except (OSError, ValueError, subprocess.TimeoutExpired):
            pass
        return None

    def wait(self, timeout: Optional[float] = None) -> Optional[WaitResult]:
        if not self._done.wait(timeout):
            return None
        return self._result

    def signal(self, signum: int) -> None:
        try:
            _run([self.docker, "kill", "--signal", str(signum),
                  self.container_id], timeout=10.0)
        except (OSError, subprocess.TimeoutExpired):
            pass

    def kill(self, kill_timeout: float = 5.0) -> None:
        # docker stop = SIGTERM, grace period, then SIGKILL — the same
        # ladder the reference configures (docker.go Kill).
        try:
            _run([self.docker, "stop", "-t", str(int(max(1, kill_timeout))),
                  self.container_id], timeout=kill_timeout + 30.0)
        except (OSError, subprocess.TimeoutExpired):
            pass
        self._done.wait(5.0)
        if self.syslog is not None:
            self.syslog.stop()
        try:
            _run([self.docker, "rm", "-f", self.container_id], timeout=30.0)
        except (OSError, subprocess.TimeoutExpired):
            pass


@register_driver
class DockerDriver(Driver):
    name = "docker"

    def fingerprint(self, node: Node) -> bool:
        docker = _docker_bin()
        if not docker:
            node.attributes.pop("driver.docker", None)
            return False
        try:
            proc = _run([docker, "version", "--format", "{{.Server.Version}}"],
                        timeout=10.0)
        except (OSError, subprocess.TimeoutExpired):
            proc = None
        if proc is None or proc.returncode != 0:
            node.attributes.pop("driver.docker", None)
            return False
        node.attributes["driver.docker"] = "1"
        node.attributes["driver.docker.version"] = proc.stdout.strip()
        return True

    config_schema = FieldSchema({
        "image": Field("string", required=True),
        "command": Field("string"),
        "args": Field("list"),
        # Image archives (relative to the task dir) loaded instead of
        # pulled (docker.go:97 LoadImages).
        "load": Field("list"),
        # [{label: container_port}, ...] — allocated host ports publish
        # to these container ports (docker.go:104 PortMapRaw).
        "port_map": Field("list"),
        "network_mode": Field("string"),
        "ipc_mode": Field("string"),
        "pid_mode": Field("string"),
        "uts_mode": Field("string"),
        "dns_servers": Field("list"),
        "dns_search_domains": Field("list"),
        "hostname": Field("string"),
        "labels": Field("list"),  # [{k: v}, ...] (docker.go LabelsRaw)
        # [{username, password, email, server_address}] for private
        # registries (docker.go:112 Auth).
        "auth": Field("list"),
        "ssl": Field("bool"),
        "work_dir": Field("string"),
        "privileged": Field("bool"),
    })

    @staticmethod
    def _parse_repo_tag(image: str):
        """repo, tag — the tag is after the last ':' only if that comes
        after the last '/' (registry.example:5000/img has no tag)."""
        slash = image.rfind("/")
        colon = image.rfind(":")
        if colon > slash:
            return image[:colon], image[colon + 1:]
        return image, "latest"

    def _ensure_image(self, docker: str, cfg: dict, ctx: TaskContext,
                      image: str) -> None:
        """Pull policy (docker.go:636 createImage): a non-latest tag
        already present locally is reused; 'latest' always re-pulls so
        a moved tag is seen; `load` archives short-circuit the
        registry entirely. Registry auth rides an ephemeral
        DOCKER_CONFIG (the CLI analog of AuthConfiguration) so
        credentials never touch the operator's ~/.docker."""
        _repo, tag = self._parse_repo_tag(image)
        if tag != "latest":
            probe = _run([docker, "image", "inspect", image], timeout=30.0)
            if probe.returncode == 0:
                return
        loads = cfg.get("load") or []
        if loads:
            # Resolve against the task ROOT: that's where fetch_artifact
            # delivers downloads, so `artifact { ... } + load = [...]`
            # composes (resolving against local/ broke that pairing).
            base = ctx.task_root or ctx.task_dir or "."
            for archive in loads:
                path = os.path.join(base, str(archive))
                proc = _run([docker, "load", "-i", path], timeout=300.0)
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"docker load {path!r} failed: "
                        f"{proc.stderr.strip()}")
            return
        env = None
        tmp = None
        auths = cfg.get("auth") or []
        if auths:
            import base64
            import tempfile

            a = dict(auths[0])
            registry = a.get("server_address")
            if not registry:
                # Only a first path segment with a '.' or ':' (or
                # "localhost") is a registry HOST; "myorg/app" is a
                # Docker Hub org and its credentials key is the Hub
                # index URL — keying on "myorg" would never match and
                # the pull would silently go anonymous.
                first = image.split("/", 1)[0]
                if "/" in image and ("." in first or ":" in first
                                    or first == "localhost"):
                    registry = first
                    if cfg.get("ssl"):
                        registry = "https://" + registry
                else:
                    registry = "https://index.docker.io/v1/"
            token = base64.b64encode(
                f"{a.get('username', '')}:{a.get('password', '')}"
                .encode()).decode()
            entry = {"auth": token}
            if a.get("email"):
                entry["email"] = a["email"]
            tmp = tempfile.mkdtemp(prefix="nomad-docker-auth-")
            with open(os.path.join(tmp, "config.json"), "w") as f:
                json.dump({"auths": {registry: entry}}, f)
            os.chmod(os.path.join(tmp, "config.json"), 0o600)
            env = {**os.environ, "DOCKER_CONFIG": tmp}
        try:
            proc = subprocess.run(
                [docker, "pull", image], capture_output=True, text=True,
                timeout=600.0, env=env)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"docker pull {image!r} failed: {proc.stderr.strip()}")
        finally:
            if tmp is not None:
                shutil.rmtree(tmp, ignore_errors=True)

    def start(self, ctx: TaskContext, task: Task) -> DriverHandle:
        docker = _docker_bin()
        if not docker:
            raise RuntimeError("docker binary not found")
        cfg = task.config or {}
        image = cfg.get("image")
        if not image:
            raise ValueError(f"docker task {task.name!r} missing 'image'")
        self._ensure_image(docker, cfg, ctx, image)

        args = [docker, "run", "-d",
                "--name", f"nomad-{ctx.alloc_id[:8]}-{task.name}-{int(time.time())}"]
        # Container logs route through a local syslog collector into the
        # task's rotated log files (logging/universal_collector.go:207 —
        # docker gives the client no stdout/stderr pipes).
        syslog = None
        if ctx.log_dir:
            from ..syslog import SyslogCollector

            syslog = SyslogCollector(
                ctx.log_dir, task.name,
                max_files=ctx.log_max_files,
                max_bytes=ctx.log_max_file_size_mb * 1024 * 1024,
            )
            args += ["--log-driver", "syslog",
                     "--log-opt", f"syslog-address={syslog.addr}",
                     "--log-opt", f"tag={task.name}"]
        # Resource limits (docker.go createContainer): MHz→shares, MB→bytes.
        if task.resources is not None:
            if task.resources.cpu:
                args += ["--cpu-shares", str(task.resources.cpu)]
            if task.resources.memory_mb:
                args += ["--memory", f"{task.resources.memory_mb}m"]
        # Bind the shared alloc dir and task local dir at the same
        # in-container paths the reference uses (docker.go:27-33).
        if ctx.alloc_dir:
            args += ["-v", f"{os.path.abspath(ctx.alloc_dir)}:/alloc"]
        if ctx.task_dir:
            args += ["-v", f"{os.path.abspath(ctx.task_dir)}:/local"]
        if ctx.task_root:
            # secrets/ carries vault_token and rendered credentials; the
            # reference binds it alongside alloc and local (docker.go:27-33).
            secrets = os.path.join(os.path.abspath(ctx.task_root), "secrets")
            os.makedirs(secrets, exist_ok=True)
            args += ["-v", f"{secrets}:/secrets"]
        # Port publishing (docker.go:519-577): every allocated port of
        # the first network publishes host ip:port -> container port,
        # tcp AND udp; port_map relabels the container side, default
        # 1:1. The task env advertises the CONTAINER port for mapped
        # labels (taskEnv.SetPortMap) — that's the port the in-container
        # process must bind.
        port_map = {}
        for entry in cfg.get("port_map") or []:
            if not isinstance(entry, dict):
                # The old string form ("8080:80") must fail loudly: a
                # silently-dropped mapping ships a container with no
                # published ports.
                raise ValueError(
                    f"port_map entries must be label->port maps, got "
                    f"{entry!r}")
            port_map.update({str(k): int(v) for k, v in entry.items()})
        env = dict(ctx.env)
        if port_map and not ctx.networks:
            raise RuntimeError(
                "trying to map ports but no network interface is "
                "available")
        if ctx.networks:
            net = ctx.networks[0]
            ip = getattr(net, "ip", "") or ""
            prefix = f"{ip}:" if ip else ""
            for port in (list(net.reserved_ports)
                         + list(net.dynamic_ports)):
                container = port_map.get(port.label, port.value)
                args += ["-p", f"{prefix}{port.value}:{container}/tcp",
                         "-p", f"{prefix}{port.value}:{container}/udp"]
                if port.label in port_map:
                    label = port.label.upper().replace("-", "_")
                    env[f"NOMAD_PORT_{label}"] = str(container)
        for key, val in env.items():
            args += ["-e", f"{key}={val}"]
        if cfg.get("network_mode"):
            args += ["--network", str(cfg["network_mode"])]
        for mode_flag, key in (("--ipc", "ipc_mode"), ("--pid", "pid_mode"),
                               ("--uts", "uts_mode")):
            if cfg.get(key):
                args += [mode_flag, str(cfg[key])]
        for ip_addr in cfg.get("dns_servers") or []:
            args += ["--dns", str(ip_addr)]
        for domain in cfg.get("dns_search_domains") or []:
            args += ["--dns-search", str(domain)]
        if cfg.get("hostname"):
            args += ["--hostname", str(cfg["hostname"])]
        for entry in cfg.get("labels") or []:
            if isinstance(entry, dict):
                for k, v in entry.items():
                    args += ["--label", f"{k}={v}"]
        if cfg.get("work_dir"):
            args += ["-w", str(cfg["work_dir"])]
        if cfg.get("privileged"):
            args += ["--privileged"]
        args.append(image)
        if cfg.get("command"):
            args.append(str(cfg["command"]))
        args += [str(a) for a in cfg.get("args", [])]

        try:
            proc = _run(args, timeout=300.0)
        except BaseException:
            if syslog is not None:
                syslog.stop()
            raise
        if proc.returncode != 0:
            if syslog is not None:
                syslog.stop()
            raise RuntimeError(
                f"docker run failed: {proc.stderr.strip() or proc.stdout.strip()}"
            )
        container_id = proc.stdout.strip().splitlines()[-1]
        return DockerHandle(docker, container_id, task.name, syslog=syslog)

    def open(self, ctx: TaskContext, handle_id: str) -> Optional[DriverHandle]:
        if not handle_id.startswith("docker:"):
            return None
        parts = handle_id.split(":", 3)
        if len(parts) == 4 and parts[2].isdigit():
            _, container_id, port_s, task_name = parts
            syslog_port = int(port_s)
        else:  # pre-port handle format
            _, container_id, task_name = handle_id.split(":", 2)
            syslog_port = 0
        docker = _docker_bin()
        if not docker:
            return None
        try:
            proc = _run([docker, "inspect", "-f", "{{json .State.Running}}",
                         container_id], timeout=10.0)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            return None
        try:
            running = json.loads(proc.stdout.strip())
        except ValueError:
            return None
        if not running:
            return None
        # Rebind the log collector on the same port the container's
        # syslog driver targets (the old collector died with the old
        # client); without this every post-restart log line is lost.
        syslog = None
        if syslog_port and ctx.log_dir:
            from ..syslog import SyslogCollector

            try:
                syslog = SyslogCollector(
                    ctx.log_dir, task_name,
                    max_files=ctx.log_max_files,
                    max_bytes=ctx.log_max_file_size_mb * 1024 * 1024,
                    port=syslog_port)
            except OSError:
                syslog = None  # port taken: logs dropped THIS session;
                # the port persists in the id for the next restart
        return DockerHandle(docker, container_id, task_name, syslog=syslog,
                            syslog_port=syslog_port)
