"""Driver config schema validation.

Reference: helper/fields (FieldData/FieldSchema) — every driver
validates its opaque `task.config` map against a declared schema before
start, so typos and type errors fail at validation time instead of
surfacing as weird runtime behavior.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional


@dataclass
class Field:
    type: str  # "string" | "int" | "bool" | "float" | "list" | "map"
    required: bool = False


class FieldSchema:
    def __init__(self, fields: Dict[str, Field]):
        self.fields = fields

    def validate(self, config: Optional[Dict[str, Any]],
                 where: str = "config") -> List[str]:
        """Returns a list of error strings (empty when valid)."""
        config = config or {}
        errors = []
        for key, f in self.fields.items():
            if not f.required:
                continue
            # An empty string is as useless as a missing key for the
            # required (string) fields — reject both, like the old
            # per-driver `if not config.get(...)` checks did.
            if key not in config or config[key] in ("", None):
                errors.append(f"{where}: missing required key {key!r}")

        def _weak_int(v):
            if isinstance(v, bool):
                return False
            if isinstance(v, int):
                return True
            if isinstance(v, str):
                try:
                    int(v)
                    return True
                except ValueError:
                    return False
            return False

        def _weak_float(v):
            if isinstance(v, bool):
                return False
            if isinstance(v, (int, float)):
                return True
            if isinstance(v, str):
                try:
                    float(v)
                    return True
                except ValueError:
                    return False
            return False

        # WeakDecode semantics (helper/fields via mapstructure): HCL
        # users write numbers/bools as strings freely.
        checkers = {
            "any": lambda v: True,
            "string": lambda v: isinstance(v, str),
            "int": _weak_int,
            "float": _weak_float,
            "bool": lambda v: isinstance(v, bool)
            or (isinstance(v, str) and v.lower() in ("true", "false")),
            "list": lambda v: isinstance(v, list),
            "map": lambda v: isinstance(v, dict),
        }
        for key, value in config.items():
            f = self.fields.get(key)
            if f is None:
                errors.append(f"{where}: unknown key {key!r}")
                continue
            if isinstance(value, str) and "${" in value:
                # Interpolated at start time (utils/interpolate.py);
                # its post-substitution type can't be known yet. The
                # task runner re-validates the interpolated config
                # before start, so deferral never skips the check.
                continue
            if not checkers[f.type](value):
                errors.append(
                    f"{where}: key {key!r} must be a {f.type}, "
                    f"got {type(value).__name__}")
        return errors

    def coerce(self, config: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        """Convert weak-decoded string values to their declared types
        (mapstructure WeakDecode does the same); call after validate —
        non-coercible values pass through unchanged."""
        out = dict(config or {})
        for key, value in out.items():
            f = self.fields.get(key)
            if f is None or not isinstance(value, str):
                continue
            try:
                if f.type == "int":
                    out[key] = int(value)
                elif f.type == "float":
                    out[key] = float(value)
                elif f.type == "bool":
                    out[key] = value.lower() == "true"
            except ValueError:
                pass
        return out
