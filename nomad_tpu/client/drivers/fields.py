"""Driver config schema validation.

Reference: helper/fields (FieldData/FieldSchema) — every driver
validates its opaque `task.config` map against a declared schema before
start, so typos and type errors fail at validation time instead of
surfacing as weird runtime behavior.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional


@dataclass
class Field:
    type: str  # "string" | "int" | "bool" | "float" | "list" | "map"
    required: bool = False


class FieldSchema:
    def __init__(self, fields: Dict[str, Field]):
        self.fields = fields

    def validate(self, config: Optional[Dict[str, Any]],
                 where: str = "config") -> List[str]:
        """Returns a list of error strings (empty when valid)."""
        config = config or {}
        errors = []
        for key, f in self.fields.items():
            if f.required and key not in config:
                errors.append(f"{where}: missing required key {key!r}")
        checkers = {
            "any": lambda v: True,
            "string": lambda v: isinstance(v, str),
            "int": lambda v: isinstance(v, int) and not isinstance(v, bool),
            "float": lambda v: isinstance(v, (int, float))
            and not isinstance(v, bool),
            "bool": lambda v: isinstance(v, bool),
            "list": lambda v: isinstance(v, list),
            "map": lambda v: isinstance(v, dict),
        }
        for key, value in config.items():
            f = self.fields.get(key)
            if f is None:
                errors.append(f"{where}: unknown key {key!r}")
                continue
            ok = checkers[f.type](value)
            if not ok:
                errors.append(
                    f"{where}: key {key!r} must be a {f.type}, "
                    f"got {type(value).__name__}")
        return errors
