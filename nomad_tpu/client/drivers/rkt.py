"""rkt driver: run an appc/OCI pod image via the rkt CLI.

Reference: client/driver/rkt.go:441 — fingerprint shells `rkt version`
and requires a minimum rkt version (rkt.go:100-140); Start optionally
trusts a key prefix (`rkt trust --prefix=`), then builds
`rkt run <image>` with the alloc dir volume-mounted, --exec/args
overrides, dns servers/search domains, --net and port forwards from
port_map (rkt.go:150-330), all under the out-of-process executor.
Config keys: image, command, args, trust_prefix, dns_servers,
dns_search_domains, net, port_map, volumes, insecure_options, debug.
"""

from __future__ import annotations

import re
import shutil
import subprocess
from dataclasses import replace
from typing import Optional

from ...structs import Node, Task
from .fields import Field, FieldSchema
from .base import Driver, DriverHandle, TaskContext, register_driver

RKT_BIN = "rkt"
MIN_VERSION = (1, 0, 0)


def _rkt_version(rkt: str) -> Optional[dict]:
    try:
        proc = subprocess.run(
            [rkt, "version"], capture_output=True, text=True, timeout=10.0
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    out = {}
    m = re.search(r"rkt [Vv]ersion:?\s*([\d.]+)", proc.stdout)
    if m:
        out["version"] = m.group(1)
    m = re.search(r"appc [Vv]ersion:?\s*([\d.+]+)", proc.stdout)
    if m:
        out["appc.version"] = m.group(1)
    return out or None


@register_driver
class RktDriver(Driver):
    name = "rkt"

    def fingerprint(self, node: Node) -> bool:
        rkt = shutil.which(RKT_BIN)
        info = _rkt_version(rkt) if rkt else None
        if info is None:
            node.attributes.pop("driver.rkt", None)
            return False
        version = info.get("version", "0")
        parts = tuple(int(p) for p in version.split(".")[:3] if p.isdigit())
        if parts < MIN_VERSION:
            # Old rkt lacks --net/--dns flags the driver uses (rkt.go
            # minimum-version gate).
            node.attributes.pop("driver.rkt", None)
            return False
        node.attributes["driver.rkt"] = "1"
        node.attributes["driver.rkt.version"] = version
        if "appc.version" in info:
            node.attributes["driver.rkt.appc.version"] = info["appc.version"]
        return True

    config_schema = FieldSchema({
        "image": Field("string", required=True),
        "command": Field("string"),
        "args": Field("list"),
        "trust_prefix": Field("string"),
        "dns_servers": Field("list"),
        "dns_search_domains": Field("list"),
        "net": Field("any"),
        "port_map": Field("map"),
        "volumes": Field("list"),
        "insecure_options": Field("list"),
        "debug": Field("bool"),
    })


    def start(self, ctx: TaskContext, task: Task) -> DriverHandle:
        from ..executor import launch_executor

        rkt = shutil.which(RKT_BIN)
        if not rkt:
            raise RuntimeError(f"{RKT_BIN} not found")
        cfg = task.config or {}
        image = cfg.get("image")
        if not image:
            raise ValueError(f"rkt task {task.name!r} missing 'image'")

        # Establish trust for signed images before run (rkt.go:180-200).
        trust_prefix = cfg.get("trust_prefix")
        if trust_prefix:
            subprocess.run(
                [rkt, "trust", "--skip-fingerprint-review=true",
                 f"--prefix={trust_prefix}"],
                capture_output=True, timeout=30.0, check=False,
            )

        argv = ["run"]
        for opt in cfg.get("insecure_options", []):
            argv.append(f"--insecure-options={opt}")
        if not trust_prefix and not cfg.get("insecure_options"):
            # unsigned local images still need image verification off
            argv.append("--insecure-options=image")
        if cfg.get("debug"):
            argv.append("--debug=true")

        # Mount the alloc shared dir into the pod (rkt.go volume setup).
        argv += [f"--volume=alloc,kind=host,source={ctx.alloc_dir}",
                 "--mount=volume=alloc,target=/alloc"]
        for i, vol in enumerate(cfg.get("volumes", [])):
            # "host_path:container_path" pairs
            host, _, container = str(vol).partition(":")
            argv += [f"--volume=vol{i},kind=host,source={host}",
                     f"--mount=volume=vol{i},target={container or host}"]

        for server in cfg.get("dns_servers", []):
            argv.append(f"--dns={server}")
        for domain in cfg.get("dns_search_domains", []):
            argv.append(f"--dns-search={domain}")
        net = cfg.get("net")
        if net:
            argv.append(f"--net={','.join(net) if isinstance(net, list) else net}")
        # Host-port forwards from the task's allocated ports
        # (rkt.go:260-300 port_map handling).
        for container_port, host_port in (cfg.get("port_map") or {}).items():
            argv.append(f"--port={container_port}:{host_port}")

        argv.append(image)
        command = cfg.get("command")
        if command:
            argv.append(f"--exec={command}")
        args = cfg.get("args", [])
        if args:
            argv.append("--")
            argv += [str(a) for a in args]

        exec_task = replace(task, config={"command": rkt, "args": argv})
        return launch_executor(ctx, exec_task)

    def open(self, ctx: TaskContext, handle_id: str) -> Optional[DriverHandle]:
        from ..executor import reattach_executor

        return reattach_executor(handle_id)
