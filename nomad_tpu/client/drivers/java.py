"""java driver: run a jar under the JVM via the out-of-process executor.

Reference: client/driver/java.go:423 — fingerprint shells `java
-version` and parses the version/runtime from stderr (java.go:71-120);
Start builds `java [jvm_options...] -jar <jar> [args...]` and hands it
to the executor, which applies the same isolation as exec
(java.go:160-220). Config keys: jar_path, jvm_options, args.
"""

from __future__ import annotations

import os
import re
import shutil
import subprocess
from dataclasses import replace
from typing import Optional

from ...structs import Node, Task
from .fields import Field, FieldSchema
from .base import Driver, DriverHandle, TaskContext, register_driver


def _java_version(java: str) -> Optional[str]:
    try:
        proc = subprocess.run(
            [java, "-version"], capture_output=True, text=True, timeout=10.0
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    # `java -version` prints to stderr: java/openjdk version "11.0.x"
    out = proc.stderr or proc.stdout
    m = re.search(r'version "([^"]+)"', out)
    if m:
        return m.group(1)
    return None if proc.returncode != 0 else "unknown"


@register_driver
class JavaDriver(Driver):
    name = "java"

    def fingerprint(self, node: Node) -> bool:
        java = shutil.which("java")
        version = _java_version(java) if java else None
        if version is None:
            node.attributes.pop("driver.java", None)
            return False
        node.attributes["driver.java"] = "1"
        node.attributes["driver.java.version"] = version
        return True

    config_schema = FieldSchema({
        "jar_path": Field("string", required=True),
        "jvm_options": Field("list"),
        "args": Field("list"),
    })


    def start(self, ctx: TaskContext, task: Task) -> DriverHandle:
        from ..executor import launch_executor

        java = shutil.which("java")
        if not java:
            raise RuntimeError("java binary not found")
        cfg = task.config or {}
        jar = cfg.get("jar_path")
        if not jar:
            raise ValueError(f"java task {task.name!r} missing 'jar_path'")
        if not os.path.isabs(jar):
            jar = os.path.join(ctx.task_root or ctx.task_dir, jar)
        argv = [str(o) for o in cfg.get("jvm_options", [])]
        argv += ["-jar", jar]
        argv += [str(a) for a in cfg.get("args", [])]
        # Rewrite the task config into an exec-shaped command for the
        # shared executor path (java.go delegates to the same executor).
        exec_task = replace(task, config={"command": java, "args": argv})
        mem_bytes = None
        if task.resources is not None and task.resources.memory_mb:
            mem_bytes = task.resources.memory_mb * 1024 * 1024
        return launch_executor(ctx, exec_task, rlimit_as=mem_bytes)

    def open(self, ctx: TaskContext, handle_id: str) -> Optional[DriverHandle]:
        from ..executor import reattach_executor

        return reattach_executor(handle_id)
