"""raw_exec driver: run a command with no isolation.

Reference: client/driver/raw_exec.go:312 — opt-in via client option
driver.raw_exec.enable; the command runs under the out-of-process
executor so it survives client restarts (executor_plugin.go), with
stdout/stderr rotated into the alloc log dir.
"""

from __future__ import annotations

import os
import signal
import subprocess
import threading
from typing import Optional

from ...structs import Node, Task
from .fields import Field, FieldSchema
from .base import Driver, DriverHandle, TaskContext, WaitResult, register_driver


class ProcessHandle(DriverHandle):
    """In-process child handle — used by drivers that manage their own
    external supervisor (e.g. docker) or in tests."""

    def __init__(self, proc: subprocess.Popen, task_name: str):
        self.proc = proc
        self.task_name = task_name
        self._result: Optional[WaitResult] = None
        self._done = threading.Event()
        self._waiter = threading.Thread(target=self._wait_proc, daemon=True)
        self._waiter.start()

    def _wait_proc(self) -> None:
        code = self.proc.wait()
        if code < 0:
            self._result = WaitResult(exit_code=0, signal=-code)
        else:
            self._result = WaitResult(exit_code=code)
        self._done.set()

    def id(self) -> str:
        return f"{self.task_name}:{self.proc.pid}"

    def pid(self) -> Optional[int]:
        return self.proc.pid

    def wait(self, timeout: Optional[float] = None) -> Optional[WaitResult]:
        if not self._done.wait(timeout):
            return None
        return self._result

    def signal(self, signum: int) -> None:
        try:
            os.killpg(self.proc.pid, signum)
        except (ProcessLookupError, PermissionError, OSError):
            pass

    def kill(self, kill_timeout: float = 5.0) -> None:
        if self._done.is_set():
            return
        try:
            # Signal the whole process group (we start with setsid).
            os.killpg(self.proc.pid, signal.SIGINT)
        except (ProcessLookupError, PermissionError, OSError):
            pass
        if not self._done.wait(kill_timeout):
            try:
                os.killpg(self.proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                self.proc.kill()
            self._done.wait(2.0)


def launch_command(ctx: TaskContext, task: Task, preexec=None) -> subprocess.Popen:
    """Direct (non-executor) launch, kept for driver-internal use."""
    cfg = task.config or {}
    command = cfg.get("command")
    if not command:
        raise ValueError(f"missing command for task {task.name!r}")
    args = [command] + [str(a) for a in cfg.get("args", [])]
    env = dict(os.environ)
    env.update(ctx.env)
    stdout = open(os.path.join(ctx.log_dir, f"{task.name}.stdout.0"), "ab")
    stderr = open(os.path.join(ctx.log_dir, f"{task.name}.stderr.0"), "ab")
    return subprocess.Popen(
        args,
        cwd=ctx.task_root or ctx.task_dir,
        env=env,
        stdout=stdout,
        stderr=stderr,
        start_new_session=True,  # own process group for clean kills
        preexec_fn=preexec,
    )


@register_driver
class RawExecDriver(Driver):
    name = "raw_exec"

    config_schema = FieldSchema({
        "command": Field("string", required=True),
        "args": Field("list"),
    })


    def fingerprint(self, node: Node) -> bool:
        # Opt-in only: no isolation (raw_exec.go fingerprint gate).
        if node.attributes.get("driver.raw_exec.enable") != "1":
            node.attributes.pop("driver.raw_exec", None)
            return False
        node.attributes["driver.raw_exec"] = "1"
        return True

    def start(self, ctx: TaskContext, task: Task) -> DriverHandle:
        from ..executor import launch_executor

        return launch_executor(ctx, task)

    def open(self, ctx: TaskContext, handle_id: str) -> Optional[DriverHandle]:
        from ..executor import reattach_executor

        return reattach_executor(handle_id)
