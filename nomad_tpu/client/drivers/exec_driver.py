"""exec driver: command execution with best-effort isolation.

Reference: client/driver/exec.go:326 + exec_linux.go (cgroup + chroot
via the out-of-process executor). Here: own session + rlimits applied
in the child via preexec; full cgroup/chroot isolation requires root
and lands with the native executor.
"""

from __future__ import annotations

import os
import resource
import subprocess
from typing import Optional

from ...structs import Node, Task
from .base import Driver, DriverHandle, TaskContext, register_driver
from .raw_exec import ProcessHandle


@register_driver
class ExecDriver(Driver):
    name = "exec"

    def fingerprint(self, node: Node) -> bool:
        if node.attributes.get("kernel.name", "linux") != "linux":
            return False
        node.attributes["driver.exec"] = "1"
        return True

    def start(self, ctx: TaskContext, task: Task) -> DriverHandle:
        cfg = task.config or {}
        command = cfg.get("command")
        if not command:
            raise ValueError(f"missing command for task {task.name!r}")
        args = [command] + [str(a) for a in cfg.get("args", [])]
        env = dict(os.environ)
        env.update(ctx.env)
        stdout = open(os.path.join(ctx.log_dir, f"{task.name}.stdout.0"), "ab")
        stderr = open(os.path.join(ctx.log_dir, f"{task.name}.stderr.0"), "ab")

        mem_bytes = None
        if task.resources is not None and task.resources.memory_mb:
            mem_bytes = task.resources.memory_mb * 1024 * 1024

        def preexec():
            if mem_bytes is not None:
                try:
                    resource.setrlimit(resource.RLIMIT_AS, (mem_bytes, mem_bytes))
                except (ValueError, OSError):
                    pass

        proc = subprocess.Popen(
            args,
            cwd=ctx.task_dir,
            env=env,
            stdout=stdout,
            stderr=stderr,
            start_new_session=True,
            preexec_fn=preexec,
        )
        return ProcessHandle(proc, task.name)
