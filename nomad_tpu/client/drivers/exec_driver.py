"""exec driver: command execution with best-effort isolation.

Reference: client/driver/exec.go:326 + exec_linux.go (cgroup + chroot
via the out-of-process executor). Here: own session + rlimits applied
in the child via preexec; full cgroup/chroot isolation requires root
and lands with the native executor.
"""

from __future__ import annotations

import resource

from ...structs import Node, Task
from .base import Driver, DriverHandle, TaskContext, register_driver
from .raw_exec import ProcessHandle, launch_command


@register_driver
class ExecDriver(Driver):
    name = "exec"

    def fingerprint(self, node: Node) -> bool:
        if node.attributes.get("kernel.name", "linux") != "linux":
            return False
        node.attributes["driver.exec"] = "1"
        return True

    def start(self, ctx: TaskContext, task: Task) -> DriverHandle:
        mem_bytes = None
        if task.resources is not None and task.resources.memory_mb:
            mem_bytes = task.resources.memory_mb * 1024 * 1024

        def preexec():
            if mem_bytes is not None:
                try:
                    resource.setrlimit(resource.RLIMIT_AS, (mem_bytes, mem_bytes))
                except (ValueError, OSError):
                    pass

        return ProcessHandle(
            launch_command(ctx, task, preexec=preexec), task.name
        )
