"""exec driver: command execution with resource isolation.

Reference: client/driver/exec.go:326 + exec_linux.go — runs under the
out-of-process executor, which applies cgroup limits when root
(executor_linux.go:48) plus an address-space rlimit in the child, and
optional chroot when explicitly configured.
"""

from __future__ import annotations

import os
from typing import Optional

from ...structs import Node, Task
from .fields import Field, FieldSchema
from .base import Driver, DriverHandle, TaskContext, register_driver


@register_driver
class ExecDriver(Driver):
    name = "exec"

    config_schema = FieldSchema({
        "command": Field("string", required=True),
        "args": Field("list"),
        "chroot": Field("bool"),
    })

    def validate_config(self, task: Task) -> None:
        # chroot_env is an OPERATOR setting (ClientConfig.chroot_env,
        # matching the reference's client-config placement): a job
        # submitter choosing which host paths get hardlinked into the
        # chroot (/etc/shadow, /root/.ssh, ...) would silently undo
        # chroot as an isolation boundary. Checked BEFORE the generic
        # schema pass so the rejection names the client-config home of
        # the knob instead of a generic unknown-key error.
        if (task.config or {}).get("chroot_env") is not None:
            raise ValueError(
                "exec config: 'chroot_env' is a client agent setting "
                "(client config chroot_env), not task config")
        super().validate_config(task)

    def fingerprint(self, node: Node) -> bool:
        if node.attributes.get("kernel.name", "linux") != "linux":
            return False
        node.attributes["driver.exec"] = "1"
        return True

    def start(self, ctx: TaskContext, task: Task) -> DriverHandle:
        from ..executor import launch_executor

        mem_bytes = None
        if task.resources is not None and task.resources.memory_mb:
            mem_bytes = task.resources.memory_mb * 1024 * 1024
        # Chroot only on explicit opt-in while running as root: embed
        # the host toolchain into the task dir (alloc_dir.go:348 Embed
        # + exec_linux.go:48) so the chrooted binary finds its loader
        # and libraries, then ask the executor to chroot there. The
        # embed map comes from CLIENT config (ctx.chroot_env; None =
        # allocdir defaults), and the embed registers its subtrees in
        # agent-owned AllocDir state via ctx.embed_chroot so the disk
        # watcher prunes them.
        chroot = None
        if (task.config or {}).get("chroot") and os.geteuid() == 0:
            chroot = ctx.task_root or ctx.task_dir
            if ctx.embed_chroot is not None:
                ctx.embed_chroot(ctx.chroot_env)
            else:
                from ..allocdir import embed_chroot

                embed_chroot(chroot, ctx.chroot_env)
        return launch_executor(ctx, task, rlimit_as=mem_bytes, chroot=chroot)

    def open(self, ctx: TaskContext, handle_id: str) -> Optional[DriverHandle]:
        from ..executor import reattach_executor

        return reattach_executor(handle_id)
