"""TaskRunner: per-task lifecycle state machine.

Reference: client/task_runner.go:123 — Run:298 (validate -> prestart ->
start -> wait/restart loop), shouldRestart:560, killTask:605, event
handling for Update/Kill, and persisted driver handle ids for reattach
(RestoreState:189).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

from ..structs import (
    Allocation,
    Task,
    TaskEvent,
    TaskState,
    consts,
    new_task_event,
)
from .allocdir import TASK_LOCAL, TASK_SECRETS, AllocDir
from .drivers import new_driver
from .drivers.base import TaskContext, WaitResult
from .env import build_task_env
from .restarts import NO_RESTART, RestartTracker


class TaskRunner:
    def __init__(
        self,
        alloc: Allocation,
        task: Task,
        alloc_dir: AllocDir,
        update_cb: Callable[[str, TaskState], None],
        max_kill_timeout: float = 30.0,
        logger: Optional[logging.Logger] = None,
        restore_handle_id: str = "",
        persist_cb: Optional[Callable[[], None]] = None,
        template_kv: Optional[Callable[[str], Optional[str]]] = None,
        vault_client=None,
        chroot_env=None,
    ):
        self.alloc = alloc
        self.task = task
        self.alloc_dir = alloc_dir
        self.update_cb = update_cb
        self.max_kill_timeout = max_kill_timeout
        self.logger = logger or logging.getLogger(f"nomad_tpu.task.{task.name}")

        tg = alloc.job.lookup_task_group(alloc.task_group)
        self.restart_tracker = RestartTracker(
            tg.restart_policy, alloc.job.type
        )

        self.state = TaskState()
        self.handle = None
        self.handle_id = ""
        self._template_manager = None
        self._restart_requested = threading.Event()
        # Persisted handle id from a previous client run; run() tries to
        # reattach before starting fresh (task_runner.go:189).
        self.restore_handle_id = restore_handle_id
        # Called whenever handle_id changes so the client snapshots it
        # immediately — a crash between task start and the periodic save
        # would otherwise orphan the executor and duplicate the task.
        self.persist_cb = persist_cb
        # KV lookup for {{ key "..." }} templates (service registry).
        self.template_kv = template_kv
        # Vault token manager (client/vaultclient); None when the task
        # has no vault block or the client runs without vault.
        self.vault_client = vault_client
        # Operator chroot embed map (ClientConfig.chroot_env via
        # AllocRunner); rides the TaskContext into the exec driver.
        self.chroot_env = chroot_env
        self._vault_token = ""
        self._kill = threading.Event()
        self._destroy_event: Optional[TaskEvent] = None
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # Set by update_inplace: the next start must re-render the
        # task environment from the adopted alloc/task definition.
        self._env_stale = False  # guarded-by: _lock
        # Bumped by update_inplace. An update landing while a start is
        # in flight (env already rendered, RUNNING not yet emitted)
        # finds no live handle to restart-kill and no future start to
        # adopt it — the run loop compares generations after coming up
        # and restarts itself if one was missed.
        self._def_gen = 0  # guarded-by: _lock

    # ------------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, name=f"task-{self.alloc.id[:8]}-{self.task.name}",
            daemon=True,
        )
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def kill(self, event: Optional[TaskEvent] = None,
             fail: bool = False) -> None:
        """`fail=True` marks the task failed when it dies (a policy
        kill — disk quota, leader kill — not an operator stop)."""
        with self._lock:
            self._destroy_event = event or new_task_event(consts.TASK_EVENT_KILLING)
            self._destroy_fail = fail
            self._kill.set()
            handle = self.handle  # run() re-kills if start() races us
        if handle is not None:
            kill_timeout = min(self.task.kill_timeout, self.max_kill_timeout)
            try:
                handle.kill(kill_timeout)
            except Exception:
                self.logger.exception("kill failed")

    def update_inplace(self, alloc: Allocation, task) -> None:
        """Server pushed an in-place alloc update (an env/meta-level
        job tweak, scheduler/util.py tasks_updated): adopt the new
        task definition and restart the live task so its next start
        renders the new environment. Rides the template
        change_mode=restart machinery — the restart is requested
        work, never a failure, so it does not count against the
        restart policy. A task that is not currently running just
        adopts the definition (its next start reads it anyway)."""
        with self._lock:
            self.alloc = alloc
            self.task = task
            self._env_stale = True
            self._def_gen += 1
            handle = self.handle
        if handle is None or self.state.state != consts.TASK_STATE_RUNNING:
            return
        self._restart_requested.set()
        ev = new_task_event(consts.TASK_EVENT_RESTART_SIGNAL)
        ev.message = "In-place update: restarting with the new task environment"
        self._emit(self.state.state, ev)
        try:
            handle.kill(min(self.task.kill_timeout, self.max_kill_timeout))
        except Exception:
            self.logger.exception("in-place update restart kill failed")

    # ------------------------------------------------------------------

    def _emit(self, state: str, event: Optional[TaskEvent] = None,
              failed: Optional[bool] = None) -> None:
        self.state.state = state
        if failed is not None:
            self.state.failed = failed
        if event is not None:
            self.state.events.append(event)
            if len(self.state.events) > 10:  # bounded (structs.go maxTaskEvents)
                self.state.events = self.state.events[-10:]
        self.update_cb(self.task.name, self.state)

    def run(self) -> None:
        # validate
        errors = self.task.validate()
        if errors:
            ev = new_task_event(consts.TASK_EVENT_FAILED_VALIDATION)
            ev.validation_error = "; ".join(errors)
            self._emit(consts.TASK_STATE_DEAD, ev, failed=True)
            return

        from .env import task_env_from_alloc_dir

        task_dir = self.alloc_dir.task_dirs[self.task.name]
        ctx = TaskContext(
            alloc_id=self.alloc.id,
            alloc_dir=self.alloc_dir.shared_dir,
            task_dir=os.path.join(task_dir, TASK_LOCAL),
            task_root=task_dir,
            log_dir=self.alloc_dir.log_dir(),
            env=task_env_from_alloc_dir(self.alloc, self.task,
                                        self.alloc_dir),
            networks=list(getattr(
                self.alloc.task_resources.get(self.task.name),
                "networks", None) or []),
            max_kill_timeout=self.max_kill_timeout,
            log_max_files=(self.task.log_config.max_files
                           if self.task.log_config else 10),
            log_max_file_size_mb=(self.task.log_config.max_file_size_mb
                                  if self.task.log_config else 10),
            chroot_env=self.chroot_env,
            embed_chroot=lambda sources=None: self.alloc_dir.embed_chroot(
                self.task.name, sources),
        )

        try:
            driver = new_driver(self.task.driver)
        except ValueError as e:
            ev = new_task_event(consts.TASK_EVENT_DRIVER_FAILURE)
            ev.driver_error = str(e)
            self._emit(consts.TASK_STATE_DEAD, ev, failed=True)
            return

        reattached = self._try_reattach(driver, ctx)
        # Driver config schema check (helper/fields analog): a config
        # typo is a permanent validation failure, not a restartable
        # driver error. Gates fresh starts only — a live task from a
        # previous client must reattach regardless, or its process is
        # orphaned.
        if not reattached:
            try:
                driver.validate_config(self.task)
            except ValueError as e:
                ev = new_task_event(consts.TASK_EVENT_FAILED_VALIDATION)
                ev.validation_error = str(e)
                self._emit(consts.TASK_STATE_DEAD, ev, failed=True)
                return
        if self._kill.is_set():
            # kill() raced _try_reattach while handle was still None: the
            # while loop below won't run, so reap any adopted task here
            # and always report a terminal state.
            self._finish_killed()
            return

        while not self._kill.is_set():
            # An in-place update (update_inplace) swapped the task
            # definition underneath us: re-render the environment so
            # this start picks up the new env/meta. Everything else in
            # the ctx is in-place-invariant by the scheduler's
            # compatibility rules (resources/networks never change).
            with self._lock:
                env_stale = self._env_stale
                self._env_stale = False
                start_gen = self._def_gen
            if env_stale:
                ctx.env = task_env_from_alloc_dir(
                    self.alloc, self.task, self.alloc_dir)
            # prestart: artifacts + initial template render
            # (task_runner.go:354; re-run on every restart like the
            # reference, so transient download failures retry under the
            # restart policy)
            if reattached:
                # Prestart already ran in the previous client process,
                # but the template watcher lives in ours: restart it so
                # change_mode keeps working across client restarts.
                self._start_templates(ctx, fail_fast=False)
                # The old process's renewal heap died with it. The
                # running task still holds the ORIGINAL token (in its
                # environment), so recover that token from
                # secrets/vault_token and resume renewing it — minting a
                # fresh one would leave the live process with a token
                # that silently expires at TTL (reference: client
                # restore re-renews the persisted token). Fall back to
                # deriving only if the persisted token is gone.
                if not self._recover_vault_token(ctx):
                    vault_err = self._derive_vault_token(ctx)
                    if vault_err is not None:
                        self.logger.warning(
                            "vault re-derive after reattach failed: %s",
                            vault_err,
                        )
            else:
                prestart_err = self._prestart(ctx)
                if prestart_err is not None:
                    result = WaitResult(exit_code=-1, error=prestart_err)
                    if self._handle_terminated(result):
                        self._stop_template_manager()
                        return
                    continue

            # start (unless we reattached to a still-live task)
            try:
                if reattached:
                    handle = self.handle
                    reattached = False
                    with self._lock:
                        killed_during_start = self._kill.is_set()
                else:
                    # Driver config strings may reference the task env
                    # (env.go ParseAndReplace): interpolate a start-time
                    # copy; the stored task keeps the raw spec. With the
                    # variables substituted the schema check runs in
                    # full (values deferred at submit time included),
                    # then weak string values coerce to declared types.
                    from dataclasses import replace as _dc_replace

                    from ..utils.interpolate import interpolate_value

                    config = interpolate_value(self.task.config or {},
                                               ctx.env)
                    start_task = _dc_replace(self.task, config=config)
                    try:
                        driver.validate_config(start_task)
                    except ValueError as e:
                        # Permanent: a bad interpolated value won't
                        # improve on retry. Prestart already ran, so
                        # tear its watchers down.
                        ev = new_task_event(
                            consts.TASK_EVENT_FAILED_VALIDATION)
                        ev.validation_error = str(e)
                        self._stop_template_manager()
                        self._stop_vault_renewal()
                        self._emit(consts.TASK_STATE_DEAD, ev, failed=True)
                        return
                    if driver.config_schema is not None:
                        start_task.config = driver.config_schema.coerce(
                            config)
                    handle = driver.start(ctx, start_task)
                    with self._lock:
                        self.handle = handle
                        self.handle_id = handle.id()
                        killed_during_start = self._kill.is_set()
                    self._persist_handle()
                if killed_during_start:
                    # kill() raced driver.start and found handle None;
                    # re-issue so the process isn't orphaned.
                    handle.kill(min(self.task.kill_timeout, self.max_kill_timeout))
            except Exception as e:  # noqa: BLE001 - driver start errors
                ev = new_task_event(consts.TASK_EVENT_DRIVER_FAILURE)
                ev.driver_error = str(e)
                self._emit(consts.TASK_STATE_PENDING, ev)
                result = WaitResult(exit_code=-1, error=str(e))
            else:
                self._emit(consts.TASK_STATE_RUNNING, new_task_event(consts.TASK_EVENT_STARTED))
                # An in-place update that landed while this start was
                # in flight adopted its definition (update_inplace saw
                # no RUNNING task to bounce) but this start rendered
                # the OLD env — and a `sleep`-forever task never starts
                # again on its own. Close the window: restart now.
                # _env_stale is still set (the update set it after the
                # consume above), so the next iteration re-renders.
                with self._lock:
                    missed_update = self._def_gen != start_gen
                if missed_update:
                    self._restart_requested.set()
                    ev = new_task_event(consts.TASK_EVENT_RESTART_SIGNAL)
                    ev.message = ("In-place update: restarting with "
                                  "the new task environment")
                    self._emit(consts.TASK_STATE_RUNNING, ev)
                    try:
                        handle.kill(min(self.task.kill_timeout,
                                        self.max_kill_timeout))
                    except Exception:
                        self.logger.exception(
                            "in-place update restart kill failed")
                result = None
                while result is None and not self._kill.is_set():
                    result = self.handle.wait(timeout=0.25)
                if result is None:
                    # killed: wait for the handle to finish dying
                    result = self.handle.wait(timeout=self.max_kill_timeout) or WaitResult(
                        exit_code=-1, signal=9
                    )

            if self._handle_terminated(result):
                self._stop_template_manager()
                return

        # _kill landed between the pre-loop check and the loop condition
        # (every in-loop exit returns above): still report terminal.
        self._stop_template_manager()
        self._finish_killed()

    def _handle_terminated(self, result: WaitResult) -> bool:
        """Process one task exit; True when run() should return (task is
        terminally dead), False to loop around and restart."""
        if self._kill.is_set():
            with self._lock:
                destroy_ev = self._destroy_event
                destroy_fail = getattr(self, "_destroy_fail", False)
            self._emit(
                consts.TASK_STATE_DEAD,
                destroy_ev or new_task_event(consts.TASK_EVENT_KILLED),
                failed=destroy_fail,
            )
            return True

        # terminated: record the exit
        ev = new_task_event(consts.TASK_EVENT_TERMINATED)
        ev.exit_code = result.exit_code
        ev.signal = result.signal
        ev.message = result.error
        self._emit(consts.TASK_STATE_PENDING, ev)

        # A template-triggered restart is deliberate: it neither consults
        # nor consumes the restart policy (consul_template.go restart).
        if self._restart_requested.is_set():
            self._restart_requested.clear()
            self._emit(consts.TASK_STATE_PENDING,
                       new_task_event(consts.TASK_EVENT_RESTARTING))
            return False

        decision, wait = self.restart_tracker.next_restart(result.successful())
        if decision == NO_RESTART:
            self._emit(
                consts.TASK_STATE_DEAD,
                new_task_event(consts.TASK_EVENT_NOT_RESTARTING),
                failed=not result.successful(),
            )
            return True

        restart_ev = new_task_event(consts.TASK_EVENT_RESTARTING)
        restart_ev.start_delay = wait
        self._emit(consts.TASK_STATE_PENDING, restart_ev)
        if self._kill.wait(wait):
            self._emit(consts.TASK_STATE_DEAD,
                       new_task_event(consts.TASK_EVENT_KILLED), failed=False)
            return True
        return False

    def _prestart(self, ctx) -> Optional[str]:
        """Artifacts + initial template render (task_runner.go:354
        prestart). Returns an error string on failure, None on success."""
        if self.task.artifacts:
            self._emit(
                consts.TASK_STATE_PENDING,
                new_task_event(consts.TASK_EVENT_DOWNLOADING_ARTIFACTS),
            )
            from .getter import ArtifactError, fetch_artifact

            for artifact in self.task.artifacts:
                try:
                    fetch_artifact(artifact, ctx.task_root or ctx.task_dir)
                except ArtifactError as e:
                    ev = new_task_event(
                        consts.TASK_EVENT_ARTIFACT_DOWNLOAD_FAILED
                    )
                    ev.message = str(e)
                    self._emit(consts.TASK_STATE_PENDING, ev)
                    return f"artifact download failed: {e}"

        vault_err = self._derive_vault_token(ctx)
        if vault_err is not None:
            return vault_err

        return self._start_templates(ctx, fail_fast=True)

    def _start_templates(self, ctx, fail_fast: bool) -> Optional[str]:
        """Create + start the template manager (idempotent). With
        fail_fast the initial render error is returned (prestart);
        otherwise it is only logged (reattach path — the task is already
        running and must not be failed for a render hiccup)."""
        if not self.task.templates or self._template_manager is not None:
            return None
        from .template import TaskTemplateManager

        mgr = TaskTemplateManager(
            self.task, ctx.env, ctx.task_root or ctx.task_dir,
            kv=self.template_kv,
            on_change=self._on_template_change, logger=self.logger,
        )
        try:
            mgr.render_all()
        except (ValueError, OSError) as e:
            if fail_fast:
                return f"template render failed: {e}"
            self.logger.exception("template render after reattach failed")
        self._template_manager = mgr
        mgr.start()
        return None

    def _on_template_change(self, mode: str, signal_name: str) -> None:
        """A re-render changed a template (consul_template.go change
        handling)."""
        with self._lock:
            handle = self.handle
        # Only act on a live task: a change firing during restart
        # backoff would otherwise set a stale _restart_requested that a
        # later unrelated crash consumes to bypass the restart policy.
        if handle is None or self.state.state != consts.TASK_STATE_RUNNING:
            return
        if mode == "restart":
            self._restart_requested.set()
            ev = new_task_event(consts.TASK_EVENT_RESTART_SIGNAL)
            ev.message = "Template with change_mode restart re-rendered"
            self._emit(self.state.state, ev)
            try:
                handle.kill(min(self.task.kill_timeout, self.max_kill_timeout))
            except Exception:
                self.logger.exception("template restart kill failed")
        elif mode == "signal":
            import signal as _signal

            signum = getattr(_signal, signal_name or "SIGHUP", _signal.SIGHUP)
            ev = new_task_event(consts.TASK_EVENT_SIGNALING)
            ev.message = f"Template re-rendered; sending {signal_name or 'SIGHUP'}"
            self._emit(self.state.state, ev)
            try:
                handle.signal(int(signum))
            except Exception:
                self.logger.exception("template signal failed")

    def _stop_template_manager(self) -> None:
        if self._template_manager is not None:
            self._template_manager.stop()
            self._template_manager = None
        self._stop_vault_renewal()

    # -------------------------------------------------------------- vault

    def _derive_vault_token(self, ctx) -> Optional[str]:
        """Fetch this task's vault token through the server, write it to
        secrets/vault_token, export VAULT_TOKEN, and keep it renewed
        (task_runner.go prestart vault wait + consul_template vault
        plumbing). Returns an error string on failure."""
        vault = self.task.vault
        if vault is None or self.vault_client is None:
            return None
        # A restart loop must not leave the previous token renewing
        # forever: drop it before deriving a fresh one.
        self._stop_vault_renewal()
        try:
            tokens, ttl = self.vault_client.derive_token(
                self.alloc.id, [self.task.name]
            )
            token = tokens[self.task.name]
        except Exception as e:  # noqa: BLE001 — API/permission errors
            return f"vault token derivation failed: {e}"
        self._vault_token = token
        secrets_dir = os.path.join(ctx.task_root or ctx.task_dir, TASK_SECRETS)
        os.makedirs(secrets_dir, exist_ok=True)
        token_path = os.path.join(secrets_dir, "vault_token")
        with open(token_path, "w") as f:
            f.write(token)
        os.chmod(token_path, 0o600)
        if vault.env:
            ctx.env["VAULT_TOKEN"] = token

        self.vault_client.renew_token(token, ttl, self._vault_on_renew_fail)
        return None

    # Assumed lease for a token recovered from disk after client restart:
    # the real TTL is unknown until the first successful renewal reports
    # it, so renew promptly but give transient failures a grace window.
    RECOVERED_TOKEN_TTL = 60.0

    def _recover_vault_token(self, ctx) -> bool:
        """Adopt the persisted secrets/vault_token after reattach and
        resume its renewal. Returns False when there is nothing to
        recover (caller may derive a fresh token)."""
        vault = self.task.vault
        if vault is None or self.vault_client is None:
            return True  # nothing to do either way
        token_path = os.path.join(
            ctx.task_root or ctx.task_dir, TASK_SECRETS, "vault_token"
        )
        try:
            with open(token_path) as f:
                token = f.read().strip()
        except OSError:
            return False
        if not token:
            return False
        self._stop_vault_renewal()
        self._vault_token = token
        if vault.env:
            ctx.env["VAULT_TOKEN"] = token
        self.vault_client.renew_token(
            token, self.RECOVERED_TOKEN_TTL, self._vault_on_renew_fail,
            renew_now=True,
        )
        return True

    def _vault_on_renew_fail(self, err: str) -> None:
        # Renewal failure applies the vault change_mode
        # (structs Vault.ChangeMode) like a template change would.
        vault = self.task.vault
        if vault is None:
            return
        if vault.change_mode == "restart":
            self._on_template_change("restart", "")
        elif vault.change_mode == "signal":
            self._on_template_change("signal", vault.change_signal)

    def _stop_vault_renewal(self) -> None:
        if self.vault_client is not None and self._vault_token:
            self.vault_client.stop_renew_token(self._vault_token)
            self._vault_token = ""

    def _finish_killed(self) -> None:
        """Reap the handle (if any) and emit the terminal killed state —
        every run() exit path must leave the task DEAD or the alloc
        never reaches a terminal client status."""
        if self.handle is not None:
            try:
                self.handle.kill(min(self.task.kill_timeout, self.max_kill_timeout))
            except Exception:
                self.logger.exception("kill during shutdown failed")
        with self._lock:
            destroy_ev = self._destroy_event
            destroy_fail = getattr(self, "_destroy_fail", False)
        self._emit(
            consts.TASK_STATE_DEAD,
            destroy_ev or new_task_event(consts.TASK_EVENT_KILLED),
            failed=destroy_fail,
        )

    def _try_reattach(self, driver, ctx) -> bool:
        """Reopen a persisted driver handle after client restart
        (task_runner.go:189 RestoreState). Returns True when the task is
        still live under its executor; False falls through to a fresh
        start."""
        if not self.restore_handle_id:
            return False
        handle_id, self.restore_handle_id = self.restore_handle_id, ""
        try:
            handle = driver.open(ctx, handle_id)
        except Exception:  # noqa: BLE001 - treat as unrecoverable handle
            self.logger.exception("reattach failed")
            handle = None
        if handle is None:
            ev = new_task_event(consts.TASK_EVENT_DRIVER_FAILURE)
            ev.driver_error = "failed to reattach to task; restarting"
            self._emit(consts.TASK_STATE_PENDING, ev)
            return False
        with self._lock:
            self.handle = handle
            self.handle_id = handle.id()
        self._persist_handle()
        # run() emits RUNNING when it picks the handle up.
        return True

    def _persist_handle(self) -> None:
        if self.persist_cb is not None:
            try:
                self.persist_cb()
            except Exception:
                self.logger.exception("handle persist failed")

    # ------------------------------------------------------------------

    def persist(self) -> dict:
        return {
            "task": self.task.name,
            "handle_id": self.handle_id,
            "state": self.state.state,
            "failed": self.state.failed,
        }
