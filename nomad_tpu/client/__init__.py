from .mock_client import MockClient

__all__ = ["MockClient"]
