from .agent import ClientAgent
from .config import ClientConfig
from .mock_client import MockClient

__all__ = ["ClientAgent", "ClientConfig", "MockClient"]
