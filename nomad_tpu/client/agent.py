"""Client agent: node registration, heartbeats, alloc watching, task
execution.

Reference: client/client.go:166 — setupNode:609 + fingerprints:696 +
driver fingerprints:756, registerAndHeartbeat:812, long-poll
watchAllocations:1125 (diff keyed on alloc_modify_index), runAllocs:1285,
batched status sync allocSync:1050, state persistence saveState:531.
Talks to the server over the HTTP API (the wire substrate here; the
reference uses msgpack RPC).
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional

from ..api.client import APIError, Client as APIClient
from ..structs import Allocation, Node, Resources, consts
from ..utils.ids import generate_uuid
from ..utils.pool import WorkPool
from .alloc_runner import AllocRunner
from .config import ClientConfig
from .drivers import DRIVER_REGISTRY
from .fingerprint import fingerprint_consul, fingerprint_node
from .servers import ServerList

ALLOC_SYNC_INTERVAL = 0.2  # client.go allocSyncIntv (batched updates)


class ClientAgent:
    def __init__(self, config: ClientConfig, node: Optional[Node] = None):
        self.config = config
        self.logger = logging.getLogger("nomad_tpu.client")
        self.consul = config.consul_api
        if self.consul is None and config.consul_addr:
            from ..consul import ConsulAPI

            self.consul = ConsulAPI(config.consul_addr)
        if not config.servers and self.consul is None:
            raise ValueError("no servers configured and no consul for discovery")
        self.servers = ServerList(config.servers)
        if not config.servers:
            self._consul_discover()
        if not len(self.servers):
            raise ValueError("no servers configured or discovered")
        self.api = APIClient(self.servers.get(), timeout=330.0,
                             ssl_context=config.ssl_context)
        self.vault_client = None
        self.syncer = None
        if self.consul is not None:
            from ..consul import ConsulSyncer

            self.syncer = ConsulSyncer(self.consul)
        # alloc id -> consul service domains registered for its tasks;
        # guarded by _consul_lock (mutated from runner callback threads
        # and the alloc-watch thread). _consul_removed tombstones GC'd
        # allocs (insertion-ordered dict used as a bounded set) so a
        # late task-state callback can't re-register their services
        # after removal.
        self._consul_domains: Dict[str, set] = {}
        self._consul_removed: Dict[str, None] = {}
        self._consul_lock = threading.Lock()

        if not config.alloc_dir:
            config.alloc_dir = tempfile.mkdtemp(prefix="nomad_tpu_allocs_")
        if not config.state_dir:
            config.state_dir = tempfile.mkdtemp(prefix="nomad_tpu_state_")

        self.node = node or Node()
        self._setup_node()
        self._restored_handles: Dict[str, Dict[str, str]] = {}
        # Restore a persisted node identity + task handles before first
        # contact (client.go:496 restoreState).
        self._restore_state()

        self.alloc_runners: Dict[str, AllocRunner] = {}
        self._runners_lock = threading.Lock()
        self._dirty_allocs: Dict[str, Allocation] = {}
        self._dirty_lock = threading.Lock()
        # Replacement allocs waiting on a LOCAL previous alloc to go
        # terminal (client.go:1330 blockedAllocations), keyed by the
        # previous alloc id; and ids of allocs whose REMOTE previous
        # alloc is being waited on / migrated (client.go:153
        # migratingAllocs).
        self._blocked_allocs: Dict[str, Allocation] = {}
        # Guards _blocked_allocs alone: _release_blocked fires from
        # runner state-change callbacks, where taking _runners_lock
        # could deadlock against a runner started under it.
        self._blocked_lock = threading.Lock()
        self._migrating_allocs: Dict[str, None] = {}
        self._migrate_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # Bounded pools replacing per-event thread spawns: remote
        # migrations can block for minutes (waiting out the previous
        # alloc), so they get their own pool and can't starve the quick
        # housekeeping tasks (blocked-alloc release, runner destroy,
        # executor reaping).
        self._migrate_pool = WorkPool(8, name="client-migrate")
        self._task_pool = WorkPool(4, name="client-bg")
        self.heartbeat_ttl = 1.0

    # ------------------------------------------------------------------

    def _setup_node(self) -> None:
        node = self.node
        if not node.id:
            node.id = generate_uuid()
        if not node.secret_id:
            node.secret_id = generate_uuid()
        node.datacenter = self.config.datacenter
        node.node_class = self.config.node_class
        node.http_addr = self.config.http_addr
        node.meta.update(self.config.meta)
        if node.resources is None:
            node.resources = Resources()
        if self.config.reserved is not None:
            node.reserved = self.config.reserved
        # Client options become attributes drivers can gate on, e.g.
        # driver.raw_exec.enable (config "options", client/config).
        for k, v in self.config.options.items():
            node.attributes[k] = v
        fingerprint_node(node)
        if self.config.network_speed:
            for net in node.resources.networks:
                net.mbits = self.config.network_speed
        if self.consul is not None:
            fingerprint_consul(node, self.consul)
        if self.config.node_name:
            node.name = self.config.node_name
        # Driver fingerprints advertise availability.
        whitelist = set(self.config.driver_whitelist)
        for name, cls in DRIVER_REGISTRY.items():
            if whitelist and name not in whitelist:
                continue
            try:
                cls().fingerprint(node)
            except Exception:
                self.logger.exception("driver %s fingerprint failed", name)
        node.status = consts.NODE_STATUS_INIT

    # ------------------------------------------------------------------

    def start(self) -> None:
        self._sweep_stale_prev_dirs()
        try:
            self.heartbeat_ttl = self.api.nodes.register(self.node)
            self.api.nodes.update_status(self.node.id, consts.NODE_STATUS_READY)
        except APIError as e:
            if 400 <= e.status < 500:
                raise  # the server rejected us: a real config problem
            # Server unreachable (status 0) or transiently failing
            # (5xx, e.g. "no leader" while a raft cluster is still
            # forming): rotate endpoints and let the heartbeat loop's
            # re-register path bring us online (client.go
            # registerAndHeartbeat retries forever).
            self.logger.warning(
                "initial registration failed (%s); will retry", e)
            if e.status == 0:
                self._rpc_failed()
        # Vault tokens are derived through the server once the node has
        # an identity (client/vaultclient wiring, client.go:166).
        from .vaultclient import VaultClient

        self.vault_client = VaultClient(
            self.api, self.node.id, self.node.secret_id
        )
        if self.syncer is not None:
            # Scope consul ids to this node so reconcile never reaps
            # another agent's registrations (see ConsulSyncer.instance).
            self.syncer.instance = self.node.id[:8]
            self.syncer.start()
        targets = [
            (self._heartbeat_loop, "heartbeat"),
            (self._watch_allocations, "alloc-watch"),
            (self._alloc_sync_loop, "alloc-sync"),
            (self._save_state_loop, "save-state"),
        ]
        if self.consul is not None:
            targets.append((self._fingerprint_loop, "fingerprint"))
        for target, name in targets:
            t = threading.Thread(target=target, name=f"client-{name}", daemon=True)
            t.start()
            self._threads.append(t)

    def shutdown(self, destroy_allocs: bool = False) -> None:
        self._stop.set()
        if self.syncer is not None:
            self.syncer.shutdown()
        if self.vault_client is not None:
            self.vault_client.stop()
        for t in self._threads:
            t.join(timeout=3.0)
        if destroy_allocs:
            with self._runners_lock:
                runners = list(self.alloc_runners.values())
            for r in runners:
                r.destroy()
        self._save_state()

    # ------------------------------------------------------------------

    def _rpc_failed(self) -> None:
        """Demote the current server and move to the next-ranked one;
        fall back to consul catalog discovery when every configured
        endpoint has failed (serverlist.go + client.go:1762)."""
        cur = self.api.address
        self.servers.notify_failure(cur)
        nxt = self.servers.get()
        if nxt == cur or nxt is None:
            self._consul_discover()
            nxt = self.servers.get()
        if nxt and nxt != cur:
            self.logger.warning("rpc failover: %s -> %s", cur, nxt)
            self.api.address = nxt

    def _consul_discover(self) -> None:
        if self.consul is None:
            return
        from ..consul import discover_servers

        try:
            found = discover_servers(
                self.consul, service=self.config.consul_service)
        except Exception as e:  # noqa: BLE001 - consul down is soft
            self.logger.debug("consul discovery failed: %s", e)
            return
        addrs = [a if "://" in a else f"http://{a}" for a in found]
        if addrs:
            merged = list(dict.fromkeys(self.servers.all() + addrs))
            self.servers.set_servers(merged)

    def _heartbeat_loop(self) -> None:
        from ..chaos import chaos

        while not self._stop.is_set():
            interval = max(self.heartbeat_ttl / 2.0, 0.05)
            if self._stop.wait(interval):
                return
            if chaos.enabled and chaos.fire(
                    "client.heartbeat", node=self.node.id) == "drop":
                # Injected heartbeat loss: the renewal never reaches the
                # leader; enough consecutive drops expire the TTL and
                # the node goes down through the normal path.
                continue
            try:
                self.heartbeat_ttl = self.api.nodes.heartbeat(
                    self.node.id, self.node.secret_id
                )
                self.servers.notify_success(self.api.address)
            except APIError as e:
                if e.status == 0:
                    self._rpc_failed()
                    continue  # agent unreachable: try the next server
                # The server rejected the heartbeat (e.g. it lost our node
                # after a restart): re-register.
                self.logger.warning("heartbeat failed: %s", e)
                try:
                    self.heartbeat_ttl = self.api.nodes.register(self.node)
                    self.api.nodes.update_status(
                        self.node.id, consts.NODE_STATUS_READY
                    )
                except APIError:
                    self.logger.warning(
                        "re-registration failed; retrying next tick",
                        exc_info=True)
            except Exception:  # noqa: BLE001 - loop must survive
                self.logger.warning(
                    "heartbeat failed unexpectedly; retrying next tick",
                    exc_info=True)

    def _fingerprint_loop(self) -> None:
        """Periodic re-run of dynamic fingerprints (client.go:739):
        consul appearing/vanishing updates node attributes, and the
        changed node is re-registered so constraints see it."""
        interval = 3.0 if self.config.dev_mode else 15.0
        while not self._stop.wait(interval):
            before = dict(self.node.attributes)
            fingerprint_consul(self.node, self.consul)
            if self.node.attributes != before:
                try:
                    self.api.nodes.register(self.node)
                    # register overwrites server-side status with our
                    # local INIT snapshot; restore ready immediately so
                    # the node isn't filtered out until next heartbeat.
                    self.api.nodes.update_status(
                        self.node.id, consts.NODE_STATUS_READY)
                except Exception:  # noqa: BLE001 - next heartbeat retries
                    self.logger.debug(
                        "fingerprint re-registration failed; the next "
                        "heartbeat re-registers", exc_info=True)

    def _watch_allocations(self) -> None:
        """Blocking-query loop on this node's allocations; apply the
        diff (client.go:1125/1285)."""
        index = 0
        while not self._stop.is_set():
            try:
                allocs, new_index = self.api.nodes.allocations(
                    self.node.id, secret=self.node.secret_id,
                    index=index, wait=2.0,
                )
            except APIError as e:
                if e.status == 0:
                    self._rpc_failed()
                if self._stop.wait(0.5):
                    return
                continue
            except Exception:
                if self._stop.wait(0.5):
                    return
                continue
            index = max(new_index, index)
            self._run_allocs(allocs)

    def _run_allocs(self, pulled: List[Allocation]) -> None:
        pulled_ids = {a.id for a in pulled}
        with self._runners_lock:
            # removed: the server GC'd them
            for alloc_id in list(self.alloc_runners):
                if alloc_id not in pulled_ids:
                    runner = self.alloc_runners.pop(alloc_id)
                    self._remove_alloc_services(alloc_id)
                    self._task_pool.submit(runner.destroy)
            for alloc in pulled:
                runner = self.alloc_runners.get(alloc.id)
                if runner is not None:
                    if alloc.alloc_modify_index > runner.alloc.alloc_modify_index:
                        runner.update(alloc)
                    continue
                if alloc.terminal_status():
                    self._kill_restored_handles(alloc.id)
                    continue
                with self._migrate_lock:
                    if alloc.id in self._migrating_allocs:
                        continue  # remote-previous wait already running
                prev_id = alloc.previous_allocation
                prev_runner = (
                    self.alloc_runners.get(prev_id) if prev_id else None
                )
                if prev_runner is not None and not prev_runner.alloc.terminal_status():
                    # Chained to a live local alloc: start when it
                    # terminates (client.go:1330 blocked queue). The
                    # terminal transition can land between the check
                    # above and the insertion — re-check afterwards and
                    # release ourselves if the event already fired.
                    with self._blocked_lock:
                        self._blocked_allocs[prev_id] = alloc
                    if prev_runner.alloc.terminal_status():
                        self._release_blocked(prev_id)
                    continue
                if prev_id and prev_runner is None:
                    # Previous alloc lives on another node: wait for it
                    # and migrate its sticky disk off-thread
                    # (client.go:1371 blockForRemoteAlloc).
                    with self._migrate_lock:
                        self._migrating_allocs[alloc.id] = None
                    self._migrate_pool.submit(self._block_for_remote_alloc, alloc)
                    continue
                self._add_alloc_locked(
                    alloc, self._sticky_prev_dir(alloc, prev_runner))
            # Allocs that disappeared (or went terminal) while the
            # client was down never re-arrive, but their executors are
            # still running the task: reap them (the reference restores
            # runners from disk and destroys unneeded ones).
            for alloc_id in list(self._restored_handles):
                if alloc_id not in pulled_ids:
                    self._kill_restored_handles(alloc_id)

    def _add_alloc_locked(self, alloc: Allocation, prev_dir=None) -> None:
        """Create and start the runner (caller holds _runners_lock).
        prev_dir is a previous allocation's AllocDir whose sticky
        ephemeral disk the new alloc adopts (client.go:1585 addAlloc)."""
        if alloc.id in self.alloc_runners:
            return
        runner = AllocRunner(
            alloc, self.config.alloc_dir, self._mark_dirty,
            self.config.max_kill_timeout,
            restored_handles=self._restored_handles.pop(alloc.id, None),
            persist_cb=self._save_state,
            template_kv=self._template_kv,
            vault_client=self.vault_client,
            previous_alloc_dir=prev_dir,
            chroot_env=self.config.chroot_env,
        )
        self.alloc_runners[alloc.id] = runner
        runner.run()

    def _add_alloc(self, alloc: Allocation, prev_dir=None) -> None:
        with self._runners_lock:
            self._add_alloc_locked(alloc, prev_dir)

    def _sticky_prev_dir(self, alloc: Allocation, prev_runner):
        """The local previous alloc's dir, when the task group asks for
        a sticky ephemeral disk (client.go:1349-1355)."""
        if prev_runner is None or alloc.job is None:
            return None
        tg = alloc.job.lookup_task_group(alloc.task_group)
        if tg is None or tg.ephemeral_disk is None or not tg.ephemeral_disk.sticky:
            return None
        return prev_runner.alloc_dir

    # ------------------------------------------- sticky-disk migration

    def _sweep_stale_prev_dirs(self) -> None:
        """Remove leftover migration staging dirs (<alloc>.prev[.tmp]).
        At boot no migration is in flight — any pending one restarts
        from scratch — so everything matching is garbage from a crash
        or a mid-stream fetch failure."""
        import shutil

        try:
            names = os.listdir(self.config.alloc_dir)
        except OSError:
            return
        for name in names:
            if name.endswith(".prev") or name.endswith(".prev.tmp"):
                shutil.rmtree(
                    os.path.join(self.config.alloc_dir, name),
                    ignore_errors=True)

    def _block_for_remote_alloc(self, alloc: Allocation, index: int = 0) -> None:
        """One bounded round of waiting out a remote previous allocation
        (client.go:1371 blockForRemoteAlloc + :1405 waitForAllocTerminal):
        a single blocking-query poll; when the previous alloc is
        terminal, pull its sticky disk and start the replacement. Not
        yet terminal -> re-submit to the pool tail, so long-lived waits
        rotate through the bounded pool instead of wedging it (a 9th
        concurrent migration still makes progress with 8 workers)."""
        if self._stop.is_set():
            with self._migrate_lock:
                self._migrating_allocs.pop(alloc.id, None)
            return
        prev_id = alloc.previous_allocation
        try:
            prev, new_index = self.api.allocations.info(
                prev_id, index=index, wait=2.0)
        except APIError as e:
            if e.status == 404:
                self._finish_migration(alloc, None)
                return
            self._resubmit_migration(alloc, index, delay=1.0)
            return
        except Exception:
            self._resubmit_migration(alloc, index, delay=1.0)
            return
        if prev is not None and not prev.terminal_status():
            self._resubmit_migration(alloc, max(new_index, index), delay=0.0)
            return
        prev_dir = None
        try:
            if prev is not None:
                prev_dir = self._migrate_remote_alloc_dir(prev, alloc)
        except Exception:
            self.logger.exception(
                "migration from remote alloc %s failed", prev_id)
        self._finish_migration(alloc, prev_dir)

    def _resubmit_migration(self, alloc: Allocation, index: int,
                            delay: float) -> None:
        from ..utils.timer import default_wheel

        if delay > 0:
            default_wheel().schedule(
                delay, self._migrate_pool.submit,
                self._block_for_remote_alloc, alloc, index)
        else:
            self._migrate_pool.submit(self._block_for_remote_alloc, alloc, index)

    def _finish_migration(self, alloc: Allocation, prev_dir) -> None:
        if self._stop.is_set():
            with self._migrate_lock:
                self._migrating_allocs.pop(alloc.id, None)
            return
        try:
            self._add_alloc(alloc, prev_dir)
        finally:
            with self._migrate_lock:
                self._migrating_allocs.pop(alloc.id, None)

    def _migrate_remote_alloc_dir(self, prev: Allocation, alloc: Allocation):
        """Fetch the previous alloc's snapshot tar from its node's HTTP
        API and unpack it into a previous-alloc dir for move()
        (client.go:1441 migrateRemoteAllocDir)."""
        tg = alloc.job.lookup_task_group(alloc.task_group) if alloc.job else None
        if (tg is None or tg.ephemeral_disk is None
                or not tg.ephemeral_disk.sticky or not tg.ephemeral_disk.migrate):
            return None
        node, _ = self.api.nodes.info(prev.node_id)
        if node is None or node.status == consts.NODE_STATUS_DOWN:
            self.logger.info(
                "not migrating alloc %s: node %s down", prev.id, prev.node_id)
            return None
        if not node.http_addr:
            self.logger.warning(
                "not migrating alloc %s: node %s has no http addr",
                prev.id, prev.node_id)
            return None
        url = f"{node.http_addr}/v1/client/allocation/{prev.id}/snapshot"
        import shutil
        import urllib.request

        dest = os.path.join(self.config.alloc_dir, f"{alloc.id}.prev")
        tmp = dest + ".tmp"
        from .allocdir import AllocDir

        # The response feeds the tar reader incrementally (stream mode)
        # so a large ephemeral disk never materializes in client memory
        # on either end (the source streams chunked too). Unpack into a
        # staging dir and rename on success: a mid-stream failure (the
        # source truncating the chunked reply, the 60s timeout) must not
        # leave a partial .prev dir that move() would half-adopt — and
        # cleanup here (plus the boot sweep) keeps failures from leaking
        # gigabytes of ephemeral disk.
        shutil.rmtree(tmp, ignore_errors=True)
        try:
            # Peer nodes advertise https under cluster TLS: verify
            # against the cluster CA (config.ssl_context), never the
            # system store.
            ctx = (self.config.ssl_context
                   if url.startswith("https://") else None)
            with urllib.request.urlopen(url, timeout=60.0,
                                        context=ctx) as resp:
                AllocDir.restore_snapshot_stream(resp, tmp)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        shutil.rmtree(dest, ignore_errors=True)
        os.rename(tmp, dest)
        return AllocDir.from_existing(dest)

    def _kill_restored_handles(self, alloc_id: str) -> None:
        handles = self._restored_handles.pop(alloc_id, None) or {}
        if not handles:
            return

        def reap():
            from .executor import reattach_executor

            for handle_id in handles.values():
                try:
                    handle = reattach_executor(handle_id)
                    if handle is not None:
                        handle.kill()
                except Exception:
                    self.logger.exception("failed to reap restored handle")

        # Off-thread: reattach probes can block seconds and this is
        # called while _runners_lock is held.
        self._task_pool.submit(reap)

    def _template_kv(self, path: str):
        """KV source for {{ key "..." }} templates: consul KV when an
        agent is configured (consul_template.go), falling back to client
        options under the template.kv. prefix."""
        if self.consul is not None:
            try:
                val = self.consul.kv_get(path)
                if val is not None:
                    return val
            except Exception:  # noqa: BLE001 - consul down is soft
                self.logger.debug(
                    "consul KV read for %r failed; using client options",
                    path, exc_info=True)
        return (self.config.options or {}).get(f"template.kv.{path}")

    def _mark_dirty(self, alloc: Allocation) -> None:
        with self._dirty_lock:
            self._dirty_allocs[alloc.id] = alloc
        self._sync_task_services(alloc)
        if alloc.terminal_status():
            self._release_blocked(alloc.id)

    def _release_blocked(self, prev_id: str) -> None:
        """A local alloc went terminal: start any replacement that was
        queued behind it, handing over its sticky disk
        (client.go:1067-1079 blocked-allocation handoff)."""
        with self._blocked_lock:
            blocked = self._blocked_allocs.pop(prev_id, None)
        if blocked is None:
            return

        def _start():
            with self._runners_lock:
                prev_runner = self.alloc_runners.get(prev_id)
                self._add_alloc_locked(
                    blocked, self._sticky_prev_dir(blocked, prev_runner))

        # Off the state-change callback thread: runner start touches
        # _runners_lock and may do filesystem renames.
        self._task_pool.submit(_start)

    # ------------------------------------------------ consul services

    def _sync_task_services(self, alloc: Allocation) -> None:
        """Advertise services of running tasks; withdraw them when the
        task leaves running (syncer.go SetServices per task domain)."""
        if self.syncer is None or alloc.job is None:
            return
        from ..consul import task_services

        tg = next((g for g in alloc.job.task_groups
                   if g.name == alloc.task_group), None)
        if tg is None:
            return
        runner = self.alloc_runners.get(alloc.id)
        with self._consul_lock:
            if alloc.id in self._consul_removed:
                return  # alloc was GC'd; never re-register
            domains = self._consul_domains.setdefault(alloc.id, set())
            for task in tg.tasks:
                state = (alloc.task_states or {}).get(task.name)
                domain = f"task-{alloc.id}-{task.name}"
                if (state is not None
                        and state.state == consts.TASK_STATE_RUNNING):
                    services = task_services(
                        alloc, task, env=self._task_env(runner, alloc, task))
                    if services:
                        self.syncer.set_services(domain, services)
                        domains.add(domain)
                elif domain in domains:
                    self.syncer.remove_services(domain)
                    domains.discard(domain)
            if not domains:
                self._consul_domains.pop(alloc.id, None)

    def _task_env(self, runner, alloc: Allocation, task):
        """The task's real env (actual dir paths) for service
        interpolation; None falls back to identity-only vars."""
        if runner is None or task.name not in runner.alloc_dir.task_dirs:
            return None
        from .env import task_env_from_alloc_dir

        return task_env_from_alloc_dir(alloc, task, runner.alloc_dir)

    def _remove_alloc_services(self, alloc_id: str) -> None:
        if self.syncer is None:
            return
        with self._consul_lock:
            self._consul_removed[alloc_id] = None
            # The tombstone only needs to outlive in-flight task-state
            # callbacks for its alloc — bound the set so a long-lived
            # client with batch churn doesn't grow it forever.
            while len(self._consul_removed) > 512:
                self._consul_removed.pop(next(iter(self._consul_removed)))
            domains = self._consul_domains.pop(alloc_id, set())
        for domain in domains:
            self.syncer.remove_services(domain)

    def _alloc_sync_loop(self) -> None:
        """Batched client->server status updates (client.go:1050)."""
        while not self._stop.wait(ALLOC_SYNC_INTERVAL):
            self._flush_dirty()
        self._flush_dirty()

    def _flush_dirty(self) -> None:
        with self._dirty_lock:
            dirty = list(self._dirty_allocs.values())
            self._dirty_allocs.clear()
        if not dirty:
            return
        updates = []
        for alloc in dirty:
            sync = Allocation(
                id=alloc.id,
                client_status=alloc.client_status,
                client_description=alloc.client_description,
                task_states=alloc.task_states,
            )
            updates.append(sync)
        try:
            self.api.nodes.update_allocs(self.node.id, updates)
        except Exception:
            # Re-queue on failure
            with self._dirty_lock:
                for alloc in dirty:
                    self._dirty_allocs.setdefault(alloc.id, alloc)

    # ------------------------------------------------------------------

    def _state_path(self) -> str:
        return os.path.join(self.config.state_dir, "client_state.json")

    def _save_state_loop(self) -> None:
        interval = 1.0 if self.config.dev_mode else self.config.save_interval
        while not self._stop.wait(interval):
            self._save_state()

    def _save_state(self) -> None:
        with self._runners_lock:
            runners = list(self.alloc_runners.values())
            restored = {a: dict(h) for a, h in self._restored_handles.items()}
        alloc_entries = [r.persist() for r in runners]
        # Restored handles not yet claimed by a runner must survive
        # rewrites of the state file, or a second restart before the
        # first alloc pull would orphan their executors.
        persisted_ids = {e["alloc_id"] for e in alloc_entries}
        for alloc_id, handles in restored.items():
            if alloc_id not in persisted_ids:
                alloc_entries.append({
                    "alloc_id": alloc_id,
                    "task_runners": [
                        {"task": t, "handle_id": h} for t, h in handles.items()
                    ],
                })
        state = {
            "node_id": self.node.id,
            "secret_id": self.node.secret_id,
            "allocs": alloc_entries,
        }
        tmp = self._state_path() + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(state, f)
            os.replace(tmp, self._state_path())
        except OSError:
            self.logger.exception("failed to save client state")

    def _restore_state(self) -> None:
        try:
            with open(self._state_path()) as f:
                state = json.load(f)
        except (OSError, ValueError):
            return
        # Keep a stable node identity across restarts (client.go:496).
        self.node.id = state.get("node_id") or self.node.id
        self.node.secret_id = state.get("secret_id") or self.node.secret_id
        # Saved driver handle ids, keyed alloc id -> task name; consumed
        # when the server re-sends each alloc so TaskRunners reattach to
        # still-live executors instead of restarting tasks.
        for entry in state.get("allocs") or []:
            handles = {
                tr.get("task", ""): tr.get("handle_id", "")
                for tr in entry.get("task_runners", [])
                if tr.get("handle_id")
            }
            if handles:
                self._restored_handles[entry.get("alloc_id", "")] = handles

    # ------------------------------------------------------------------

    def stats(self) -> dict:
        with self._runners_lock:
            return {
                "node_id": self.node.id,
                "num_allocs": len(self.alloc_runners),
                "heartbeat_ttl": self.heartbeat_ttl,
            }

    # ---------------------------------------- fs + stats (HTTP-facing)

    def fs(self, alloc_id: str):
        """AllocDir for a local allocation, backing the /v1/client/fs
        endpoints (allocdir file APIs, alloc_dir.go:461-551)."""
        with self._runners_lock:
            runner = self.alloc_runners.get(alloc_id)
        if runner is None:
            raise ValueError(f"unknown allocation {alloc_id!r}")
        return runner.alloc_dir

    def host_stats(self) -> dict:
        """Host cpu/mem/disk usage (/v1/client/stats, stats/host.go)."""
        from .stats import HostStatsCollector

        if not hasattr(self, "_host_stats"):
            self._host_stats = HostStatsCollector(
                data_dirs=[self.config.alloc_dir]
            )
            self._host_stats.collect()  # prime the cpu delta
        return self._host_stats.collect()

    def alloc_stats(self, alloc_id: str) -> dict:
        """Per-task cpu/rss usage for one allocation
        (/v1/client/allocation/<id>/stats)."""
        from .stats import ProcessStatsSampler

        if not hasattr(self, "_proc_stats"):
            self._proc_stats = ProcessStatsSampler()
        with self._runners_lock:
            runner = self.alloc_runners.get(alloc_id)
        if runner is None:
            raise ValueError(f"unknown allocation {alloc_id!r}")
        tasks = {}
        for name, tr in runner.task_runners.items():
            handle = tr.handle
            usage = None
            if handle is not None:
                pid = handle.pid()
                if pid is not None:
                    usage = self._proc_stats.sample(pid)
            tasks[name] = usage
        return {"alloc_id": alloc_id, "tasks": tasks, "timestamp": time.time()}
