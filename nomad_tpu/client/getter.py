"""Artifact fetcher: download TaskArtifact sources into the task dir.

Reference: client/getter/getter.go (go-getter based) — supports URL
sources with checksum verification and automatic archive unpacking,
invoked from the task prestart phase (task_runner.go:354).

Supported schemes: http://, https://, file://, and bare local paths.
Getter options (TaskArtifact.GetterOptions):
  checksum = "<algo>:<hex>"   md5 | sha1 | sha256 | sha512
  archive  = "false"          disable auto-unpacking
"""

from __future__ import annotations

import hashlib
import os
import shutil
import tarfile
import urllib.parse
import urllib.request
import zipfile

from ..structs import TaskArtifact


class ArtifactError(Exception):
    pass


def _contained(path: str, base: str) -> bool:
    """True when abspath(path) is base or inside it. A bare
    startswith() would let sibling dirs sharing the prefix through
    (e.g. <alloc>/web2 vs base <alloc>/web)."""
    path = os.path.abspath(path)
    base = os.path.abspath(base)
    return path == base or path.startswith(base + os.sep)


def _verify_checksum(path: str, spec: str) -> None:
    try:
        algo, _, want = spec.partition(":")
        h = hashlib.new(algo.strip())
    except (ValueError, TypeError) as e:
        raise ArtifactError(f"invalid checksum spec {spec!r}: {e}") from e
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    got = h.hexdigest()
    if got != want.strip().lower():
        raise ArtifactError(
            f"checksum mismatch for {os.path.basename(path)}: "
            f"got {algo}:{got}, want {spec}"
        )


def _unpack(path: str, dest_dir: str) -> bool:
    """Auto-unpack archives the way go-getter does (by extension).
    Returns True when the file was an archive and was extracted."""
    lower = path.lower()
    if lower.endswith((".tar.gz", ".tgz", ".tar.bz2", ".tbz2", ".tar.xz", ".txz", ".tar")):
        with tarfile.open(path) as tf:
            _safe_extract_tar(tf, dest_dir)
        return True
    if lower.endswith(".zip"):
        with zipfile.ZipFile(path) as zf:
            for info in zf.infolist():
                target = os.path.join(dest_dir, info.filename)
                if not _contained(target, dest_dir):
                    raise ArtifactError(f"zip entry escapes dest: {info.filename}")
            zf.extractall(dest_dir)
        return True
    return False


def _safe_extract_tar(tf: tarfile.TarFile, dest_dir: str) -> None:
    for member in tf.getmembers():
        target = os.path.join(dest_dir, member.name)
        if not _contained(target, dest_dir):
            raise ArtifactError(f"tar entry escapes dest: {member.name}")
        if member.issym() or member.islnk():
            link_target = os.path.join(
                os.path.dirname(target), member.linkname
            )
            if not _contained(link_target, dest_dir):
                raise ArtifactError(f"tar link escapes dest: {member.name}")
    try:
        tf.extractall(dest_dir, filter="data")
    except TypeError:  # pre-3.12 tarfile without filter=
        tf.extractall(dest_dir)


def fetch_artifact(artifact: TaskArtifact, task_dir: str,
                   timeout: float = 300.0) -> str:
    """Download one artifact into task_dir/<relative_dest>. Returns the
    destination directory."""
    source = artifact.getter_source
    if not source:
        raise ArtifactError("artifact has no source")
    opts = artifact.getter_options or {}

    dest_dir = os.path.join(task_dir, artifact.relative_dest or "")
    dest_dir = os.path.abspath(dest_dir)
    if not _contained(dest_dir, task_dir):
        raise ArtifactError(f"artifact dest escapes task dir: {artifact.relative_dest}")
    os.makedirs(dest_dir, exist_ok=True)

    parsed = urllib.parse.urlparse(source)
    filename = os.path.basename(parsed.path or source) or "artifact"
    staging = os.path.join(dest_dir, f".download-{filename}")

    try:
        if parsed.scheme in ("http", "https"):
            req = urllib.request.Request(
                source, headers={"User-Agent": "nomad-tpu-getter"}
            )
            with urllib.request.urlopen(req, timeout=timeout) as resp, \
                    open(staging, "wb") as out:
                shutil.copyfileobj(resp, out)
        elif parsed.scheme == "file" or not parsed.scheme:
            src_path = parsed.path if parsed.scheme else source
            shutil.copyfile(src_path, staging)
        else:
            raise ArtifactError(f"unsupported artifact scheme {parsed.scheme!r}")

        checksum = opts.get("checksum")
        if checksum:
            _verify_checksum(staging, checksum)

        final = os.path.join(dest_dir, filename)
        if opts.get("archive") == "false" or not _unpack(staging, dest_dir):
            os.replace(staging, final)
            # Downloaded programs are usually meant to run.
            os.chmod(final, os.stat(final).st_mode | 0o755)
        else:
            os.unlink(staging)
    except ArtifactError:
        raise
    except Exception as e:  # noqa: BLE001 - network/fs errors -> typed error
        raise ArtifactError(f"failed to fetch {source!r}: {e}") from e
    finally:
        if os.path.exists(staging):
            try:
                os.unlink(staging)
            except OSError:
                pass
    return dest_dir
