"""AllocRunner: per-allocation supervisor.

Reference: client/alloc_runner.go:95 — builds the AllocDir, runs one
TaskRunner per task, aggregates task states into the alloc client
status (setTaskState:365/syncStatus:345), and handles destroy/GC.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Dict, Optional

from ..structs import Allocation, TaskState, consts, new_task_event
from .allocdir import AllocDir
from .task_runner import TaskRunner

# Ephemeral-disk usage poll cadence (alloc_dir.go:618 uses a rising
# 250ms..duration watcher; one flat interval keeps the walk cost
# predictable). Module-level so tests can shrink it.
DISK_WATCH_INTERVAL = 5.0


class AllocRunner:
    def __init__(
        self,
        alloc: Allocation,
        alloc_root: str,
        sync_cb: Callable[[Allocation], None],
        max_kill_timeout: float = 30.0,
        logger: Optional[logging.Logger] = None,
        restored_handles: Optional[Dict[str, str]] = None,
        persist_cb: Optional[Callable[[], None]] = None,
        template_kv=None,
        vault_client=None,
        previous_alloc_dir=None,
        chroot_env=None,
    ):
        self.alloc = alloc
        self.sync_cb = sync_cb
        self.max_kill_timeout = max_kill_timeout
        self.logger = logger or logging.getLogger(
            f"nomad_tpu.alloc.{alloc.id[:8]}"
        )
        self.alloc_dir = AllocDir(os.path.join(alloc_root, alloc.id))
        self.task_runners: Dict[str, TaskRunner] = {}
        self.task_states: Dict[str, TaskState] = {}
        # task name -> persisted driver handle id (reattach after client
        # restart, alloc_runner.go SaveState/RestoreState).
        self.restored_handles = restored_handles or {}
        self.persist_cb = persist_cb
        self.template_kv = template_kv
        self.vault_client = vault_client
        # Operator chroot embed map (ClientConfig.chroot_env); None =
        # allocdir defaults. Never sourced from the job spec.
        self.chroot_env = chroot_env
        # Sticky-disk handoff: a previous allocation's AllocDir whose
        # data dirs this alloc adopts before tasks start
        # (client.go:1585 addAlloc prevAllocDir).
        self.previous_alloc_dir = previous_alloc_dir
        self._lock = threading.Lock()
        self._destroyed = False

    # ------------------------------------------------------------------

    def run(self) -> None:
        tg = self.alloc.job.lookup_task_group(self.alloc.task_group) if self.alloc.job else None
        if tg is None:
            self.alloc.client_status = consts.ALLOC_CLIENT_FAILED
            self.alloc.client_description = (
                f"missing task group '{self.alloc.task_group}'"
            )
            self.sync_cb(self.alloc)
            return

        self.alloc_dir.build([t.name for t in tg.tasks])
        if self.previous_alloc_dir is not None:
            # Adopt the sticky ephemeral disk before any task starts
            # (alloc_runner.go Run -> Move semantics).
            try:
                self.alloc_dir.move(
                    self.previous_alloc_dir, [t.name for t in tg.tasks]
                )
            except OSError:
                self.logger.exception("sticky-disk move failed")
            self.previous_alloc_dir = None
        for task in tg.tasks:
            runner = TaskRunner(
                self.alloc, task, self.alloc_dir, self._on_task_state,
                self.max_kill_timeout,
                restore_handle_id=self.restored_handles.get(task.name, ""),
                persist_cb=self.persist_cb,
                template_kv=self.template_kv,
                vault_client=self.vault_client,
                chroot_env=self.chroot_env,
            )
            self.task_runners[task.name] = runner
            runner.start()
        ed = tg.ephemeral_disk
        if ed is not None and ed.size_mb:
            threading.Thread(
                target=self._disk_watcher, args=(float(ed.size_mb),),
                daemon=True, name=f"disk-watch-{self.alloc.id[:8]}",
            ).start()

    def _disk_watcher(self, limit_mb: float) -> None:
        """Enforce EphemeralDisk.SizeMB (alloc_dir.go:618 disk
        watcher): a task group writing past its quota gets every task
        killed with a disk-exceeded event and the alloc fails — the
        scheduler counted that disk on this node for OTHER allocs."""
        import time as _time

        while not self._destroyed:
            states = list(self.task_states.values())
            if states and all(
                    s.state == consts.TASK_STATE_DEAD for s in states):
                return
            used = self.alloc_dir.disk_used_mb()
            if used > limit_mb:
                self.logger.warning(
                    "ephemeral disk exceeded: %.1fMB used > %dMB limit",
                    used, limit_mb)
                ev = new_task_event(consts.TASK_EVENT_DISK_EXCEEDED)
                ev.message = (
                    f"ephemeral disk: {used:.0f}MB used exceeds "
                    f"{limit_mb:.0f}MB limit")
                for runner in self.task_runners.values():
                    runner.kill(ev, fail=True)
                return
            _time.sleep(DISK_WATCH_INTERVAL)

    def _on_task_state(self, task_name: str, state: TaskState) -> None:
        with self._lock:
            # Copy: runner keeps mutating its own state object.
            self.task_states[task_name] = TaskState(
                state=state.state,
                failed=state.failed,
                events=list(state.events),
            )
            self._sync_status()

    def _sync_status(self) -> None:
        """Aggregate task states -> alloc client status
        (alloc_runner.go:365-423)."""
        states = self.task_states.values()
        if any(s.state == consts.TASK_STATE_RUNNING for s in states):
            status = consts.ALLOC_CLIENT_RUNNING
        elif all(s.state == consts.TASK_STATE_DEAD for s in states) and states:
            if any(s.failed for s in states):
                status = consts.ALLOC_CLIENT_FAILED
            else:
                status = consts.ALLOC_CLIENT_COMPLETE
        else:
            status = consts.ALLOC_CLIENT_PENDING

        # A failed task takes the whole alloc down (leader task logic is
        # post-0.5; all tasks are peers here).
        if status == consts.ALLOC_CLIENT_FAILED:
            for name, runner in self.task_runners.items():
                st = self.task_states.get(name)
                if st is not None and st.state != consts.TASK_STATE_DEAD:
                    runner.kill(new_task_event(consts.TASK_EVENT_KILLING))

        self.alloc.client_status = status
        self.alloc.task_states = dict(self.task_states)
        self.sync_cb(self.alloc)

    # ------------------------------------------------------------------

    def update(self, alloc: Allocation) -> None:
        """Server pushed a new version of this alloc (desired status or
        in-place task updates)."""
        old_job = self.alloc.job
        self.alloc.desired_status = alloc.desired_status
        self.alloc.desired_description = alloc.desired_description
        self.alloc.alloc_modify_index = alloc.alloc_modify_index
        self.alloc.modify_index = alloc.modify_index
        if alloc.job is not None:
            self.alloc.job = alloc.job
        if alloc.desired_status in (
            consts.ALLOC_DESIRED_STOP,
            consts.ALLOC_DESIRED_EVICT,
        ):
            self.kill_tasks()
            return
        # In-place task update (the scheduler's env/meta-compatible
        # path, scheduler/util.py tasks_updated): the new job version
        # carries changed task definitions for the SAME placement —
        # push them into the live runners, which restart with the new
        # environment. Only genuinely-changed work restarts; a pure
        # desired-status ping must not bounce anything. Job- and
        # task-group-level meta render into every task's NOMAD_META_*
        # env (client/env.py) without appearing on the Task itself, so
        # a meta-only tweak restarts the whole group.
        if alloc.job is None:
            return
        tg = alloc.job.lookup_task_group(self.alloc.task_group)
        if tg is None:
            return
        old_tg = (old_job.lookup_task_group(self.alloc.task_group)
                  if old_job is not None else None)
        meta_changed = (
            old_job is None or old_tg is None
            or old_job.meta != alloc.job.meta
            or old_tg.meta != tg.meta)
        for task in tg.tasks:
            runner = self.task_runners.get(task.name)
            if runner is not None and (meta_changed
                                       or runner.task != task):
                runner.update_inplace(self.alloc, task)

    def kill_tasks(self) -> None:
        for runner in self.task_runners.values():
            runner.kill()

    def destroy(self) -> None:
        if self._destroyed:
            return
        self._destroyed = True
        self.kill_tasks()
        for runner in self.task_runners.values():
            runner.join(timeout=self.max_kill_timeout + 2.0)
        self.alloc_dir.destroy()

    def alive(self) -> bool:
        return any(
            s.state != consts.TASK_STATE_DEAD for s in self.task_states.values()
        ) or not self.task_states

    # ------------------------------------------------------------------

    def persist(self) -> dict:
        return {
            "alloc_id": self.alloc.id,
            # list() first: run() may still be adding runners.
            "task_runners": [r.persist() for r in list(self.task_runners.values())],
        }
