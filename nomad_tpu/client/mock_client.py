"""MockClient: an in-process client agent stand-in.

Registers a node, heartbeats, watches its allocations (the client pull
model, reference client/client.go:1125 watchAllocations keyed on
alloc_modify_index), and drives alloc client status pending -> running
(-> complete for batch). The real client agent (fingerprints, task
runners, drivers) lands in stage 6; this is the smallest thing that
exercises eval -> plan -> commit -> client status end-to-end
(SURVEY.md section 7 step 3).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

from .. import mock
from ..chaos import chaos
from ..state import watch
from ..structs import Node, TaskState, consts


class MockClient:
    def __init__(self, server, node: Optional[Node] = None,
                 complete_after: Optional[float] = None):
        self.server = server
        self.logger = logging.getLogger("nomad_tpu.mock_client")
        self.node = node or mock.node()
        # How long a "task" runs before completing (batch semantics);
        # None means run forever (service semantics).
        self.complete_after = complete_after
        self._stop = threading.Event()
        self._threads = []
        self._seen_index: Dict[str, int] = {}  # alloc id -> alloc_modify_index
        self._started_at: Dict[str, float] = {}
        self.heartbeat_ttl = 0.0

    # ------------------------------------------------------------------

    def start(self) -> None:
        self.node.status = consts.NODE_STATUS_INIT
        self.server.node_register(self.node)
        self.heartbeat_ttl = self.server.node_update_status(
            self.node.id, consts.NODE_STATUS_READY
        )
        for target in (self._heartbeat_loop, self._watch_allocs):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)

    # ------------------------------------------------------------------

    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            interval = max(self.heartbeat_ttl / 2.0, 0.05)
            if self._stop.wait(interval):
                return
            if chaos.enabled and chaos.fire(
                    "client.heartbeat", node=self.node.id) == "drop":
                continue  # injected heartbeat loss (see client/agent.py)
            try:
                self.heartbeat_ttl = self.server.node_heartbeat(
                    self.node.id, self.node.secret_id
                )
            except Exception:  # noqa: BLE001 - loop must survive
                self.logger.debug(
                    "heartbeat failed; retrying next tick", exc_info=True)

    def _watch_allocs(self) -> None:
        """Long-poll on this node's alloc scope; sync changed allocs'
        client status back (client.go:1125/runAllocs:1285)."""
        state = self.server.fsm.state
        items = [watch.alloc_node(self.node.id)]
        while not self._stop.is_set():
            ev = state.watch(items)
            self._sync_once()
            ev.wait(0.2)
            state.stop_watch(items, ev)

    def _sync_once(self) -> None:
        state = self.server.fsm.state
        updates = []
        now = time.time()
        for alloc in state.allocs_by_node(self.node.id):
            seen = self._seen_index.get(alloc.id, -1)
            task_names = (
                [t.name for t in alloc.job.lookup_task_group(alloc.task_group).tasks]
                if alloc.job and alloc.job.lookup_task_group(alloc.task_group)
                else ["task"]
            )
            if alloc.desired_status == consts.ALLOC_DESIRED_RUN:
                if alloc.client_status == consts.ALLOC_CLIENT_PENDING:
                    updated = alloc.copy()
                    updated.client_status = consts.ALLOC_CLIENT_RUNNING
                    updated.task_states = {
                        name: TaskState(state=consts.TASK_STATE_RUNNING)
                        for name in task_names
                    }
                    updates.append(updated)
                    self._started_at[alloc.id] = now
                elif (
                    alloc.client_status == consts.ALLOC_CLIENT_RUNNING
                    and self.complete_after is not None
                    and now - self._started_at.get(alloc.id, now)
                    >= self.complete_after
                ):
                    updated = alloc.copy()
                    updated.client_status = consts.ALLOC_CLIENT_COMPLETE
                    updated.task_states = {
                        name: TaskState(state=consts.TASK_STATE_DEAD, failed=False)
                        for name in task_names
                    }
                    updates.append(updated)
            elif alloc.desired_status in (
                consts.ALLOC_DESIRED_STOP,
                consts.ALLOC_DESIRED_EVICT,
            ):
                if alloc.client_status in (
                    consts.ALLOC_CLIENT_PENDING,
                    consts.ALLOC_CLIENT_RUNNING,
                ):
                    updated = alloc.copy()
                    updated.client_status = consts.ALLOC_CLIENT_COMPLETE
                    updated.task_states = {
                        name: TaskState(state=consts.TASK_STATE_DEAD, failed=False)
                        for name in task_names
                    }
                    updates.append(updated)
            self._seen_index[alloc.id] = alloc.alloc_modify_index
        if updates:
            try:
                self.server.node_update_allocs(updates)
            except Exception:  # noqa: BLE001 - next watch tick retries
                self.logger.debug(
                    "alloc status sync failed; retried next tick",
                    exc_info=True)
