"""Allocation directories: shared alloc dir + per-task dirs.

Reference: client/allocdir/alloc_dir.go:58 — shared `alloc/` (logs,
tmp, data) and per-task dirs with `local/` and `secrets/`, plus the
file APIs backing the HTTP fs endpoints (List/Stat/ReadAt:461-551) and
the sticky-disk migration pair Snapshot:134 / Move:194.
"""

from __future__ import annotations

import os
import shutil
import stat
import tarfile
from typing import Dict, List, Optional

SHARED_ALLOC_NAME = "alloc"
SHARED_DIRS = ("data", "logs", "tmp")
TASK_LOCAL = "local"
TASK_SECRETS = "secrets"

# Host paths a chrooted exec task sees (client/allocdir/alloc_dir.go:40
# chrootEnv): the toolchain a dynamically-linked binary needs. Embedded
# by hardlink (copy across filesystems), so the disk cost is inodes,
# not bytes.
CHROOT_ENV = {
    "/bin": "bin",
    "/sbin": "sbin",
    "/usr": "usr",
    "/lib": "lib",
    "/lib32": "lib32",
    "/lib64": "lib64",
    "/etc/ld.so.cache": "etc/ld.so.cache",
    "/etc/ld.so.conf": "etc/ld.so.conf",
    "/etc/ld.so.conf.d": "etc/ld.so.conf.d",
    "/etc/passwd": "etc/passwd",
    "/run/resolvconf": "run/resolvconf",
}


def _link_or_copy(src: str, dst: str) -> None:
    if os.path.exists(dst):
        return
    if os.path.islink(src):
        # Preserve symlinks (ld.so farms are full of them); a hardlink
        # would flatten the chain and break same-dir relative targets.
        os.symlink(os.readlink(src), dst)
        return
    try:
        os.link(src, dst)
    except OSError:
        try:
            shutil.copy2(src, dst)
        except OSError:
            pass  # unreadable host file: leave a hole, not a failure


# Agent-owned record of embedded chroot subtrees, at the alloc-dir
# ROOT — outside every task-writable tree (task filesystem views are
# confined to the task dir / shared alloc dir). The disk watcher's
# prune list loads from here, never from inside a task dir: a manifest
# the task can write would let the workload exempt its own writes from
# (or sabotage) the ephemeral-disk quota it is policed by.
EMBEDS_STATE = ".nomad-embeds.json"


def embed_rels(sources: Optional[Dict[str, str]] = None) -> List[str]:
    """Top-level destination dirs an embed of `sources` will populate —
    derivable BEFORE any linking happens, so the disk-accounting prune
    list can be recorded up front (an embed of /usr can run for
    minutes; the disk watcher must not count the half-built toolchain
    meanwhile)."""
    return sorted({rel.lstrip("/").split("/", 1)[0]
                   for rel in (sources or CHROOT_ENV).values()})


def embed_chroot(root: str,
                 sources: Optional[Dict[str, str]] = None) -> List[str]:
    """Populate `root` as a chroot by hardlinking host paths into it
    (alloc_dir.go:348 Embed). `sources` maps host path -> relative
    destination; missing host paths are skipped (not every distro has
    /lib32). Returns embed_rels(sources), for callers that record the
    prune list themselves — AllocDir.embed_chroot records it BEFORE
    invoking this."""
    rels = embed_rels(sources)
    for src, rel in (sources or CHROOT_ENV).items():
        if not os.path.exists(src):
            continue
        dst = os.path.join(root, rel.lstrip("/"))
        if os.path.isdir(src) and not os.path.islink(src):
            for dirpath, _dirnames, filenames in os.walk(src):
                relpath = os.path.relpath(dirpath, src)
                tdir = dst if relpath == "." else os.path.join(dst, relpath)
                try:
                    os.makedirs(tdir, exist_ok=True)
                except OSError:
                    continue
                for fn in filenames:
                    _link_or_copy(os.path.join(dirpath, fn),
                                  os.path.join(tdir, fn))
                # os.walk doesn't descend symlinked dirs: recreate the
                # link itself (its target is embedded on its own).
                for dn in _dirnames:
                    sp = os.path.join(dirpath, dn)
                    if os.path.islink(sp):
                        _link_or_copy(sp, os.path.join(tdir, dn))
        else:
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            _link_or_copy(src, dst)
    return rels


class AllocDir:
    def __init__(self, root: str):
        self.root = root
        self.shared_dir = os.path.join(root, SHARED_ALLOC_NAME)
        self.task_dirs: Dict[str, str] = {}
        # task name -> top-level dirs embedded into its chroot. Agent
        # state, persisted at the alloc root (EMBEDS_STATE) so a client
        # restart + reattach keeps pruning the hardlinked toolchain
        # from disk accounting instead of falsely killing the alloc.
        self._embedded: Dict[str, List[str]] = {}
        self._load_embedded()

    def _load_embedded(self) -> None:
        import json as _json

        try:
            with open(os.path.join(self.root, EMBEDS_STATE)) as f:
                data = _json.load(f)
        except (OSError, ValueError):
            return
        if isinstance(data, dict):
            self._embedded = {
                str(task): sorted(str(rel) for rel in rels)
                for task, rels in data.items()
                if isinstance(rels, list)
            }

    def embed_chroot(self, task_name: str,
                     sources: Optional[Dict[str, str]] = None) -> None:
        """Embed the chroot toolchain into `task_name`'s dir, recording
        the embedded subtrees in agent-owned state (the prune list
        disk_used_mb consumes) BEFORE the embed starts — embedding a
        host /usr can take minutes and the disk watcher polls
        meanwhile; counting the half-built toolchain would falsely
        kill the alloc. The record persists at the alloc root — never
        inside the task-writable tree."""
        import json as _json

        task_dir = self.task_dirs.get(task_name) or os.path.join(
            self.root, task_name)
        merged = set(self._embedded.get(task_name, ()))
        merged.update(embed_rels(sources))
        self._embedded[task_name] = sorted(merged)
        try:
            with open(os.path.join(self.root, EMBEDS_STATE), "w") as f:
                _json.dump(self._embedded, f)
        except OSError:
            pass  # accounting degrades; the embed still proceeds
        embed_chroot(task_dir, sources)

    def build(self, task_names: List[str]) -> None:
        os.makedirs(self.shared_dir, exist_ok=True)
        for sub in SHARED_DIRS:
            os.makedirs(os.path.join(self.shared_dir, sub), exist_ok=True)
        for name in task_names:
            task_dir = os.path.join(self.root, name)
            os.makedirs(os.path.join(task_dir, TASK_LOCAL), exist_ok=True)
            secrets = os.path.join(task_dir, TASK_SECRETS)
            os.makedirs(secrets, exist_ok=True)
            os.chmod(secrets, stat.S_IRWXU)
            self.task_dirs[name] = task_dir

    def log_dir(self) -> str:
        return os.path.join(self.shared_dir, "logs")

    def destroy(self) -> None:
        shutil.rmtree(self.root, ignore_errors=True)

    # ------------------------------- sticky-disk migration ------------

    def _migratable_roots(self) -> List[str]:
        """The dirs that travel with a sticky ephemeral disk: the shared
        `alloc/data` dir and every task's `local/` dir
        (alloc_dir.go:134-141)."""
        roots = [os.path.join(self.shared_dir, "data")]
        for path in self.task_dirs.values():
            roots.append(os.path.join(path, TASK_LOCAL))
        return roots

    def snapshot(self, fileobj) -> None:
        """Write a tar archive of the migratable dirs to `fileobj`,
        member names relative to the alloc root so the receiver can
        restore them into its own layout (alloc_dir.go:134 Snapshot).
        Symlinks are skipped, like the reference, so a task can't smuggle
        host paths to the destination node."""
        with tarfile.open(fileobj=fileobj, mode="w|") as tw:
            for root in self._migratable_roots():
                if not os.path.isdir(root):
                    continue
                for dirpath, dirnames, filenames in os.walk(root):
                    for name in dirnames + filenames:
                        full = os.path.join(dirpath, name)
                        if os.path.islink(full):
                            continue
                        rel = os.path.relpath(full, self.root)
                        tw.add(full, arcname=rel, recursive=False)

    @staticmethod
    def restore_snapshot_stream(fileobj, dest_root: str) -> "AllocDir":
        """Unpack a snapshot() archive into `dest_root`, producing a
        previous-alloc dir that move() can consume (the untar loop of
        client.go:1489-1529). Reads `fileobj` incrementally (tar stream
        mode), so a large ephemeral disk never materializes in memory —
        the reference streams too (alloc_dir.go Snapshot). Member paths
        are validated against the destination root (the reference trusts
        its peer; we don't)."""
        os.makedirs(dest_root, exist_ok=True)
        dest = os.path.normpath(dest_root)
        with tarfile.open(fileobj=fileobj, mode="r|") as tr:
            for member in tr:
                if not (member.isreg() or member.isdir()):
                    continue
                full = os.path.normpath(os.path.join(dest, member.name))
                if full != dest and not full.startswith(dest + os.sep):
                    raise PermissionError(
                        f"snapshot member escapes dest: {member.name!r}")
                if member.isdir():
                    os.makedirs(full, exist_ok=True)
                else:
                    os.makedirs(os.path.dirname(full), exist_ok=True)
                    src = tr.extractfile(member)
                    with open(full, "wb") as out:
                        shutil.copyfileobj(src, out)
        return AllocDir.from_existing(dest_root)

    @staticmethod
    def from_existing(root: str) -> "AllocDir":
        """Wrap an on-disk previous-alloc dir: non-shared top-level dirs
        are task dirs (the inverse of snapshot()'s relative layout)."""
        prev = AllocDir(root)
        for name in os.listdir(root):
            if name != SHARED_ALLOC_NAME and os.path.isdir(
                os.path.join(root, name)
            ):
                prev.task_dirs[name] = os.path.join(root, name)
        return prev

    def move(self, other: "AllocDir", task_names: List[str]) -> None:
        """Adopt `other`'s migratable data by rename: the shared data
        dir and each task's local dir (alloc_dir.go:194 Move). Call
        after build() so the destinations exist."""
        other_data = os.path.join(other.shared_dir, "data")
        data_dir = os.path.join(self.shared_dir, "data")
        if os.path.isdir(other_data):
            shutil.rmtree(data_dir, ignore_errors=True)
            try:
                os.rename(other_data, data_dir)
            except FileNotFoundError:
                # Source destroyed between the isdir check and the
                # rename (previous runner GC racing the handoff):
                # migration is best-effort, start with a fresh dir.
                os.makedirs(data_dir, exist_ok=True)
        for name in task_names:
            other_local = os.path.join(other.root, name, TASK_LOCAL)
            mine = self.task_dirs.get(name)
            if mine and os.path.isdir(other_local):
                local = os.path.join(mine, TASK_LOCAL)
                shutil.rmtree(local, ignore_errors=True)
                try:
                    os.rename(other_local, local)
                except FileNotFoundError:
                    os.makedirs(local, exist_ok=True)

    # ------------------------------ file APIs (HTTP fs endpoints) -----

    def _resolve(self, path: str) -> str:
        root = os.path.normpath(self.root)
        full = os.path.normpath(os.path.join(root, path.lstrip("/")))
        # Separator-boundary check: '/allocs/abc-evil' must not pass for
        # root '/allocs/abc'.
        if full != root and not full.startswith(root + os.sep):
            raise PermissionError(f"path escapes alloc dir: {path!r}")
        return full

    def list_dir(self, path: str) -> List[dict]:
        full = self._resolve(path)
        out = []
        for name in sorted(os.listdir(full)):
            st = os.stat(os.path.join(full, name))
            out.append(
                {
                    "name": name,
                    "is_dir": stat.S_ISDIR(st.st_mode),
                    "size": st.st_size,
                    "mod_time": st.st_mtime,
                }
            )
        return out

    def stat_file(self, path: str) -> dict:
        full = self._resolve(path)
        st = os.stat(full)
        return {
            "name": os.path.basename(full),
            "is_dir": stat.S_ISDIR(st.st_mode),
            "size": st.st_size,
            "mod_time": st.st_mtime,
        }

    def read_at(self, path: str, offset: int = 0, limit: Optional[int] = None) -> bytes:
        full = self._resolve(path)
        with open(full, "rb") as f:
            f.seek(offset)
            return f.read(limit if limit is not None else -1)

    def logs_read(
        self,
        task: str,
        ltype: str = "stdout",
        offset: int = 0,
        origin: str = "start",
        limit: Optional[int] = None,
    ) -> dict:
        """Read from the newest rotated log file `<task>.<type>.<n>` in
        the shared log dir (reference streams these via the framed
        fs_endpoint.go log API; here reads are offset-based and the
        caller re-polls with the returned offset to follow)."""
        if ltype not in ("stdout", "stderr"):
            raise ValueError(f"invalid log type {ltype!r}")
        log_dir = self.log_dir()
        prefix = f"{task}.{ltype}."
        try:
            indexes = sorted(
                int(name[len(prefix):])
                for name in os.listdir(log_dir)
                if name.startswith(prefix) and name[len(prefix):].isdigit()
            )
        except OSError:
            indexes = []
        if not indexes:
            return {"file": "", "data": b"", "offset": 0, "size": 0}
        name = f"{prefix}{indexes[-1]}"
        path = os.path.join(log_dir, name)
        size = os.path.getsize(path)
        if origin == "end":
            offset = max(0, size - offset)
        offset = min(offset, size)
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(limit if limit is not None else -1)
        return {
            "file": name,
            "data": data,
            "offset": offset + len(data),
            "size": size,
        }

    def disk_used_mb(self) -> float:
        """Bytes the ALLOCATION is charged for: everything under the
        alloc dir except the embedded chroot toolchain (those hardlinks
        consume no new disk and would blow any sane quota), with each
        inode counted once so a task can't dodge (or double-pay) the
        quota through its own hardlinks. The prune list comes from
        AGENT-OWNED state recorded when embed_chroot ran — never from
        anything inside the task-writable tree, which the policed
        workload could edit to exempt its writes or trigger a false
        kill."""
        pruned = set()
        for task_name, rels in self._embedded.items():
            task_dir = self.task_dirs.get(task_name) or os.path.join(
                self.root, task_name)
            for rel in rels:
                pruned.add(os.path.join(task_dir, rel))
        total = 0
        seen = set()
        for dirpath, dirnames, files in os.walk(self.root):
            dirnames[:] = [d for d in dirnames
                           if os.path.join(dirpath, d) not in pruned]
            for name in files:
                try:
                    st = os.lstat(os.path.join(dirpath, name))
                except OSError:
                    continue
                if st.st_nlink > 1:
                    key = (st.st_dev, st.st_ino)
                    if key in seen:
                        continue
                    seen.add(key)
                total += st.st_size
        return total / (1024 * 1024)
