"""Allocation directories: shared alloc dir + per-task dirs.

Reference: client/allocdir/alloc_dir.go:58 — shared `alloc/` (logs,
tmp, data) and per-task dirs with `local/` and `secrets/`, plus the
file APIs backing the HTTP fs endpoints (List/Stat/ReadAt:461-551).
"""

from __future__ import annotations

import os
import shutil
import stat
from typing import Dict, List, Optional

SHARED_ALLOC_NAME = "alloc"
SHARED_DIRS = ("data", "logs", "tmp")
TASK_LOCAL = "local"
TASK_SECRETS = "secrets"


class AllocDir:
    def __init__(self, root: str):
        self.root = root
        self.shared_dir = os.path.join(root, SHARED_ALLOC_NAME)
        self.task_dirs: Dict[str, str] = {}

    def build(self, task_names: List[str]) -> None:
        os.makedirs(self.shared_dir, exist_ok=True)
        for sub in SHARED_DIRS:
            os.makedirs(os.path.join(self.shared_dir, sub), exist_ok=True)
        for name in task_names:
            task_dir = os.path.join(self.root, name)
            os.makedirs(os.path.join(task_dir, TASK_LOCAL), exist_ok=True)
            secrets = os.path.join(task_dir, TASK_SECRETS)
            os.makedirs(secrets, exist_ok=True)
            os.chmod(secrets, stat.S_IRWXU)
            self.task_dirs[name] = task_dir

    def log_dir(self) -> str:
        return os.path.join(self.shared_dir, "logs")

    def destroy(self) -> None:
        shutil.rmtree(self.root, ignore_errors=True)

    # ------------------------------ file APIs (HTTP fs endpoints) -----

    def _resolve(self, path: str) -> str:
        root = os.path.normpath(self.root)
        full = os.path.normpath(os.path.join(root, path.lstrip("/")))
        # Separator-boundary check: '/allocs/abc-evil' must not pass for
        # root '/allocs/abc'.
        if full != root and not full.startswith(root + os.sep):
            raise PermissionError(f"path escapes alloc dir: {path!r}")
        return full

    def list_dir(self, path: str) -> List[dict]:
        full = self._resolve(path)
        out = []
        for name in sorted(os.listdir(full)):
            st = os.stat(os.path.join(full, name))
            out.append(
                {
                    "name": name,
                    "is_dir": stat.S_ISDIR(st.st_mode),
                    "size": st.st_size,
                    "mod_time": st.st_mtime,
                }
            )
        return out

    def stat_file(self, path: str) -> dict:
        full = self._resolve(path)
        st = os.stat(full)
        return {
            "name": os.path.basename(full),
            "is_dir": stat.S_ISDIR(st.st_mode),
            "size": st.st_size,
            "mod_time": st.st_mtime,
        }

    def read_at(self, path: str, offset: int = 0, limit: Optional[int] = None) -> bytes:
        full = self._resolve(path)
        with open(full, "rb") as f:
            f.seek(offset)
            return f.read(limit if limit is not None else -1)

    def logs_read(
        self,
        task: str,
        ltype: str = "stdout",
        offset: int = 0,
        origin: str = "start",
        limit: Optional[int] = None,
    ) -> dict:
        """Read from the newest rotated log file `<task>.<type>.<n>` in
        the shared log dir (reference streams these via the framed
        fs_endpoint.go log API; here reads are offset-based and the
        caller re-polls with the returned offset to follow)."""
        if ltype not in ("stdout", "stderr"):
            raise ValueError(f"invalid log type {ltype!r}")
        log_dir = self.log_dir()
        prefix = f"{task}.{ltype}."
        try:
            indexes = sorted(
                int(name[len(prefix):])
                for name in os.listdir(log_dir)
                if name.startswith(prefix) and name[len(prefix):].isdigit()
            )
        except OSError:
            indexes = []
        if not indexes:
            return {"file": "", "data": b"", "offset": 0, "size": 0}
        name = f"{prefix}{indexes[-1]}"
        path = os.path.join(log_dir, name)
        size = os.path.getsize(path)
        if origin == "end":
            offset = max(0, size - offset)
        offset = min(offset, size)
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(limit if limit is not None else -1)
        return {
            "file": name,
            "data": data,
            "offset": offset + len(data),
            "size": size,
        }

    def disk_used_mb(self) -> float:
        total = 0
        for dirpath, _, files in os.walk(self.root):
            for name in files:
                try:
                    total += os.path.getsize(os.path.join(dirpath, name))
                except OSError:
                    pass
        return total / (1024 * 1024)
