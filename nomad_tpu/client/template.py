"""Task template rendering and change watching.

Reference: client/consul_template.go:452 TaskTemplateManager — renders
Template blocks (inline or from a source file) into the task dir and
applies change_mode (noop | signal | restart) when a re-render changes
the output.

The template language is a small consul-template-compatible subset:

    {{ env "NAME" }}    task environment variable
    {{ key "path" }}    key/value lookup (service registry KV, see
                        client/servicereg.py; empty when missing)
    {{ file "path" }}   contents of a file (resolved in the task dir)

Values re-render on a poll loop; a change triggers the configured
change_mode with the template's splay delay (consul_template.go splay).
"""

from __future__ import annotations

import logging
import os
import re
import threading
from typing import Callable, Dict, List, Optional

from ..structs import Task, Template
from .getter import _contained

_FUNC_RE = re.compile(
    r"\{\{\s*(env|key|file)\s+\"([^\"]*)\"\s*\}\}"
)

KVFunc = Callable[[str], Optional[str]]


def render_template(text: str, env: Dict[str, str], kv: Optional[KVFunc],
                    task_dir: str = "") -> str:
    def repl(m: re.Match) -> str:
        fn, arg = m.group(1), m.group(2)
        if fn == "env":
            return env.get(arg, "")
        if fn == "key":
            if kv is None:
                return ""
            return kv(arg) or ""
        if fn == "file":
            path = arg if os.path.isabs(arg) else os.path.join(task_dir, arg)
            try:
                with open(path) as f:
                    return f.read()
            except OSError:
                return ""
        return m.group(0)

    return _FUNC_RE.sub(repl, text)


class TaskTemplateManager:
    """Renders a task's templates and watches for changes.

    on_change(mode, signal_name) is invoked (once per poll round, with
    the strongest mode among changed templates: restart > signal) after
    the splay delay.
    """

    POLL_INTERVAL = 2.0

    def __init__(
        self,
        task: Task,
        env: Dict[str, str],
        task_dir: str,
        kv: Optional[KVFunc] = None,
        on_change: Optional[Callable[[str, str], None]] = None,
        logger: Optional[logging.Logger] = None,
    ):
        self.task = task
        self.templates: List[Template] = list(task.templates or [])
        self.env = env
        self.task_dir = task_dir
        self.kv = kv
        self.on_change = on_change
        self.logger = logger or logging.getLogger("nomad_tpu.template")
        self._rendered: Dict[int, str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------

    def _source_text(self, tmpl: Template) -> str:
        if tmpl.embedded_tmpl:
            return tmpl.embedded_tmpl
        path = tmpl.source_path
        if path and not os.path.isabs(path):
            path = os.path.join(self.task_dir, path)
        try:
            with open(path) as f:
                return f.read()
        except OSError as e:
            raise ValueError(f"template source {tmpl.source_path!r}: {e}") from e

    def _dest_path(self, i: int, tmpl: Template) -> str:
        # Dest-less templates get an index-unique default so two of
        # them can't silently clobber each other's output.
        dest = tmpl.dest_path or f"rendered-{i}.tmpl"
        path = os.path.abspath(os.path.join(self.task_dir, dest))
        if not _contained(path, self.task_dir):
            raise ValueError(f"template dest escapes task dir: {tmpl.dest_path}")
        return path

    def _render_one(self, i: int, tmpl: Template) -> bool:
        """Render template i; write + return True when output changed."""
        out = render_template(
            self._source_text(tmpl), self.env, self.kv, self.task_dir
        )
        if self._rendered.get(i) == out:
            return False
        dest = self._dest_path(i, tmpl)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        tmp = dest + ".tmp"
        with open(tmp, "w") as f:
            f.write(out)
        os.replace(tmp, dest)
        self._rendered[i] = out
        return True

    def render_all(self) -> None:
        """Initial render during prestart; raises on any failure."""
        for i, tmpl in enumerate(self.templates):
            self._render_one(i, tmpl)

    # ------------------------------------------------------------------

    def start(self) -> None:
        if not self.templates:
            return
        self._thread = threading.Thread(
            target=self._watch, daemon=True,
            name=f"templates-{self.task.name}",
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _watch(self) -> None:
        while not self._stop.wait(self.POLL_INTERVAL):
            changed_modes: List[Template] = []
            for i, tmpl in enumerate(self.templates):
                try:
                    if self._render_one(i, tmpl):
                        changed_modes.append(tmpl)
                except (ValueError, OSError):
                    self.logger.exception("template re-render failed")
            if not changed_modes or self.on_change is None:
                continue
            # restart dominates signal dominates noop
            mode, signal_name, splay = "noop", "", 0.0
            for tmpl in changed_modes:
                splay = max(splay, tmpl.splay)
                if tmpl.change_mode == "restart":
                    mode = "restart"
                elif tmpl.change_mode == "signal" and mode != "restart":
                    mode, signal_name = "signal", tmpl.change_signal
            if mode == "noop":
                continue
            if splay and self._stop.wait(splay):
                return
            try:
                self.on_change(mode, signal_name)
            except Exception:
                self.logger.exception("template change handler failed")
