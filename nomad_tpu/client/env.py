"""Task environment builder: the NOMAD_* variables.

Reference: client/driver/env/env.go:487 — alloc dir, task dirs,
resources, ports, meta, alloc/task identity.
"""

from __future__ import annotations

from typing import Dict

from ..structs import Allocation, Task


def task_env_from_alloc_dir(alloc: Allocation, task: Task,
                            alloc_dir) -> Dict[str, str]:
    """Task env with the real paths from an AllocDir — the single place
    the dir layout maps into NOMAD_* vars (used by the task runner's
    start context and by consul service interpolation)."""
    import os

    from .allocdir import TASK_LOCAL, TASK_SECRETS

    task_dir = alloc_dir.task_dirs[task.name]
    return build_task_env(
        alloc, task, alloc_dir.shared_dir,
        os.path.join(task_dir, TASK_LOCAL),
        os.path.join(task_dir, TASK_SECRETS),
    )


def build_task_env(alloc: Allocation, task: Task, alloc_dir: str,
                   task_dir: str, secrets_dir: str) -> Dict[str, str]:
    env: Dict[str, str] = {
        "NOMAD_ALLOC_DIR": alloc_dir,
        "NOMAD_TASK_DIR": task_dir,
        "NOMAD_SECRETS_DIR": secrets_dir,
        "NOMAD_ALLOC_ID": alloc.id,
        "NOMAD_ALLOC_NAME": alloc.name,
        "NOMAD_ALLOC_INDEX": str(alloc.index()),
        "NOMAD_TASK_NAME": task.name,
        "NOMAD_GROUP_NAME": alloc.task_group,
        "NOMAD_JOB_NAME": alloc.job.name if alloc.job else "",
    }
    resources = alloc.task_resources.get(task.name) or task.resources
    if resources is not None:
        env["NOMAD_CPU_LIMIT"] = str(resources.cpu)
        env["NOMAD_MEMORY_LIMIT"] = str(resources.memory_mb)
        for net in resources.networks:
            env["NOMAD_IP"] = net.ip
            for port in list(net.reserved_ports) + list(net.dynamic_ports):
                label = port.label.upper().replace("-", "_")
                env[f"NOMAD_PORT_{label}"] = str(port.value)
                env[f"NOMAD_ADDR_{label}"] = f"{net.ip}:{port.value}"
    # job/task/group meta, upper-cased (env.go meta handling)
    metas = []
    if alloc.job is not None:
        metas.append(alloc.job.meta)
        tg = alloc.job.lookup_task_group(alloc.task_group)
        if tg is not None:
            metas.append(tg.meta)
    metas.append(task.meta)
    for meta in metas:
        for k, v in (meta or {}).items():
            env[f"NOMAD_META_{k.upper().replace('-', '_')}"] = v
    # User env values may reference the NOMAD_* variables built above
    # (env.go ParseAndReplace).
    from ..utils.interpolate import replace_env

    for k, v in (task.env or {}).items():
        env[k] = replace_env(str(v), env)
    return env
