"""Host and per-process resource usage.

Reference: client/stats/host.go:187 (HostStats: cpu/mem/disk/uptime,
served at /v1/client/stats) and the executor's pid-scan usage sampling
(client/driver/executor/executor.go). Linux /proc is read directly;
non-Linux hosts degrade to loadavg-only.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100
_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def _read_meminfo() -> Dict[str, int]:
    out: Dict[str, int] = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 2:
                    out[parts[0].rstrip(":")] = int(parts[1]) * 1024  # kB -> bytes
    except OSError:
        pass
    return out


def _read_cpu_times() -> Optional[List[int]]:
    try:
        with open("/proc/stat") as f:
            first = f.readline().split()
        if first and first[0] == "cpu":
            return [int(x) for x in first[1:]]
    except (OSError, ValueError):
        pass
    return None


class HostStatsCollector:
    """Samples host cpu/memory/disk; cpu% is computed between calls."""

    def __init__(self, data_dirs: Optional[List[str]] = None):
        self.data_dirs = data_dirs or []
        self._last_cpu = _read_cpu_times()
        self._last_ts = time.time()

    def collect(self) -> dict:
        now = time.time()
        mem = _read_meminfo()
        cpu_pct = 0.0
        cur = _read_cpu_times()
        if cur is not None and self._last_cpu is not None:
            delta = [c - l for c, l in zip(cur, self._last_cpu)]
            total = sum(delta)
            idle = delta[3] + (delta[4] if len(delta) > 4 else 0)  # idle+iowait
            if total > 0:
                cpu_pct = 100.0 * (total - idle) / total
        self._last_cpu = cur
        self._last_ts = now

        disks = []
        for d in self.data_dirs:
            try:
                st = os.statvfs(d)
                size = st.f_blocks * st.f_frsize
                avail = st.f_bavail * st.f_frsize
                disks.append({
                    "device": d,
                    "size": size,
                    "used": size - st.f_bfree * st.f_frsize,
                    "available": avail,
                    "used_percent": 100.0 * (size - st.f_bfree * st.f_frsize) / size if size else 0.0,
                })
            except OSError:
                pass

        try:
            load1, load5, load15 = os.getloadavg()
        except OSError:
            load1 = load5 = load15 = 0.0

        uptime = 0.0
        try:
            with open("/proc/uptime") as f:
                uptime = float(f.read().split()[0])
        except (OSError, ValueError):
            pass

        return {
            "timestamp": now,
            "cpu_percent": cpu_pct,
            "load_avg": [load1, load5, load15],
            "memory": {
                "total": mem.get("MemTotal", 0),
                "available": mem.get("MemAvailable", 0),
                "used": max(0, mem.get("MemTotal", 0) - mem.get("MemAvailable", 0)),
                "free": mem.get("MemFree", 0),
            },
            "disk_stats": disks,
            "uptime": uptime,
        }


class ProcessStatsSampler:
    """Per-pid cpu%/rss via /proc/<pid>/stat + statm; cpu% is computed
    between successive sample() calls for the same pid."""

    def __init__(self):
        self._last: Dict[int, tuple] = {}  # pid -> (proc_ticks, wall_ts)

    def sample(self, pid: int) -> Optional[dict]:
        try:
            with open(f"/proc/{pid}/stat") as f:
                fields = f.read().rsplit(")", 1)[1].split()
            utime, stime = int(fields[11]), int(fields[12])
            with open(f"/proc/{pid}/statm") as f:
                rss_pages = int(f.read().split()[1])
        except (OSError, IndexError, ValueError):
            self._last.pop(pid, None)
            return None

        ticks = utime + stime
        now = time.time()
        cpu_pct = 0.0
        last = self._last.get(pid)
        if last is not None:
            dticks, dt = ticks - last[0], now - last[1]
            if dt > 0:
                cpu_pct = 100.0 * (dticks / _CLK_TCK) / dt
        self._last[pid] = (ticks, now)
        return {
            "pid": pid,
            "cpu_percent": cpu_pct,
            "rss_bytes": rss_pages * _PAGE_SIZE,
            "cpu_ticks": ticks,
        }
