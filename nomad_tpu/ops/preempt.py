"""Dense priority preemption: victim selection and placement in one
masked pass (ROADMAP item 3; SURVEY.md build-plan stage 7).

A red-pressure cluster (admission/pressure.py) has no headroom for a
high-priority eval, so the normal feasibility mask is all-false and
the eval would block. The reference handles this with per-node
iterator walks over candidate allocs; here the whole decision runs as
ONE compiled program over the cluster:

- the host builds a ``VictimState``: per node, the V lowest-priority
  live allocations sorted priority-ascending (models/matrix.py
  ``build_victims``), with their resource/bandwidth/port footprints;
- for each ask the kernel computes, per node, the cumulative capacity
  freed by evicting the first k victims (a prefix cumsum over the
  sorted axis) and the smallest k that makes the ask fit — *victim
  choice on device*, and lowest-priority-first by construction: a
  prefix of a priority-ascending sort can never evict an alloc while
  sparing a lower-priority one on the same node;
- nodes that fit WITHOUT eviction always win (preemption scores carry
  a per-victim penalty on top of the post-eviction BestFit score), so
  the pass degenerates to the normal argmax whenever capacity exists;
- the scan carries both the claimed capacity AND the consumed-victim
  mask, so later asks in the same eval neither double-count a
  victim's capacity nor evict it twice.

The kernel returns (choice, score, n_victims) per ask; the host maps
``n_victims`` back to concrete allocations (the next n unconsumed
entries of the node's sorted candidate list — identical order by
construction) and stages them on the plan's ``node_preemptions`` leg,
which the plan applier re-verifies victim-by-victim against the
snapshot before committing eviction + placement in one raft apply
(server/plan_apply.py). A victim lost between selection and
verification (chaos site ``preempt.victim_lost``) costs a replan,
never a double-evict.

Shapes are static: N and K ride the caller's buckets, V is the fixed
``PREEMPT_MAX_VICTIMS`` — the preemption leg compiles once per bucket
and steady-state ``jit_recompiles`` stays 0 (it joins the placement
path's jit accounting in ops/binpack.py).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as _np

from .binpack import NEG_INF, _score_and_mask

# Per-node victim candidate ceiling. An ask that needs more than this
# many evictions on one node is pathological (it wants the node, not
# room on it) — the pass simply finds no fit there.
PREEMPT_MAX_VICTIMS = 8

# Score penalty per evicted victim: preemption must prefer the node
# that disrupts least, and any node that fits WITHOUT eviction beats
# any that needs one (normal fits never pay this penalty).
PREEMPT_VICTIM_PENALTY = 2.0


class VictimState(NamedTuple):
    """Per-node preemption candidates, priority-ascending along V.
    Padding slots: ok=False, prio=+inf, zero footprint."""

    res: jnp.ndarray  # [N, V, 4] victim resource footprints
    bw: jnp.ndarray  # [N, V]
    ports: jnp.ndarray  # [N, V] dynamic-port counts held
    prio: jnp.ndarray  # [N, V] job priority (f32; padding = +inf)
    ok: jnp.ndarray  # [N, V] live candidate (not padding/consumed)


def make_victim_state(res, bw, ports, prio, ok) -> VictimState:
    """HOST-side (numpy) victim state — device residency happens once,
    inside the jitted call (see binpack.make_node_state)."""
    f32 = functools.partial(_np.asarray, dtype=_np.float32)
    return VictimState(
        res=f32(res), bw=f32(bw), ports=f32(ports), prio=f32(prio),
        ok=_np.asarray(ok, bool),
    )


def _preempt_step(state, vok, victims: VictimState, ask, eval_priority,
                  config, noise):
    """One ask's combined place-or-preempt decision."""
    (ask_res, ask_bw, ask_ports, feas_row, tg_onehot, active,
     job_dh, tg_dh) = ask
    n = state.util.shape[0]
    v = victims.prio.shape[1]

    score = _score_and_mask(
        state, ask_res, ask_bw, ask_ports, feas_row, tg_onehot, job_dh,
        tg_dh, config, noise)
    normal_fit = score > NEG_INF / 2

    # Non-capacity eligibility, mirrored from _score_and_mask: a node
    # we would evict into must still satisfy constraints/readiness and
    # distinct-hosts for this ask.
    tg_cnt = jnp.sum(state.tg_count * tg_onehot[None, :], axis=1)
    elig_node = feas_row
    elig_node &= jnp.where(job_dh, state.job_count == 0, True)
    elig_node &= jnp.where(tg_dh, tg_cnt == 0, True)

    # Prefix frees over the live candidates (consumed/padding slots
    # contribute nothing and do not break the prefix).
    okf = vok.astype(jnp.float32)
    freed = jnp.cumsum(victims.res * okf[:, :, None], axis=1)  # [N,V,4]
    freed_bw = jnp.cumsum(victims.bw * okf, axis=1)  # [N,V]
    freed_ports = jnp.cumsum(victims.ports * okf, axis=1)  # [N,V]
    elig_prefix = jnp.cumprod(
        (~vok) | (victims.prio < eval_priority), axis=1).astype(bool)

    new_util = state.util + ask_res[None, :]  # [N,4]
    fits_k = jnp.all(new_util[:, None, :] - freed
                     <= state.capacity[:, None, :], axis=2)
    fits_k &= (state.bw_used + ask_bw)[:, None] - freed_bw \
        <= state.bw_avail[:, None]
    fits_k &= state.ports_free[:, None] + freed_ports >= ask_ports
    # Slot k itself must be a live, outrankable victim: a prefix ending
    # on a dead slot frees nothing the shorter prefix didn't.
    fits_k &= elig_prefix & vok

    k_star = jnp.argmax(fits_k, axis=1)  # first fitting prefix
    can_preempt = fits_k.any(axis=1) & elig_node & ~normal_fit

    take = functools.partial(jnp.take_along_axis, indices=k_star[:, None],
                             axis=1)
    freed_star = jnp.take_along_axis(
        freed, k_star[:, None, None], axis=1)[:, 0, :]  # [N,4]
    freed_bw_star = take(freed_bw)[:, 0]
    freed_ports_star = take(freed_ports)[:, 0]
    nv = take(jnp.cumsum(okf, axis=1))[:, 0]  # live victims in prefix

    # Post-eviction BestFit score with the per-victim disruption
    # penalty (binpack.py ScoreFit shape).
    util_after = new_util - freed_star
    denom = jnp.maximum(state.sched_capacity, 1.0)
    free_frac = 1.0 - util_after / denom
    fitness = 20.0 - (jnp.power(10.0, free_frac[:, 0])
                      + jnp.power(10.0, free_frac[:, 1]))
    fitness = jnp.clip(fitness, 0.0, 18.0)
    pscore = (fitness
              - config.anti_affinity_penalty
              * state.job_count.astype(jnp.float32)
              - PREEMPT_VICTIM_PENALTY * nv
              + noise)
    # Preemption is strictly last-resort PER ASK: while any node fits
    # without eviction, the eviction branch is masked out entirely —
    # BestFit's packing preference must never out-score zero
    # disruption (an empty node scores LOW on fitness by design).
    any_fit = normal_fit.any()
    total = jnp.where(normal_fit, score,
                      jnp.where(can_preempt & ~any_fit, pscore, NEG_INF))

    choice = jnp.argmax(total)
    valid = (total[choice] > NEG_INF / 2) & active
    preempted = valid & ~normal_fit[choice]
    clean_score = total[choice] - noise[choice]

    safe = jnp.where(valid, choice, n)
    d_util = ask_res - jnp.where(preempted, freed_star[choice], 0.0)
    d_bw = ask_bw - jnp.where(preempted, freed_bw_star[choice], 0.0)
    d_ports = jnp.where(preempted, freed_ports_star[choice], 0.0) - ask_ports
    new_state = state._replace(
        util=state.util.at[safe].add(d_util, mode="drop"),
        bw_used=state.bw_used.at[safe].add(d_bw, mode="drop"),
        ports_free=state.ports_free.at[safe].add(d_ports, mode="drop"),
        job_count=state.job_count.at[safe].add(1, mode="drop"),
        tg_count=state.tg_count.at[safe].add(
            tg_onehot.astype(jnp.int32), mode="drop"),
    )
    # Consume the chosen prefix's live victims.
    row = vok[jnp.clip(choice, 0, n - 1)]
    consume = preempted & (jnp.arange(v) <= k_star[jnp.clip(choice, 0, n - 1)])
    new_vok = vok.at[safe].set(row & ~consume, mode="drop")

    out_choice = jnp.where(valid, choice, -1).astype(jnp.int32)
    out_score = jnp.where(valid, clean_score, 0.0)
    out_nv = jnp.where(preempted,
                       nv[jnp.clip(choice, 0, n - 1)], 0.0).astype(jnp.int32)
    return new_state, new_vok, (out_choice, out_score, out_nv)


def preempt_placement_program(state, victims: VictimState, asks, key,
                              eval_priority, config):
    """K sequential place-or-preempt decisions as one compiled program.
    Same NodeState/Asks contract as binpack.placement_program, plus the
    victim tensor; returns (choices [K], scores [K], n_victims [K]).
    ``eval_priority`` is traced (a plain f32 scalar), so every priority
    shares one compiled program per shape bucket."""
    k_count = asks.resources.shape[0]
    n = state.util.shape[0]
    g = state.feasible.shape[1]
    noise = jax.random.uniform(
        key, (k_count, n), minval=0.0, maxval=config.noise_scale)
    tg_onehots = (jnp.arange(g)[None, :] == asks.tg_index[:, None])
    feas_rows = (jnp.take(state.feasible, asks.tg_index, axis=1).T
                 & state.node_ok[None, :])
    tg_dhs = jnp.take(asks.tg_distinct_hosts, asks.tg_index)

    def body(carry, xs):
        st, vok = carry
        (ask_res, ask_bw, ask_ports, feas_row, tg_onehot, tg_dh, active,
         noise_row) = xs
        new_st, new_vok, out = _preempt_step(
            st, vok, victims,
            (ask_res, ask_bw, ask_ports, feas_row, tg_onehot, active,
             asks.job_distinct_hosts, tg_dh),
            eval_priority, config, noise_row)
        return (new_st, new_vok), out

    (_, _), (choices, scores, n_victims) = jax.lax.scan(
        body, (state, victims.ok),
        (asks.resources, asks.bw, asks.ports, feas_rows, tg_onehots,
         tg_dhs, asks.active, noise))
    return choices, scores, n_victims


@functools.partial(jax.jit, static_argnames=("config",))
def preempt_placement_program_jit(state, victims, asks, key,
                                  eval_priority, config):
    return preempt_placement_program(state, victims, asks, key,
                                     eval_priority, config)
