"""Vectorized bin-packing placement: the TPU reformulation of the
reference's per-node iterator chain.

The reference scores candidates one node at a time through
BinPackIterator (scheduler/rank.go:161) bounded by LimitIterator
(scheduler/select.go:5). Here one evaluation's K placements run as a
`lax.scan` whose body performs the whole cluster's feasibility mask,
BestFit-v3 score, anti-affinity penalty, and masked argmax as dense
[N]-wide vector ops — one pass on the VPU instead of K x limit Python
iterations. The scan carries the proposed-usage state so placements
within an eval see each other (the reference's ProposedAllocs
semantics, scheduler/context.go:108).

Shapes are static: node count N and placement count K are bucketed by
the caller (models/matrix.py) so XLA compiles once per bucket. The
program is pure and vmap-able over a leading batch axis (independent
evals against the same snapshot = optimistic concurrency) and
shard_map-able over the node axis (parallel/mesh.py).

Port/network fidelity: dynamic-port *counts* and bandwidth are tracked
densely; exact port numbers are assigned host-side after the kernel
picks nodes, and the plan applier re-verifies every node exactly
(reference plan_apply.go:318), so a dense approximation costs at most a
retry, never correctness.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..utils.jaxcache import enable_compilation_cache

enable_compilation_cache()

# Resource dims in the dense matrices.
R_CPU, R_MEM, R_DISK, R_IOPS = 0, 1, 2, 3
NUM_RESOURCES = 4

NEG_INF = -1e30


class PlacementConfig(NamedTuple):
    """Static (compile-time) knobs."""

    anti_affinity_penalty: float  # 10 service / 5 batch (stack.go:14-18)
    # In-batch conflict pre-resolution: serialize the EVAL axis of a
    # shared-base batch on device (lax.scan instead of vmap) so eval
    # i+1 plans against the capacity/bandwidth/ports that evals 0..i
    # already claimed — the in-batch analog of the plan applier's
    # serialization (plan_apply.go:194). Without it, B evals planning
    # against one snapshot argmax toward the same headroom and the
    # applier rejects the collisions, each rejection costing a full
    # dispatch round-trip to replan. Per-JOB state (job_count/tg_count)
    # stays per-eval — distinct jobs never share anti-affinity. Only
    # the shared-base paths honor this; the mixed-base stacked path has
    # no shared capacity to carry.
    pre_resolve: bool = False
    # Per-eval tie-break noise, in FITNESS units. This is the dense
    # analog of the reference's shuffled power-of-two-choices
    # (stack.go:120-132 LimitIterator): concurrent evals planning
    # against ONE snapshot must spread across near-equally-good nodes,
    # or every eval argmaxes the same winners (BestFit gravitates to
    # the most-packed nodes) and the plan applier rejects all but the
    # first (measured: 1e-4 noise made a 60-eval 10k-node storm retry
    # 2.3x per eval on bandwidth conflicts). The reference takes the
    # best of ~log2(N) nodes drawn from a SHUFFLED feasible stream —
    # a random sample whose fitness spread on real clusters spans a
    # couple of points; 2.0 reproduces that quality band while
    # decorrelating concurrent evals.
    noise_scale: float = 2.0
    # Uniform distinct-hosts fast path: when EVERY active ask of an
    # eval is identical (one task group scaled to count=K, the storm
    # shape) AND distinct-hosts applies to it, the K sequential
    # argmax steps collapse to ONE scoring pass + top_k — placing on a
    # node never changes any OTHER node's score, and distinct-hosts
    # excludes the chosen node from the remaining asks, so the K-step
    # scan provably selects the K best-scoring feasible nodes: ~8x
    # fewer [N]-wide passes per eval. uniform_dh_flag() decides
    # eligibility host-side; the flag is compile-time like the rest of
    # the config, so each case is its own cached program.
    uniform_dh: bool = False
    # Placement kernel (nomad_tpu/kernels): which per-batch solve
    # placement_program runs. "greedy" is the native sequential
    # masked-argmax scan below; any other name resolves through the
    # kernel registry at trace time. Static (a hashable str), so each
    # kernel is its own cached XLA program and joins the batcher's
    # shape key — kernels never share a dispatch.
    kernel: str = "greedy"


class NodeState(NamedTuple):
    """Dense per-node cluster state. All arrays share leading dim N.

    util is the running utilization *including node reserved* and the
    capacity denominator subtracts reserved — exactly the reference's
    AllocsFit/ScoreFit accounting (structs/funcs.go:60,123).
    """

    capacity: jnp.ndarray  # [N, 4] total node resources
    sched_capacity: jnp.ndarray  # [N, 4] capacity - reserved (score denom)
    util: jnp.ndarray  # [N, 4] reserved + existing usage (scan-carried)
    bw_avail: jnp.ndarray  # [N] primary-device bandwidth
    bw_used: jnp.ndarray  # [N] (scan-carried)
    ports_free: jnp.ndarray  # [N] free dynamic-port count (scan-carried)
    job_count: jnp.ndarray  # [N] this job's allocs per node (scan-carried)
    tg_count: jnp.ndarray  # [N, G] per-task-group counts (scan-carried)
    feasible: jnp.ndarray  # [N, G] constraint feasibility (static mask)
    node_ok: jnp.ndarray  # [N] ready & real (not padding)


class Asks(NamedTuple):
    """The K placements to make, in order. Leading dim K."""

    resources: jnp.ndarray  # [K, 4]
    bw: jnp.ndarray  # [K]
    ports: jnp.ndarray  # [K] dynamic-port count
    tg_index: jnp.ndarray  # [K] int32 index into the G axis
    active: jnp.ndarray  # [K] bool (padding rows are inactive)
    job_distinct_hosts: jnp.ndarray  # [] bool
    tg_distinct_hosts: jnp.ndarray  # [G] bool


import numpy as _np


def make_node_state(
    capacity, sched_capacity, util, bw_avail, bw_used, ports_free,
    job_count, tg_count, feasible, node_ok,
) -> NodeState:
    """HOST-side (numpy) state. Deliberately NOT jnp: device residency
    happens once, inside the single jitted dispatch — eager jnp.asarray
    here would cost one host->device round-trip PER FIELD PER EVAL
    (ruinous through a remote-device tunnel), and the batcher must be
    able to np.stack request fields without pulling them back."""
    f32 = functools.partial(_np.asarray, dtype=_np.float32)
    return NodeState(
        capacity=f32(capacity),
        sched_capacity=f32(sched_capacity),
        util=f32(util),
        bw_avail=f32(bw_avail),
        bw_used=f32(bw_used),
        ports_free=f32(ports_free),
        job_count=_np.asarray(job_count, _np.int32),
        tg_count=_np.asarray(tg_count, _np.int32),
        feasible=_np.asarray(feasible, bool),
        node_ok=_np.asarray(node_ok, bool),
    )


def make_asks(
    resources, bw, ports, tg_index, active, job_distinct_hosts, tg_distinct_hosts
) -> Asks:
    """HOST-side (numpy) asks — see make_node_state on why."""
    return Asks(
        resources=_np.asarray(resources, _np.float32),
        bw=_np.asarray(bw, _np.float32),
        ports=_np.asarray(ports, _np.float32),
        tg_index=_np.asarray(tg_index, _np.int32),
        active=_np.asarray(active, bool),
        job_distinct_hosts=_np.asarray(job_distinct_hosts, bool),
        tg_distinct_hosts=_np.asarray(tg_distinct_hosts, bool),
    )


def check_device_chaos() -> None:
    """Host-side fault gate for device execution, called by the
    placement batcher immediately before it issues device programs.
    Armed with a ``binpack.device`` 'error' spec it raises
    ChaosInjectedError exactly as a real device/runtime fault would
    surface from the jitted call — the dense schedulers' recovery
    contract (fall back to the host iterator path, identical placement
    semantics) is exercised without needing a chip that actually
    fails. A no-op two-attribute check in production."""
    from ..chaos import chaos

    if chaos.enabled:
        chaos.fire("binpack.device")


def host_prng_key(seed: int) -> "_np.ndarray":
    """A threefry key as a HOST uint32[2] (what jax.random.PRNGKey
    yields, without the eager device transfer); jax.random accepts the
    raw layout inside jit."""
    return _np.array([0, _np.uint32(seed & 0xFFFFFFFF)], _np.uint32)


@jax.jit
def apply_base_delta(util, bw_used, ports_free, node_ok, rows,
                     util_rows, bw_rows, ports_rows, ok_rows):
    """Scatter-update the mutable arrays of a device-resident cluster
    base with recomputed node rows. Plan applies touch a handful of
    nodes; shipping those rows (a few hundred bytes) and updating on
    device beats re-uploading the full [N,4] base per snapshot — the
    device-side half of models/matrix.py's incremental delta path.
    Padding duplicates the first changed row (same value, so the
    duplicate-index scatter is benign); capacity/bandwidth-avail never
    change with allocs and keep the parent's device arrays by
    reference. node_ok rows ride the same scatter: a node-down/drain
    transition is a delta too (models/resident.py) — the row stays in
    the matrix, masked, instead of forcing a full rebuild of the node
    axis."""
    return (
        util.at[rows].set(util_rows),
        bw_used.at[rows].set(bw_rows),
        ports_free.at[rows].set(ports_rows),
        node_ok.at[rows].set(ok_rows),
    )


def _score_and_mask(state: NodeState, ask_res, ask_bw, ask_ports, feas_row,
                    tg_onehot, job_dh, tg_dh, config: PlacementConfig,
                    noise):
    """One placement's dense pass: feasibility mask + score over all N
    nodes. feas_row is the [N] constraint-feasibility column for this
    ask's task group (gathered ONCE per eval outside the scan — the
    [N, G] one-hot contraction per step was pure wasted traffic);
    tg_onehot is the [G] one-hot still used for the carried tg_count
    contraction, tg_dh the scalar distinct-hosts flag for this ask's
    group. Returns masked_score [N]."""
    new_util = state.util + ask_res[None, :]

    # AllocsFit: full capacity superset on every dimension.
    fits = jnp.all(new_util <= state.capacity, axis=1)
    # Bandwidth and dynamic-port count.
    fits &= state.bw_used + ask_bw <= state.bw_avail
    fits &= state.ports_free >= ask_ports
    # Constraint feasibility for this TG (precomputed per class) and
    # node readiness, pre-ANDed into feas_row by the caller.
    fits &= feas_row
    # distinct_hosts: job-level blocks any co-placement of the job;
    # TG-level blocks only same-TG co-placement (feasible.go:211-238).
    tg_cnt = jnp.sum(state.tg_count * tg_onehot[None, :], axis=1)
    fits &= jnp.where(job_dh, state.job_count == 0, True)
    fits &= jnp.where(tg_dh, tg_cnt == 0, True)

    # ScoreFit (BestFit-v3): packed nodes score high.
    denom = jnp.maximum(state.sched_capacity, 1.0)
    free_frac = 1.0 - new_util / denom
    fitness = 20.0 - (
        jnp.power(10.0, free_frac[:, R_CPU]) + jnp.power(10.0, free_frac[:, R_MEM])
    )
    fitness = jnp.clip(fitness, 0.0, 18.0)
    # Zero schedulable capacity scores worst (fully-reserved node).
    fitness = jnp.where(
        (state.sched_capacity[:, R_CPU] <= 0) | (state.sched_capacity[:, R_MEM] <= 0),
        0.0,
        fitness,
    )

    # Job anti-affinity (rank.go:287-299).
    score = fitness - config.anti_affinity_penalty * state.job_count.astype(jnp.float32)

    # Random tie-break: preserves the reference's shuffled-source
    # de-correlation between concurrent workers.
    score = score + noise
    return jnp.where(fits, score, NEG_INF)


def placement_step(state: NodeState, ask, config: PlacementConfig, noise):
    """Place one ask: pick the argmax-score node and update the carried
    state. Returns (new_state, (choice, score)); choice is -1 when no
    node fits or the ask row is padding.

    The state update is a single-row scatter (`.at[choice]`, OOB-drop
    for the no-fit case) instead of the old [N]-wide one-hot
    multiply-adds: the update side read+wrote every carried array per
    step, roughly half the scan body's memory traffic for work that
    touches exactly one row."""
    (ask_res, ask_bw, ask_ports, feas_row, tg_onehot, active,
     job_dh, tg_dh) = ask
    n = state.util.shape[0]

    score = _score_and_mask(
        state, ask_res, ask_bw, ask_ports, feas_row, tg_onehot, job_dh,
        tg_dh, config, noise
    )
    choice = jnp.argmax(score)
    valid = (score[choice] > NEG_INF / 2) & active
    # Reported score excludes the tie-break noise: AllocMetric must
    # carry the node's actual fitness, not the per-eval PRNG draw.
    clean_score = score[choice] - noise[choice]

    # Row n is out of range: mode="drop" makes the invalid case a no-op.
    safe = jnp.where(valid, choice, n)
    new_state = state._replace(
        util=state.util.at[safe].add(ask_res, mode="drop"),
        bw_used=state.bw_used.at[safe].add(ask_bw, mode="drop"),
        ports_free=state.ports_free.at[safe].add(-ask_ports, mode="drop"),
        job_count=state.job_count.at[safe].add(1, mode="drop"),
        tg_count=state.tg_count.at[safe].add(
            tg_onehot.astype(jnp.int32), mode="drop"),
    )
    out_choice = jnp.where(valid, choice, -1).astype(jnp.int32)
    out_score = jnp.where(valid, clean_score, 0.0)
    return new_state, (out_choice, out_score)


def _uniform_topk_program(state: NodeState, asks: Asks, key,
                          config: PlacementConfig):
    """The uniform distinct-hosts placement: ONE scoring pass + top_k
    instead of K sequential argmax steps (see PlacementConfig.
    uniform_dh for the equivalence argument). The caller guarantees
    every active ask row is identical (uniform_dh_flag); ask row 0 is
    the representative (active rows are a prefix, padding rows are
    masked by `active` exactly like the sequential path)."""
    n = state.util.shape[0]
    g = state.feasible.shape[1]
    k_count = asks.resources.shape[0]
    ask_res = asks.resources[0]
    ask_bw = asks.bw[0]
    ask_ports = asks.ports[0]
    tg_onehot = jnp.arange(g) == asks.tg_index[0]
    feas_row = jnp.any(state.feasible & tg_onehot[None, :],
                       axis=1) & state.node_ok
    tg_dh = jnp.any(asks.tg_distinct_hosts & tg_onehot)
    noise = jax.random.uniform(key, (n,), minval=0.0,
                               maxval=config.noise_scale)
    score = _score_and_mask(
        state, ask_res, ask_bw, ask_ports, feas_row, tg_onehot,
        asks.job_distinct_hosts, tg_dh, config, noise)
    # top_k requires k <= n, and the ask bucket (k_count) can pad past
    # the node bucket (n) when count > cluster size. Surplus asks can
    # never place under distinct-hosts anyway, so clamp and pad them
    # back as unplaceable — the same choice=-1 the sequential scan
    # yields once every node carries the job.
    k_eff = min(k_count, n)
    top_scores, top_idx = jax.lax.top_k(score, k_eff)
    if k_eff < k_count:
        pad = k_count - k_eff
        top_scores = jnp.concatenate(
            [top_scores, jnp.full((pad,), NEG_INF, top_scores.dtype)])
        top_idx = jnp.concatenate(
            [top_idx, jnp.zeros((pad,), top_idx.dtype)])
    valid = (top_scores > NEG_INF / 2) & asks.active
    choices = jnp.where(valid, top_idx, -1).astype(jnp.int32)
    scores_out = jnp.where(valid, top_scores - noise[top_idx], 0.0)
    # Each chosen node receives exactly one ask (distinct by top_k);
    # invalid rows scatter to row n and drop.
    safe = jnp.where(valid, top_idx, n)
    vi = valid.astype(jnp.int32)
    new_state = state._replace(
        util=state.util.at[safe].add(
            jnp.where(valid[:, None], ask_res[None, :], 0.0), mode="drop"),
        bw_used=state.bw_used.at[safe].add(
            jnp.where(valid, ask_bw, 0.0), mode="drop"),
        ports_free=state.ports_free.at[safe].add(
            jnp.where(valid, -ask_ports, 0.0), mode="drop"),
        job_count=state.job_count.at[safe].add(vi, mode="drop"),
        tg_count=state.tg_count.at[safe].add(
            vi[:, None] * tg_onehot[None, :].astype(jnp.int32),
            mode="drop"),
    )
    return choices, scores_out, new_state


def placement_program(
    state: NodeState, asks: Asks, key, config: PlacementConfig
):
    """Run K sequential placements over the cluster as one compiled
    program. Returns (choices [K] int32, scores [K] f32, final_state).

    config.kernel selects the solve: the default runs the sequential
    masked-argmax scan below; anything else resolves through the
    kernel registry (nomad_tpu/kernels) and runs in this program's
    place — same signature, same validity mask, different solve. The
    branch is on a STATIC config field, so it happens at trace time
    and every batcher path (overlay/compact/pre-resolve/fused-delta)
    carries any kernel unchanged."""
    if config.kernel != "greedy":
        from ..kernels import kernel_program

        return kernel_program(config.kernel)(state, asks, key, config)
    if config.uniform_dh:
        return _uniform_topk_program(state, asks, key, config)

    k_count = asks.resources.shape[0]
    n = state.util.shape[0]
    g = state.feasible.shape[1]
    # All tie-break noise drawn in one op; the scan consumes rows.
    noise = jax.random.uniform(
        key, (k_count, n), minval=0.0, maxval=config.noise_scale
    )
    tg_onehots = (
        jnp.arange(g)[None, :] == asks.tg_index[:, None]
    )  # [K, G]
    # Per-ask feasibility rows, gathered ONCE: the constraint mask is
    # static through the eval (only capacity/counters are carried), so
    # the per-step [N, G] contraction was pure overhead.
    feas_rows = (jnp.take(state.feasible, asks.tg_index, axis=1).T
                 & state.node_ok[None, :])  # [K, N]
    tg_dhs = jnp.take(asks.tg_distinct_hosts, asks.tg_index)  # [K]

    def body(carry, xs):
        (ask_res, ask_bw, ask_ports, feas_row, tg_onehot, tg_dh, active,
         noise_row) = xs
        new_state, out = placement_step(
            carry,
            (ask_res, ask_bw, ask_ports, feas_row, tg_onehot, active,
             asks.job_distinct_hosts, tg_dh),
            config,
            noise_row,
        )
        return new_state, out

    final_state, (choices, scores) = jax.lax.scan(
        body,
        state,
        (asks.resources, asks.bw, asks.ports, feas_rows, tg_onehots,
         tg_dhs, asks.active, noise),
    )
    return choices, scores, final_state


@functools.partial(jax.jit, static_argnames=("config",))
def placement_program_jit(state: NodeState, asks: Asks, key, config: PlacementConfig):
    return placement_program(state, asks, key, config)


@functools.partial(jax.jit, static_argnames=("config",))
def batched_placement_program(states: NodeState, asks: Asks, keys, config: PlacementConfig):
    """vmap over a leading batch axis: B independent evals planned
    against the same snapshot (optimistic concurrency — conflicts are
    caught by the plan applier, SURVEY.md section 2.4)."""
    return jax.vmap(
        lambda s, a, k: placement_program(s, a, k, config)
    )(states, asks, keys)


@functools.partial(jax.jit, static_argnames=("config",))
def batched_placement_program_shared(
    state: NodeState, asks: Asks, keys, config: PlacementConfig
):
    """Batched evals against ONE shared snapshot/ask: only the PRNG keys
    carry the batch axis, so the cluster matrix is transferred and held
    on device once — the broker drain-to-batch fast path."""
    return jax.vmap(
        lambda k: placement_program(state, asks, k, config)
    )(keys)


# vmap axes for the overlay path: the job-independent cluster base
# (capacity/util/bandwidth/ports/node_ok) is SHARED across the eval
# batch (in_axes=None — one device copy, no per-eval transfer), while
# the per-job overlay (this job's alloc counts + constraint mask) and
# the asks carry the batch axis.
_OVERLAY_STATE_AXES = NodeState(
    capacity=None, sched_capacity=None, util=None, bw_avail=None,
    bw_used=None, ports_free=None, job_count=0, tg_count=0,
    feasible=0, node_ok=None,
)
_OVERLAY_ASKS_AXES = Asks(
    resources=0, bw=0, ports=0, tg_index=0, active=0,
    job_distinct_hosts=0, tg_distinct_hosts=0,
)


def _overlay_seq(state: NodeState, asks: Asks, keys,
                 config: PlacementConfig):
    """Pre-resolving variant of the overlay batch: a lax.scan over the
    EVAL axis whose carry is the shared mutable cluster state (util,
    bw_used, ports_free), so each eval's placements see every earlier
    eval's claims — conflicts are resolved inside the dispatch instead
    of by plan-applier rejection + replan round-trips. The per-job
    overlay fields (job_count/tg_count/feasible) stay per-eval: they
    describe the eval's OWN job. Batch-padding rows scan AFTER the real
    rows, so their phantom claims never affect a real output."""

    def body(carry, xs):
        util, bw_used, ports_free = carry
        (job_count, tg_count, feasible), a, k = xs
        s = state._replace(
            util=util, bw_used=bw_used, ports_free=ports_free,
            job_count=job_count, tg_count=tg_count, feasible=feasible,
        )
        choices, scores, final = placement_program(s, a, k, config)
        return ((final.util, final.bw_used, final.ports_free),
                (choices, scores))

    carry0 = (state.util, state.bw_used, state.ports_free)
    xs = ((state.job_count, state.tg_count, state.feasible), asks, keys)
    carry, (choices, scores) = jax.lax.scan(body, carry0, xs)
    return choices, scores, carry


@functools.partial(jax.jit, static_argnames=("config",))
def batched_placement_program_overlay(
    state: NodeState, asks: Asks, keys, config: PlacementConfig
):
    """Batched evals of DIFFERENT jobs against one shared snapshot: the
    heavy [N,4] base matrices are unbatched (uploaded once per
    snapshot, cached on device by the batcher), while job_count [B,N],
    tg_count/feasible [B,N,G], asks, and keys carry the batch axis.
    This is what makes live broker-drain batches cheap: per dispatch
    only the small per-job overlays move host->device.

    With config.pre_resolve the eval axis runs as a sequential scan
    carrying claimed capacity (see _overlay_seq) instead of a vmap —
    the in-batch analog of the plan applier's serialization."""
    if config.pre_resolve:
        return _overlay_seq(state, asks, keys, config)
    return jax.vmap(
        lambda s, a, k: placement_program(s, a, k, config),
        in_axes=(_OVERLAY_STATE_AXES, _OVERLAY_ASKS_AXES, 0),
    )(state, asks, keys)


class CompactOverlay(NamedTuple):
    """Per-eval overlay in its pre-expansion form: what actually needs
    to cross host->device per request. The dense [N]/[N,G] overlays are
    rebuilt ON DEVICE from these — at 10k nodes the dense overlay is
    ~100KB x G per request, while this is a few KB:

    - feasibility = class verdicts [C, G] expanded through the base's
      device-resident class_ids [N], plus a sparse patch for rows the
      class verdict can't represent (classless nodes, escaped
      constraints);
    - job/tg counts = scatter-adds of this job's alloc row positions.

    Padding convention: row arrays pad with N (out of range) and the
    scatters drop OOB indices."""

    verdicts: jnp.ndarray  # [C, G] bool per-class feasibility
    patch_rows: jnp.ndarray  # [P] int32 node rows (pad = N)
    patch_vals: jnp.ndarray  # [P, G] bool row feasibility
    job_rows: jnp.ndarray  # [J] int32 rows of this job's allocs (pad = N)
    job_tgs: jnp.ndarray  # [J] int32 their task-group indices


def _expand_overlay(class_ids, ov: CompactOverlay, n: int, g: int):
    """Device-side overlay reconstruction (one eval)."""
    classed = class_ids >= 0
    feasible = jnp.where(
        classed[:, None],
        ov.verdicts[jnp.clip(class_ids, 0), :],
        False,
    )
    feasible = feasible.at[ov.patch_rows].set(ov.patch_vals, mode="drop")
    job_count = jnp.zeros(n, jnp.int32).at[ov.job_rows].add(1, mode="drop")
    tg_count = jnp.zeros((n, g), jnp.int32).at[ov.job_rows, ov.job_tgs].add(
        1, mode="drop")
    return feasible, job_count, tg_count


def _compact_batch(capacity, sched_capacity, util, bw_avail, bw_used,
                   ports_free, node_ok, class_ids, overlays, asks, keys,
                   config):
    n = util.shape[0]
    g = overlays.verdicts.shape[-1]

    if config.pre_resolve:
        # Sequential eval axis carrying claimed capacity (the compact
        # twin of _overlay_seq); overlays still expand on device.
        def body(carry, xs):
            u, bw, pf = carry
            ov, a, k = xs
            feasible, job_count, tg_count = _expand_overlay(
                class_ids, ov, n, g)
            s = NodeState(
                capacity=capacity, sched_capacity=sched_capacity, util=u,
                bw_avail=bw_avail, bw_used=bw, ports_free=pf,
                job_count=job_count, tg_count=tg_count, feasible=feasible,
                node_ok=node_ok,
            )
            choices, scores, final = placement_program(s, a, k, config)
            return ((final.util, final.bw_used, final.ports_free),
                    (choices, scores))

        carry, (choices, scores) = jax.lax.scan(
            body, (util, bw_used, ports_free), (overlays, asks, keys))
        return choices, scores, carry

    def one(ov, a, k):
        feasible, job_count, tg_count = _expand_overlay(class_ids, ov, n, g)
        s = NodeState(
            capacity=capacity, sched_capacity=sched_capacity, util=util,
            bw_avail=bw_avail, bw_used=bw_used, ports_free=ports_free,
            job_count=job_count, tg_count=tg_count, feasible=feasible,
            node_ok=node_ok,
        )
        return placement_program(s, a, k, config)

    return jax.vmap(
        one, in_axes=(0, _OVERLAY_ASKS_AXES, 0),
    )(overlays, asks, keys)


@functools.partial(jax.jit, static_argnames=("config",))
def batched_placement_program_compact(
    capacity, sched_capacity, util, bw_avail, bw_used, ports_free,
    node_ok, class_ids, overlays: CompactOverlay, asks: Asks, keys,
    config: PlacementConfig
):
    """The overlay path with device-side overlay expansion: the seven
    base arrays and class_ids are the device-cached cluster base
    (unbatched); `overlays` carries the batch axis on every field and
    the dense per-eval masks/counts are rebuilt on device."""
    return _compact_batch(capacity, sched_capacity, util, bw_avail,
                          bw_used, ports_free, node_ok, class_ids,
                          overlays, asks, keys, config)


@functools.partial(jax.jit, static_argnames=("config",))
def batched_placement_program_compact_delta(
    capacity, sched_capacity, util, bw_avail, bw_used, ports_free,
    node_ok, class_ids, rows, util_rows, bw_rows, ports_rows, ok_rows,
    overlays: CompactOverlay, asks: Asks, keys,
    config: PlacementConfig
):
    """Compact dispatch FUSED with a base delta-update: the mutable
    base arrays come from the (device-cached) PARENT snapshot and the
    changed rows ride this very call's arguments — deriving the child
    base costs zero extra round-trips, decisive through a remote-device
    tunnel where every RPC is ~100ms. Returns the batch results plus
    the updated (util, bw_used, ports_free, node_ok) for the batcher to
    cache under the child's token. Padding rows duplicate a real row
    (same value, so the duplicate-index scatter is benign)."""
    util2 = util.at[rows].set(util_rows)
    bw2 = bw_used.at[rows].set(bw_rows)
    ports2 = ports_free.at[rows].set(ports_rows)
    ok2 = node_ok.at[rows].set(ok_rows)
    choices, scores, final = _compact_batch(
        capacity, sched_capacity, util2, bw_avail, bw2, ports2,
        ok2, class_ids, overlays, asks, keys, config)
    return choices, scores, util2, bw2, ports2, ok2


@jax.jit
def device_resident(*arrays):
    """Identity program: makes host arrays device-resident in ONE call.
    Through a remote-device tunnel, jax.device_put pays one RPC per
    array while jitted-call arguments all ride the call itself — this
    is the cheap way to upload a cluster base."""
    return arrays


def uniform_dh_flag(placements, job_dh, tg_dh) -> bool:
    """Host-side eligibility check for PlacementConfig.uniform_dh:
    True when every placement asks for the SAME task group (identical
    resources by construction — asks are per-TG) and distinct-hosts
    applies to it (job-level, or TG-level for that group). The flag is
    static, so mixed batches never share a program with uniform ones
    (it joins the batcher's shape key via the config)."""
    if not placements:
        return False
    gi = placements[0]
    if any(p != gi for p in placements):
        return False
    return bool(job_dh) or bool(_np.asarray(tg_dh).reshape(-1)[gi])


# ------------------------------------------------------- jit accounting
#
# Every jitted entry point of the placement path, so the compile-cache
# size (programs compiled this process) is one number: steady state is
# FLAT — a growing count under load is a recompile storm (a shape
# bucket leak, an unhashable static arg, a drifting ladder) silently
# eating multi-second trace+compile stalls. Exposed via
# server.stats()["device_state"], /v1/metrics, and bench.py's
# jit_recompiles column (whose --check gate refuses dense numbers when
# it moves after warmup).

# The static mirror of _jit_entry_points() + the parallel/shard.py
# factory caches, enforced two ways: ntalint's `unregistered-jit` rule
# flags any jit/lru_cache site in ops//kernels//models//parallel/
# missing from this manifest, and tests/test_compile_surface.py diffs
# it against both the AST scan and the runtime tuple below — the
# static rule and jit_cache_size() accounting can never disagree.
NTA_JIT_ACCOUNTED = (
    "placement_program_jit",
    "batched_placement_program",
    "batched_placement_program_shared",
    "batched_placement_program_overlay",
    "batched_placement_program_compact",
    "batched_placement_program_compact_delta",
    "apply_base_delta",
    "device_resident",
    "preempt_placement_program_jit",
    "gang_placement_program_jit",
    # parallel/shard.py program factories, accounted via
    # shard_cache_size() (one compile per (mesh, pad) build key).
    "sharded_base_delta",
    "sharded_group_capacity",
)

_JIT_ENTRY_POINTS = ()


def _jit_entry_points():
    global _JIT_ENTRY_POINTS
    if not _JIT_ENTRY_POINTS:
        # The preemption leg (ops/preempt.py) and the gang leg
        # (ops/gang.py) are part of the placement path's compile
        # budget: bench.py's jit_recompiles gate must see their caches
        # too, or a preemption/gang shape leak would hide.
        from .gang import gang_placement_program_jit
        from .preempt import preempt_placement_program_jit

        _JIT_ENTRY_POINTS = (
            placement_program_jit,
            batched_placement_program,
            batched_placement_program_shared,
            batched_placement_program_overlay,
            batched_placement_program_compact,
            batched_placement_program_compact_delta,
            apply_base_delta,
            device_resident,
            preempt_placement_program_jit,
            gang_placement_program_jit,
        )
    return _JIT_ENTRY_POINTS


def jit_cache_size() -> int:
    """Total compiled-program count across the placement entry points
    (jax's per-function in-process jit cache). The defrag loop's
    global-relaxation solve (nomad_tpu/defrag/solver.py) joins the
    count: it is off the latency path, but a shape leak there would
    eat the same multi-second compile stalls — steady state is exactly
    cold+warm per live (K bucket, N) shape and then FLAT."""
    from ..defrag.solver import solve_cache_size
    from ..parallel.shard import shard_cache_size

    total = solve_cache_size() + shard_cache_size()
    for fn in _jit_entry_points():
        try:
            total += fn._cache_size()
        except Exception:  # noqa: BLE001 - accounting must never raise
            pass
    return total
