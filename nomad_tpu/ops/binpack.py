"""Vectorized bin-packing placement: the TPU reformulation of the
reference's per-node iterator chain.

The reference scores candidates one node at a time through
BinPackIterator (scheduler/rank.go:161) bounded by LimitIterator
(scheduler/select.go:5). Here one evaluation's K placements run as a
`lax.scan` whose body performs the whole cluster's feasibility mask,
BestFit-v3 score, anti-affinity penalty, and masked argmax as dense
[N]-wide vector ops — one pass on the VPU instead of K x limit Python
iterations. The scan carries the proposed-usage state so placements
within an eval see each other (the reference's ProposedAllocs
semantics, scheduler/context.go:108).

Shapes are static: node count N and placement count K are bucketed by
the caller (models/matrix.py) so XLA compiles once per bucket. The
program is pure and vmap-able over a leading batch axis (independent
evals against the same snapshot = optimistic concurrency) and
shard_map-able over the node axis (parallel/mesh.py).

Port/network fidelity: dynamic-port *counts* and bandwidth are tracked
densely; exact port numbers are assigned host-side after the kernel
picks nodes, and the plan applier re-verifies every node exactly
(reference plan_apply.go:318), so a dense approximation costs at most a
retry, never correctness.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..utils.jaxcache import enable_compilation_cache

enable_compilation_cache()

# Resource dims in the dense matrices.
R_CPU, R_MEM, R_DISK, R_IOPS = 0, 1, 2, 3
NUM_RESOURCES = 4

NEG_INF = -1e30


class PlacementConfig(NamedTuple):
    """Static (compile-time) knobs."""

    anti_affinity_penalty: float  # 10 service / 5 batch (stack.go:14-18)
    # In-batch conflict pre-resolution: serialize the EVAL axis of a
    # shared-base batch on device (lax.scan instead of vmap) so eval
    # i+1 plans against the capacity/bandwidth/ports that evals 0..i
    # already claimed — the in-batch analog of the plan applier's
    # serialization (plan_apply.go:194). Without it, B evals planning
    # against one snapshot argmax toward the same headroom and the
    # applier rejects the collisions, each rejection costing a full
    # dispatch round-trip to replan. Per-JOB state (job_count/tg_count)
    # stays per-eval — distinct jobs never share anti-affinity. Only
    # the shared-base paths honor this; the mixed-base stacked path has
    # no shared capacity to carry.
    pre_resolve: bool = False
    # Per-eval tie-break noise, in FITNESS units. This is the dense
    # analog of the reference's shuffled power-of-two-choices
    # (stack.go:120-132 LimitIterator): concurrent evals planning
    # against ONE snapshot must spread across near-equally-good nodes,
    # or every eval argmaxes the same winners (BestFit gravitates to
    # the most-packed nodes) and the plan applier rejects all but the
    # first (measured: 1e-4 noise made a 60-eval 10k-node storm retry
    # 2.3x per eval on bandwidth conflicts). The reference takes the
    # best of ~log2(N) nodes drawn from a SHUFFLED feasible stream —
    # a random sample whose fitness spread on real clusters spans a
    # couple of points; 2.0 reproduces that quality band while
    # decorrelating concurrent evals.
    noise_scale: float = 2.0


class NodeState(NamedTuple):
    """Dense per-node cluster state. All arrays share leading dim N.

    util is the running utilization *including node reserved* and the
    capacity denominator subtracts reserved — exactly the reference's
    AllocsFit/ScoreFit accounting (structs/funcs.go:60,123).
    """

    capacity: jnp.ndarray  # [N, 4] total node resources
    sched_capacity: jnp.ndarray  # [N, 4] capacity - reserved (score denom)
    util: jnp.ndarray  # [N, 4] reserved + existing usage (scan-carried)
    bw_avail: jnp.ndarray  # [N] primary-device bandwidth
    bw_used: jnp.ndarray  # [N] (scan-carried)
    ports_free: jnp.ndarray  # [N] free dynamic-port count (scan-carried)
    job_count: jnp.ndarray  # [N] this job's allocs per node (scan-carried)
    tg_count: jnp.ndarray  # [N, G] per-task-group counts (scan-carried)
    feasible: jnp.ndarray  # [N, G] constraint feasibility (static mask)
    node_ok: jnp.ndarray  # [N] ready & real (not padding)


class Asks(NamedTuple):
    """The K placements to make, in order. Leading dim K."""

    resources: jnp.ndarray  # [K, 4]
    bw: jnp.ndarray  # [K]
    ports: jnp.ndarray  # [K] dynamic-port count
    tg_index: jnp.ndarray  # [K] int32 index into the G axis
    active: jnp.ndarray  # [K] bool (padding rows are inactive)
    job_distinct_hosts: jnp.ndarray  # [] bool
    tg_distinct_hosts: jnp.ndarray  # [G] bool


import numpy as _np


def make_node_state(
    capacity, sched_capacity, util, bw_avail, bw_used, ports_free,
    job_count, tg_count, feasible, node_ok,
) -> NodeState:
    """HOST-side (numpy) state. Deliberately NOT jnp: device residency
    happens once, inside the single jitted dispatch — eager jnp.asarray
    here would cost one host->device round-trip PER FIELD PER EVAL
    (ruinous through a remote-device tunnel), and the batcher must be
    able to np.stack request fields without pulling them back."""
    f32 = functools.partial(_np.asarray, dtype=_np.float32)
    return NodeState(
        capacity=f32(capacity),
        sched_capacity=f32(sched_capacity),
        util=f32(util),
        bw_avail=f32(bw_avail),
        bw_used=f32(bw_used),
        ports_free=f32(ports_free),
        job_count=_np.asarray(job_count, _np.int32),
        tg_count=_np.asarray(tg_count, _np.int32),
        feasible=_np.asarray(feasible, bool),
        node_ok=_np.asarray(node_ok, bool),
    )


def make_asks(
    resources, bw, ports, tg_index, active, job_distinct_hosts, tg_distinct_hosts
) -> Asks:
    """HOST-side (numpy) asks — see make_node_state on why."""
    return Asks(
        resources=_np.asarray(resources, _np.float32),
        bw=_np.asarray(bw, _np.float32),
        ports=_np.asarray(ports, _np.float32),
        tg_index=_np.asarray(tg_index, _np.int32),
        active=_np.asarray(active, bool),
        job_distinct_hosts=_np.asarray(job_distinct_hosts, bool),
        tg_distinct_hosts=_np.asarray(tg_distinct_hosts, bool),
    )


def check_device_chaos() -> None:
    """Host-side fault gate for device execution, called by the
    placement batcher immediately before it issues device programs.
    Armed with a ``binpack.device`` 'error' spec it raises
    ChaosInjectedError exactly as a real device/runtime fault would
    surface from the jitted call — the dense schedulers' recovery
    contract (fall back to the host iterator path, identical placement
    semantics) is exercised without needing a chip that actually
    fails. A no-op two-attribute check in production."""
    from ..chaos import chaos

    if chaos.enabled:
        chaos.fire("binpack.device")


def host_prng_key(seed: int) -> "_np.ndarray":
    """A threefry key as a HOST uint32[2] (what jax.random.PRNGKey
    yields, without the eager device transfer); jax.random accepts the
    raw layout inside jit."""
    return _np.array([0, _np.uint32(seed & 0xFFFFFFFF)], _np.uint32)


@jax.jit
def apply_base_delta(util, bw_used, ports_free, rows,
                     util_rows, bw_rows, ports_rows):
    """Scatter-update the mutable arrays of a device-resident cluster
    base with recomputed node rows. Plan applies touch a handful of
    nodes; shipping those rows (a few hundred bytes) and updating on
    device beats re-uploading the full [N,4] base per snapshot — the
    device-side half of models/matrix.py's incremental delta path.
    Padding duplicates the first changed row (same value, so the
    duplicate-index scatter is benign); capacity/bandwidth-avail/
    node_ok never change with allocs and keep the parent's device
    arrays by reference."""
    return (
        util.at[rows].set(util_rows),
        bw_used.at[rows].set(bw_rows),
        ports_free.at[rows].set(ports_rows),
    )


def _score_and_mask(state: NodeState, ask_res, ask_bw, ask_ports, tg_onehot,
                    job_dh, tg_dh_all, config: PlacementConfig, noise):
    """One placement's dense pass: feasibility mask + score over all N
    nodes. tg_onehot is the [G] one-hot of the ask's task group —
    one-hot contractions instead of dynamic gathers keep the scan body
    free of scatter/gather ops. Returns masked_score [N]."""
    new_util = state.util + ask_res[None, :]

    # AllocsFit: full capacity superset on every dimension.
    fits = jnp.all(new_util <= state.capacity, axis=1)
    # Bandwidth and dynamic-port count.
    fits &= state.bw_used + ask_bw <= state.bw_avail
    fits &= state.ports_free >= ask_ports
    # Constraint feasibility for this TG (precomputed per class).
    fits &= jnp.any(state.feasible & tg_onehot[None, :], axis=1)
    fits &= state.node_ok
    # distinct_hosts: job-level blocks any co-placement of the job;
    # TG-level blocks only same-TG co-placement (feasible.go:211-238).
    tg_dh = jnp.any(tg_dh_all & tg_onehot)
    tg_cnt = jnp.sum(state.tg_count * tg_onehot[None, :], axis=1)
    fits &= jnp.where(job_dh, state.job_count == 0, True)
    fits &= jnp.where(tg_dh, tg_cnt == 0, True)

    # ScoreFit (BestFit-v3): packed nodes score high.
    denom = jnp.maximum(state.sched_capacity, 1.0)
    free_frac = 1.0 - new_util / denom
    fitness = 20.0 - (
        jnp.power(10.0, free_frac[:, R_CPU]) + jnp.power(10.0, free_frac[:, R_MEM])
    )
    fitness = jnp.clip(fitness, 0.0, 18.0)
    # Zero schedulable capacity scores worst (fully-reserved node).
    fitness = jnp.where(
        (state.sched_capacity[:, R_CPU] <= 0) | (state.sched_capacity[:, R_MEM] <= 0),
        0.0,
        fitness,
    )

    # Job anti-affinity (rank.go:287-299).
    score = fitness - config.anti_affinity_penalty * state.job_count.astype(jnp.float32)

    # Random tie-break: preserves the reference's shuffled-source
    # de-correlation between concurrent workers.
    score = score + noise
    return jnp.where(fits, score, NEG_INF)


def placement_step(state: NodeState, ask, config: PlacementConfig, noise):
    """Place one ask: pick the argmax-score node and update the carried
    state. Returns (new_state, (choice, score)); choice is -1 when no
    node fits or the ask row is padding."""
    ask_res, ask_bw, ask_ports, tg_onehot, active, job_dh, tg_dh_all = ask
    n = state.util.shape[0]

    score = _score_and_mask(
        state, ask_res, ask_bw, ask_ports, tg_onehot, job_dh, tg_dh_all, config, noise
    )
    choice = jnp.argmax(score)
    valid = (score[choice] > NEG_INF / 2) & active
    # Reported score excludes the tie-break noise: AllocMetric must
    # carry the node's actual fitness, not the per-eval PRNG draw.
    clean_score = score[choice] - noise[choice]

    onehot = (jnp.arange(n) == choice) & valid
    onehot_f = onehot.astype(jnp.float32)
    onehot_i = onehot.astype(jnp.int32)

    new_state = state._replace(
        util=state.util + onehot_f[:, None] * ask_res[None, :],
        bw_used=state.bw_used + onehot_f * ask_bw,
        ports_free=state.ports_free - onehot_f * ask_ports,
        job_count=state.job_count + onehot_i,
        tg_count=state.tg_count
        + onehot_i[:, None] * tg_onehot[None, :].astype(jnp.int32),
    )
    out_choice = jnp.where(valid, choice, -1).astype(jnp.int32)
    out_score = jnp.where(valid, clean_score, 0.0)
    return new_state, (out_choice, out_score)


def placement_program(
    state: NodeState, asks: Asks, key, config: PlacementConfig
):
    """Run K sequential placements over the cluster as one compiled
    program. Returns (choices [K] int32, scores [K] f32, final_state)."""

    k_count = asks.resources.shape[0]
    n = state.util.shape[0]
    g = state.feasible.shape[1]
    # All tie-break noise drawn in one op; the scan consumes rows.
    noise = jax.random.uniform(
        key, (k_count, n), minval=0.0, maxval=config.noise_scale
    )
    tg_onehots = (
        jnp.arange(g)[None, :] == asks.tg_index[:, None]
    )  # [K, G]

    def body(carry, xs):
        ask_res, ask_bw, ask_ports, tg_onehot, active, noise_row = xs
        new_state, out = placement_step(
            carry,
            (ask_res, ask_bw, ask_ports, tg_onehot, active,
             asks.job_distinct_hosts, asks.tg_distinct_hosts),
            config,
            noise_row,
        )
        return new_state, out

    final_state, (choices, scores) = jax.lax.scan(
        body,
        state,
        (asks.resources, asks.bw, asks.ports, tg_onehots, asks.active, noise),
    )
    return choices, scores, final_state


@functools.partial(jax.jit, static_argnames=("config",))
def placement_program_jit(state: NodeState, asks: Asks, key, config: PlacementConfig):
    return placement_program(state, asks, key, config)


@functools.partial(jax.jit, static_argnames=("config",))
def batched_placement_program(states: NodeState, asks: Asks, keys, config: PlacementConfig):
    """vmap over a leading batch axis: B independent evals planned
    against the same snapshot (optimistic concurrency — conflicts are
    caught by the plan applier, SURVEY.md section 2.4)."""
    return jax.vmap(
        lambda s, a, k: placement_program(s, a, k, config)
    )(states, asks, keys)


@functools.partial(jax.jit, static_argnames=("config",))
def batched_placement_program_shared(
    state: NodeState, asks: Asks, keys, config: PlacementConfig
):
    """Batched evals against ONE shared snapshot/ask: only the PRNG keys
    carry the batch axis, so the cluster matrix is transferred and held
    on device once — the broker drain-to-batch fast path."""
    return jax.vmap(
        lambda k: placement_program(state, asks, k, config)
    )(keys)


# vmap axes for the overlay path: the job-independent cluster base
# (capacity/util/bandwidth/ports/node_ok) is SHARED across the eval
# batch (in_axes=None — one device copy, no per-eval transfer), while
# the per-job overlay (this job's alloc counts + constraint mask) and
# the asks carry the batch axis.
_OVERLAY_STATE_AXES = NodeState(
    capacity=None, sched_capacity=None, util=None, bw_avail=None,
    bw_used=None, ports_free=None, job_count=0, tg_count=0,
    feasible=0, node_ok=None,
)
_OVERLAY_ASKS_AXES = Asks(
    resources=0, bw=0, ports=0, tg_index=0, active=0,
    job_distinct_hosts=0, tg_distinct_hosts=0,
)


def _overlay_seq(state: NodeState, asks: Asks, keys,
                 config: PlacementConfig):
    """Pre-resolving variant of the overlay batch: a lax.scan over the
    EVAL axis whose carry is the shared mutable cluster state (util,
    bw_used, ports_free), so each eval's placements see every earlier
    eval's claims — conflicts are resolved inside the dispatch instead
    of by plan-applier rejection + replan round-trips. The per-job
    overlay fields (job_count/tg_count/feasible) stay per-eval: they
    describe the eval's OWN job. Batch-padding rows scan AFTER the real
    rows, so their phantom claims never affect a real output."""

    def body(carry, xs):
        util, bw_used, ports_free = carry
        (job_count, tg_count, feasible), a, k = xs
        s = state._replace(
            util=util, bw_used=bw_used, ports_free=ports_free,
            job_count=job_count, tg_count=tg_count, feasible=feasible,
        )
        choices, scores, final = placement_program(s, a, k, config)
        return ((final.util, final.bw_used, final.ports_free),
                (choices, scores))

    carry0 = (state.util, state.bw_used, state.ports_free)
    xs = ((state.job_count, state.tg_count, state.feasible), asks, keys)
    carry, (choices, scores) = jax.lax.scan(body, carry0, xs)
    return choices, scores, carry


@functools.partial(jax.jit, static_argnames=("config",))
def batched_placement_program_overlay(
    state: NodeState, asks: Asks, keys, config: PlacementConfig
):
    """Batched evals of DIFFERENT jobs against one shared snapshot: the
    heavy [N,4] base matrices are unbatched (uploaded once per
    snapshot, cached on device by the batcher), while job_count [B,N],
    tg_count/feasible [B,N,G], asks, and keys carry the batch axis.
    This is what makes live broker-drain batches cheap: per dispatch
    only the small per-job overlays move host->device.

    With config.pre_resolve the eval axis runs as a sequential scan
    carrying claimed capacity (see _overlay_seq) instead of a vmap —
    the in-batch analog of the plan applier's serialization."""
    if config.pre_resolve:
        return _overlay_seq(state, asks, keys, config)
    return jax.vmap(
        lambda s, a, k: placement_program(s, a, k, config),
        in_axes=(_OVERLAY_STATE_AXES, _OVERLAY_ASKS_AXES, 0),
    )(state, asks, keys)


class CompactOverlay(NamedTuple):
    """Per-eval overlay in its pre-expansion form: what actually needs
    to cross host->device per request. The dense [N]/[N,G] overlays are
    rebuilt ON DEVICE from these — at 10k nodes the dense overlay is
    ~100KB x G per request, while this is a few KB:

    - feasibility = class verdicts [C, G] expanded through the base's
      device-resident class_ids [N], plus a sparse patch for rows the
      class verdict can't represent (classless nodes, escaped
      constraints);
    - job/tg counts = scatter-adds of this job's alloc row positions.

    Padding convention: row arrays pad with N (out of range) and the
    scatters drop OOB indices."""

    verdicts: jnp.ndarray  # [C, G] bool per-class feasibility
    patch_rows: jnp.ndarray  # [P] int32 node rows (pad = N)
    patch_vals: jnp.ndarray  # [P, G] bool row feasibility
    job_rows: jnp.ndarray  # [J] int32 rows of this job's allocs (pad = N)
    job_tgs: jnp.ndarray  # [J] int32 their task-group indices


def _expand_overlay(class_ids, ov: CompactOverlay, n: int, g: int):
    """Device-side overlay reconstruction (one eval)."""
    classed = class_ids >= 0
    feasible = jnp.where(
        classed[:, None],
        ov.verdicts[jnp.clip(class_ids, 0), :],
        False,
    )
    feasible = feasible.at[ov.patch_rows].set(ov.patch_vals, mode="drop")
    job_count = jnp.zeros(n, jnp.int32).at[ov.job_rows].add(1, mode="drop")
    tg_count = jnp.zeros((n, g), jnp.int32).at[ov.job_rows, ov.job_tgs].add(
        1, mode="drop")
    return feasible, job_count, tg_count


def _compact_batch(capacity, sched_capacity, util, bw_avail, bw_used,
                   ports_free, node_ok, class_ids, overlays, asks, keys,
                   config):
    n = util.shape[0]
    g = overlays.verdicts.shape[-1]

    if config.pre_resolve:
        # Sequential eval axis carrying claimed capacity (the compact
        # twin of _overlay_seq); overlays still expand on device.
        def body(carry, xs):
            u, bw, pf = carry
            ov, a, k = xs
            feasible, job_count, tg_count = _expand_overlay(
                class_ids, ov, n, g)
            s = NodeState(
                capacity=capacity, sched_capacity=sched_capacity, util=u,
                bw_avail=bw_avail, bw_used=bw, ports_free=pf,
                job_count=job_count, tg_count=tg_count, feasible=feasible,
                node_ok=node_ok,
            )
            choices, scores, final = placement_program(s, a, k, config)
            return ((final.util, final.bw_used, final.ports_free),
                    (choices, scores))

        carry, (choices, scores) = jax.lax.scan(
            body, (util, bw_used, ports_free), (overlays, asks, keys))
        return choices, scores, carry

    def one(ov, a, k):
        feasible, job_count, tg_count = _expand_overlay(class_ids, ov, n, g)
        s = NodeState(
            capacity=capacity, sched_capacity=sched_capacity, util=util,
            bw_avail=bw_avail, bw_used=bw_used, ports_free=ports_free,
            job_count=job_count, tg_count=tg_count, feasible=feasible,
            node_ok=node_ok,
        )
        return placement_program(s, a, k, config)

    return jax.vmap(
        one, in_axes=(0, _OVERLAY_ASKS_AXES, 0),
    )(overlays, asks, keys)


@functools.partial(jax.jit, static_argnames=("config",))
def batched_placement_program_compact(
    capacity, sched_capacity, util, bw_avail, bw_used, ports_free,
    node_ok, class_ids, overlays: CompactOverlay, asks: Asks, keys,
    config: PlacementConfig
):
    """The overlay path with device-side overlay expansion: the seven
    base arrays and class_ids are the device-cached cluster base
    (unbatched); `overlays` carries the batch axis on every field and
    the dense per-eval masks/counts are rebuilt on device."""
    return _compact_batch(capacity, sched_capacity, util, bw_avail,
                          bw_used, ports_free, node_ok, class_ids,
                          overlays, asks, keys, config)


@functools.partial(jax.jit, static_argnames=("config",))
def batched_placement_program_compact_delta(
    capacity, sched_capacity, util, bw_avail, bw_used, ports_free,
    node_ok, class_ids, rows, util_rows, bw_rows, ports_rows,
    overlays: CompactOverlay, asks: Asks, keys,
    config: PlacementConfig
):
    """Compact dispatch FUSED with a base delta-update: the mutable
    base arrays come from the (device-cached) PARENT snapshot and the
    changed rows ride this very call's arguments — deriving the child
    base costs zero extra round-trips, decisive through a remote-device
    tunnel where every RPC is ~100ms. Returns the batch results plus
    the updated (util, bw_used, ports_free) for the batcher to cache
    under the child's token. Padding rows duplicate a real row (same
    value, so the duplicate-index scatter is benign)."""
    util2 = util.at[rows].set(util_rows)
    bw2 = bw_used.at[rows].set(bw_rows)
    ports2 = ports_free.at[rows].set(ports_rows)
    choices, scores, final = _compact_batch(
        capacity, sched_capacity, util2, bw_avail, bw2, ports2,
        node_ok, class_ids, overlays, asks, keys, config)
    return choices, scores, util2, bw2, ports2


@jax.jit
def device_resident(*arrays):
    """Identity program: makes host arrays device-resident in ONE call.
    Through a remote-device tunnel, jax.device_put pays one RPC per
    array while jitted-call arguments all ride the call itself — this
    is the cheap way to upload a cluster base."""
    return arrays
