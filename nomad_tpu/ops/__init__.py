from .binpack import PlacementConfig, placement_program, make_node_state, make_asks

__all__ = ["PlacementConfig", "placement_program", "make_node_state", "make_asks"]
