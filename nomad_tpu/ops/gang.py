"""Dense all-K gang placement over the node-topology tensor.

A gang (structs/job.py ``Gang``) is a task group of count K that
places ATOMICALLY: all K members or none. The reference scheduler has
no such mode — DL-shaped workloads (Tesserae, PAPERS.md) get it here
as one compiled program over the cluster:

- **per-node fit mask -> member capacity**: how many gang members each
  node could hold (min over resource dims of floor(free/ask), bounded
  by bandwidth/ports/feasibility; clamped to 1 under distinct-hosts);
- **topology-group cumulative capacity**: member capacities scatter-add
  by the node-topology id column (models/topology.py) into per-group
  totals — the dense form of "does any rack fit the whole gang?";
- **slice selection**: among groups whose capacity covers all K, pick
  the TIGHTEST sufficient slice (smallest covering capacity, noise
  tie-broken) — a gang should consume the fragment that fits it, not
  crack open the emptiest rack (the BestFit ethos at rack granularity);
- **member assignment**: a K-step masked-argmax scan restricted to the
  chosen slice (or spread/affinity-masked for those modes), carrying
  claimed capacity and per-group member counts;
- **all-K enforcement ON DEVICE**: if any member came back unplaced,
  every choice is rewritten to -1 — a partial gang never leaves the
  device.

Modes (static, from the gang stanza): ``slice`` (hard contiguity),
``spread`` (≤ ceil(K / eligible groups) members per group),
``affinity`` (soft co-location bonus), ``free`` (atomicity only).

Shapes are static — N and K ride the caller's buckets and the
topology-group axis rides TOPO_GROUP_BUCKETS (models/topology.py) —
so the gang leg compiles once per (bucket, config) and steady-state
``jit_recompiles`` stays 0 (it joins the placement path's jit
accounting in ops/binpack.py).

The host twin lives in nomad_tpu/gang/host.py; the plan applier's
per-node verification plus the ``Plan.gang_groups`` atomicity leg
(server/plan_apply.py) make any device approximation cost a replan,
never a partial commit.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as _np

from .binpack import NEG_INF

GANG_MODE_SLICE = "slice"
GANG_MODE_SPREAD = "spread"
GANG_MODE_AFFINITY = "affinity"
GANG_MODE_FREE = "free"

# Soft co-location bonus per already-placed gang member in the node's
# topology group (affinity mode). Half the service anti-affinity
# penalty: co-location should steer ties, not overpower fit quality.
GANG_AFFINITY_BONUS = 5.0


class GangConfig(NamedTuple):
    """Static (compile-time) gang-program knobs. ``g_pad`` is the
    bucketed topology-group axis size (TOPO_GROUP_BUCKETS) — part of
    the compiled shape like the node bucket."""

    anti_affinity_penalty: float
    mode: str = GANG_MODE_FREE
    distinct_hosts: bool = False
    g_pad: int = 16
    noise_scale: float = 2.0


class GangState(NamedTuple):
    """Dense per-node inputs for one gang dispatch. All [N] unless
    noted. HOST-side numpy by convention (binpack.make_node_state):
    device residency happens once, inside the jitted call."""

    capacity: jnp.ndarray  # [N, 4]
    sched_capacity: jnp.ndarray  # [N, 4]
    util: jnp.ndarray  # [N, 4]
    bw_avail: jnp.ndarray  # [N]
    bw_used: jnp.ndarray  # [N]
    ports_free: jnp.ndarray  # [N]
    feas_row: jnp.ndarray  # [N] bool: gang TG feasibility & node_ok
    job_count: jnp.ndarray  # [N] this job's allocs (anti-affinity)
    dh_presence: jnp.ndarray  # [N] existing same-host conflicts under
    #                            distinct-hosts (zeros when dh off)
    topo_ids: jnp.ndarray  # [N] topology group id (-1 = excluded)


def make_gang_state(capacity, sched_capacity, util, bw_avail, bw_used,
                    ports_free, feas_row, job_count, dh_presence,
                    topo_ids) -> GangState:
    f32 = functools.partial(_np.asarray, dtype=_np.float32)
    return GangState(
        capacity=f32(capacity), sched_capacity=f32(sched_capacity),
        util=f32(util), bw_avail=f32(bw_avail), bw_used=f32(bw_used),
        ports_free=f32(ports_free),
        feas_row=_np.asarray(feas_row, bool),
        job_count=_np.asarray(job_count, _np.int32),
        dh_presence=_np.asarray(dh_presence, _np.int32),
        topo_ids=_np.asarray(topo_ids, _np.int32),
    )


def _member_units(state: GangState, ask_res, ask_bw, ask_ports,
                  config: GangConfig):
    """[N] f32: how many gang members each node can hold from its
    current free capacity. 0 on infeasible/excluded nodes."""
    big = 1e9
    free = state.capacity - state.util  # [N, 4]
    per_dim = jnp.where(ask_res[None, :] > 0,
                        jnp.floor(free / jnp.maximum(ask_res[None, :],
                                                     1e-9)),
                        big)
    units = jnp.min(per_dim, axis=1)
    units = jnp.minimum(units, jnp.where(
        ask_bw > 0,
        jnp.floor((state.bw_avail - state.bw_used)
                  / jnp.maximum(ask_bw, 1e-9)),
        big))
    units = jnp.minimum(units, jnp.where(
        ask_ports > 0,
        jnp.floor(state.ports_free / jnp.maximum(ask_ports, 1e-9)),
        big))
    units = jnp.maximum(units, 0.0)
    units = jnp.where(state.feas_row, units, 0.0)
    if config.distinct_hosts:
        units = jnp.minimum(units, 1.0)
        units = jnp.where(state.dh_presence > 0, 0.0, units)
    if config.mode == GANG_MODE_SLICE:
        # Nodes without a topology id can never prove contiguity.
        units = jnp.where(state.topo_ids >= 0, units, 0.0)
    return units


def _group_capacity(units, topo_ids, g_pad):
    """[g_pad] f32 member capacity per topology group; ids < 0 scatter
    out of range and drop. Under shard_map the inputs are one node-axis
    SHARD of the fleet and the result is the shard's PARTIAL group
    capacity — parallel/shard.py sharded_group_capacity psums the
    partials (a gang slice can span shards)."""
    safe_ids = jnp.where(topo_ids >= 0, topo_ids, g_pad)
    return jnp.zeros(g_pad, jnp.float32).at[safe_ids].add(
        units, mode="drop")


def gang_placement_program(state: GangState, ask_res, ask_bw, ask_ports,
                           active, key, config: GangConfig):
    """Place one gang of K uniform members. ``active`` is the [K]
    padded member mask (binpack Asks convention). Returns
    (choices [K] int32, scores [K] f32, slice_group [] int32):
    choices are ALL >= 0 (a full gang) or ALL -1 (whole-gang reject);
    slice_group is the chosen topology group id (-1 when the mode has
    no slice or nothing placed)."""
    n = state.util.shape[0]
    k = active.shape[0]
    g_pad = config.g_pad
    k_actual = jnp.sum(active.astype(jnp.float32))

    # One uniform draw per (member, node) + one per group, all from the
    # caller's host key (binpack.host_prng_key layout).
    noise = jax.random.uniform(
        key, (k, n), minval=0.0, maxval=config.noise_scale)
    group_noise = jax.random.uniform(
        jax.random.fold_in(key, 1), (g_pad,), minval=0.0, maxval=1.0)

    units = _member_units(state, ask_res, ask_bw, ask_ports, config)
    group_cap = _group_capacity(units, state.topo_ids, g_pad)

    # ---- slice selection: tightest covering group, noise tie-broken.
    chosen_group = jnp.int32(-1)
    slice_mask = jnp.ones(n, bool)
    if config.mode == GANG_MODE_SLICE:
        covers = group_cap >= k_actual
        # Smaller sufficient capacity scores higher; noise < 1 breaks
        # exact-capacity ties without reordering distinct capacities.
        gscore = jnp.where(covers, -group_cap + group_noise, NEG_INF)
        best = jnp.argmax(gscore)
        any_group = gscore[best] > NEG_INF / 2
        chosen_group = jnp.where(any_group, best, -1).astype(jnp.int32)
        # A -1 sentinel must match NOTHING: compare against g_pad + 1
        # (no real id) when no group covers the gang.
        match = jnp.where(any_group, best, g_pad + 1)
        slice_mask = state.topo_ids == match

    # ---- spread cap: at most ceil(K / eligible groups) per group.
    spread_cap = jnp.float32(k)
    if config.mode == GANG_MODE_SPREAD:
        eligible = jnp.maximum(jnp.sum((group_cap >= 1.0)
                                       .astype(jnp.float32)), 1.0)
        spread_cap = jnp.ceil(k_actual / eligible)

    safe_ids = jnp.where(state.topo_ids >= 0, state.topo_ids, g_pad)

    def body(carry, xs):
        util, bw_used, ports_free, placed, group_members = carry
        member_active, noise_row = xs

        new_util = util + ask_res[None, :]
        fits = jnp.all(new_util <= state.capacity, axis=1)
        fits &= bw_used + ask_bw <= state.bw_avail
        fits &= ports_free >= ask_ports
        fits &= state.feas_row
        fits &= slice_mask
        if config.distinct_hosts:
            fits &= (placed == 0) & (state.dh_presence == 0)
        if config.mode == GANG_MODE_SPREAD:
            gcount = group_members[jnp.clip(safe_ids, 0, g_pad - 1)]
            fits &= jnp.where(state.topo_ids >= 0,
                              gcount < spread_cap, True)

        denom = jnp.maximum(state.sched_capacity, 1.0)
        free_frac = 1.0 - new_util / denom
        fitness = 20.0 - (jnp.power(10.0, free_frac[:, 0])
                          + jnp.power(10.0, free_frac[:, 1]))
        fitness = jnp.clip(fitness, 0.0, 18.0)
        fitness = jnp.where(
            (state.sched_capacity[:, 0] <= 0)
            | (state.sched_capacity[:, 1] <= 0), 0.0, fitness)
        score = fitness - config.anti_affinity_penalty * (
            state.job_count + placed).astype(jnp.float32)
        if config.mode == GANG_MODE_AFFINITY:
            gcount = group_members[jnp.clip(safe_ids, 0, g_pad - 1)]
            score = score + GANG_AFFINITY_BONUS * jnp.where(
                state.topo_ids >= 0, gcount, 0.0)
        score = score + noise_row
        score = jnp.where(fits, score, NEG_INF)

        choice = jnp.argmax(score)
        valid = (score[choice] > NEG_INF / 2) & member_active
        clean = score[choice] - noise_row[choice]
        safe = jnp.where(valid, choice, n)
        gid = safe_ids[jnp.clip(choice, 0, n - 1)]
        gsafe = jnp.where(valid & (gid < g_pad), gid, g_pad)
        carry = (
            util.at[safe].add(ask_res, mode="drop"),
            bw_used.at[safe].add(ask_bw, mode="drop"),
            ports_free.at[safe].add(-ask_ports, mode="drop"),
            placed.at[safe].add(1, mode="drop"),
            group_members.at[gsafe].add(1.0, mode="drop"),
        )
        out_choice = jnp.where(valid, choice, -1).astype(jnp.int32)
        out_score = jnp.where(valid, clean, 0.0)
        return carry, (out_choice, out_score)

    carry0 = (state.util, state.bw_used, state.ports_free,
              jnp.zeros(n, jnp.int32), jnp.zeros(g_pad, jnp.float32))
    _, (choices, scores) = jax.lax.scan(
        body, carry0, (active, noise))

    # ---- all-K enforcement: a partial gang never leaves the device.
    all_placed = jnp.all(jnp.where(active, choices >= 0, True))
    choices = jnp.where(all_placed, choices, -1).astype(jnp.int32)
    scores = jnp.where(all_placed, scores, 0.0)
    slice_group = jnp.where(
        all_placed, chosen_group, -1).astype(jnp.int32)
    return choices, scores, slice_group


@functools.partial(jax.jit, static_argnames=("config",))
def gang_placement_program_jit(state: GangState, ask_res, ask_bw,
                               ask_ports, active, key,
                               config: GangConfig):
    return gang_placement_program(state, ask_res, ask_bw, ask_ports,
                                  active, key, config)
