from .parse import parse, parse_file
from .hcl import HCLParseError, parse_hcl

__all__ = ["parse", "parse_file", "parse_hcl", "HCLParseError"]
