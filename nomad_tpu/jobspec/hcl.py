"""A small HCL (HashiCorp Configuration Language v1) reader.

Supports the subset job specs use (reference jobspec/parse.go consumes
hashicorp/hcl): blocks with 0+ string labels, `key = value` attributes,
strings/numbers/bools/lists/objects, `#`, `//` and `/* */` comments.
Repeated blocks accumulate into lists.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class HCLParseError(ValueError):
    def __init__(self, message: str, line: int):
        super().__init__(f"line {line}: {message}")
        self.line = line


# ---------------------------------------------------------------- lexer

_PUNCT = {"{", "}", "[", "]", "=", ","}


def _tokenize(src: str) -> List[Tuple[str, Any, int]]:
    """Returns (kind, value, line) tokens. Kinds: punct, string, number,
    bool, ident."""
    tokens: List[Tuple[str, Any, int]] = []
    i, n, line = 0, len(src), 1
    while i < n:
        c = src[i]
        if c == "\n":
            line += 1
            i += 1
            continue
        if c in " \t\r":
            i += 1
            continue
        if c == "#" or src.startswith("//", i):
            while i < n and src[i] != "\n":
                i += 1
            continue
        if src.startswith("/*", i):
            end = src.find("*/", i + 2)
            if end == -1:
                raise HCLParseError("unterminated block comment", line)
            line += src.count("\n", i, end)
            i = end + 2
            continue
        if c in _PUNCT:
            tokens.append(("punct", c, line))
            i += 1
            continue
        if c == '"':
            j = i + 1
            buf = []
            while j < n and src[j] != '"':
                if src[j] == "\\" and j + 1 < n:
                    esc = src[j + 1]
                    buf.append(
                        {"n": "\n", "t": "\t", '"': '"', "\\": "\\"}.get(esc, esc)
                    )
                    j += 2
                    continue
                if src[j] == "\n":
                    raise HCLParseError("newline in string", line)
                buf.append(src[j])
                j += 1
            if j >= n:
                raise HCLParseError("unterminated string", line)
            tokens.append(("string", "".join(buf), line))
            i = j + 1
            continue
        if c.isdigit() or (c == "-" and i + 1 < n and src[i + 1].isdigit()):
            j = i + 1
            while j < n and (src[j].isdigit() or src[j] in ".eE+-"):
                # stop '-'/'+' unless part of exponent
                if src[j] in "+-" and src[j - 1] not in "eE":
                    break
                j += 1
            text = src[i:j]
            try:
                value: Any = int(text)
            except ValueError:
                try:
                    value = float(text)
                except ValueError:
                    raise HCLParseError(f"bad number {text!r}", line) from None
            tokens.append(("number", value, line))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (src[j].isalnum() or src[j] in "_.-"):
                j += 1
            word = src[i:j]
            if word in ("true", "false"):
                tokens.append(("bool", word == "true", line))
            else:
                tokens.append(("ident", word, line))
            i = j
            continue
        raise HCLParseError(f"unexpected character {c!r}", line)
    return tokens


# --------------------------------------------------------------- parser


class _Parser:
    def __init__(self, tokens: List[Tuple[str, Any, int]]):
        self.tokens = tokens
        self.pos = 0

    def _peek(self) -> Optional[Tuple[str, Any, int]]:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def _next(self) -> Tuple[str, Any, int]:
        tok = self._peek()
        if tok is None:
            last_line = self.tokens[-1][2] if self.tokens else 0
            raise HCLParseError("unexpected end of input", last_line)
        self.pos += 1
        return tok

    def _expect_punct(self, which: str) -> None:
        kind, value, line = self._next()
        if kind != "punct" or value != which:
            raise HCLParseError(f"expected {which!r}, got {value!r}", line)

    def parse_body(self, until_brace: bool) -> Dict[str, Any]:
        """A body is a sequence of `key = value` attrs and `key
        ["label"...] { ... }` blocks. Repeated keys accumulate lists."""
        out: Dict[str, Any] = {}
        while True:
            tok = self._peek()
            if tok is None:
                if until_brace:
                    raise HCLParseError("missing closing '}'", self.tokens[-1][2])
                return out
            kind, value, line = tok
            if kind == "punct" and value == "}":
                if not until_brace:
                    raise HCLParseError("unexpected '}'", line)
                self._next()
                return out
            if kind not in ("ident", "string"):
                raise HCLParseError(f"expected key, got {value!r}", line)
            self._next()
            key = value
            self._parse_entry(out, key, line)

    def _parse_entry(self, out: Dict[str, Any], key: str, line: int) -> None:
        labels: List[str] = []
        while True:
            tok = self._peek()
            if tok is None:
                raise HCLParseError(f"dangling key {key!r}", line)
            kind, value, tline = tok
            if kind == "punct" and value == "=":
                self._next()
                self._store(out, key, self.parse_value())
                return
            if kind == "punct" and value == "{":
                self._next()
                body = self.parse_body(until_brace=True)
                # labels nest: job "x" { } -> {"job": {"x": {...}}}
                node: Any = body
                for label in reversed(labels):
                    node = {label: node}
                self._store(out, key, node)
                return
            if kind == "string":
                self._next()
                labels.append(value)
                continue
            raise HCLParseError(
                f"expected '=', '{{' or label after {key!r}, got {value!r}", tline
            )

    @staticmethod
    def _store(out: Dict[str, Any], key: str, value: Any) -> None:
        if key in out:
            existing = out[key]
            if isinstance(existing, list):
                existing.append(value)
            else:
                out[key] = [existing, value]
        else:
            out[key] = value

    def parse_value(self) -> Any:
        kind, value, line = self._next()
        if kind in ("string", "number", "bool"):
            return value
        if kind == "ident":
            return value  # bare identifier treated as string
        if kind == "punct" and value == "[":
            items: List[Any] = []
            while True:
                tok = self._peek()
                if tok is None:
                    raise HCLParseError("unterminated list", line)
                if tok[0] == "punct" and tok[1] == "]":
                    self._next()
                    return items
                items.append(self.parse_value())
                tok = self._peek()
                if tok and tok[0] == "punct" and tok[1] == ",":
                    self._next()
        if kind == "punct" and value == "{":
            return self.parse_body(until_brace=True)
        raise HCLParseError(f"unexpected value {value!r}", line)


def parse_hcl(src: str) -> Dict[str, Any]:
    tokens = _tokenize(src)
    return _Parser(tokens).parse_body(until_brace=False)
