"""Job spec -> structs.Job translation.

Reference: jobspec/parse.go:28 (Parse), :71 (ParseFile); block grammar
:86-1202 (job/group/task/resources/network/constraint/restart/
ephemeral_disk/artifact/template/service/check/update/periodic/vault/
meta/logs) with strict key validation (checkHCLKeys:1202).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from ..structs import (
    Constraint,
    EphemeralDisk,
    Gang,
    Job,
    LogConfig,
    NetworkResource,
    PeriodicConfig,
    Port,
    Resources,
    RestartPolicy,
    Service,
    ServiceCheck,
    Task,
    TaskArtifact,
    TaskGroup,
    Template,
    UpdateStrategy,
    Vault,
    consts,
)
from .hcl import parse_hcl

_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")
_DURATION_UNITS = {
    "ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3, "s": 1.0,
    "m": 60.0, "h": 3600.0,
}


def parse_duration(value: Any) -> float:
    """Go-style durations: '30s', '10m', '1h30m', or bare numbers
    (seconds)."""
    if isinstance(value, (int, float)):
        return float(value)
    text = str(value).strip()
    if not text:
        return 0.0
    matches = _DURATION_RE.findall(text)
    if not matches or "".join(f"{n}{u}" for n, u in matches) != text:
        raise ValueError(f"invalid duration {value!r}")
    return sum(float(n) * _DURATION_UNITS[u] for n, u in matches)


def _listify(value: Any) -> List[Any]:
    if value is None:
        return []
    return value if isinstance(value, list) else [value]


def _check_keys(block: Dict[str, Any], valid: List[str], context: str) -> None:
    invalid = [k for k in block if k not in valid]
    if invalid:
        raise ValueError(f"invalid key(s) {invalid} in {context}")


def parse(src: str) -> Job:
    """Parse an HCL job spec into a Job."""
    root = parse_hcl(src)
    if "job" not in root:
        raise ValueError("'job' block not found")
    job_block = root["job"]
    if not isinstance(job_block, dict) or len(job_block) != 1:
        raise ValueError("exactly one job block with a name label required")
    (job_id, body), = job_block.items()
    return _parse_job(job_id, body)


def parse_file(path: str) -> Job:
    with open(path) as f:
        return parse(f.read())


def _parse_job(job_id: str, body: Dict[str, Any]) -> Job:
    _check_keys(
        body,
        ["id", "name", "region", "all_at_once", "constraint", "datacenters",
         "group", "meta", "periodic", "priority", "task", "type", "update",
         "vault_token"],
        f"job {job_id!r}",
    )
    job = Job(
        id=body.get("id", job_id),
        name=body.get("name", job_id),
        region=body.get("region", "global"),
        type=body.get("type", consts.JOB_TYPE_SERVICE),
        priority=int(body.get("priority", consts.JOB_DEFAULT_PRIORITY)),
        all_at_once=bool(body.get("all_at_once", False)),
        datacenters=_listify(body.get("datacenters")),
        vault_token=body.get("vault_token", ""),
        meta={k: str(v) for k, v in (body.get("meta") or {}).items()},
    )
    job.constraints = _parse_constraints(body.get("constraint"))
    if "update" in body:
        u = body["update"]
        _check_keys(u, ["stagger", "max_parallel"], "update")
        job.update = UpdateStrategy(
            stagger=parse_duration(u.get("stagger", 0)),
            max_parallel=int(u.get("max_parallel", 0)),
        )
    if "periodic" in body:
        p = body["periodic"]
        _check_keys(p, ["cron", "prohibit_overlap", "enabled"], "periodic")
        job.periodic = PeriodicConfig(
            enabled=bool(p.get("enabled", True)),
            spec=p.get("cron", ""),
            prohibit_overlap=bool(p.get("prohibit_overlap", False)),
        )

    # groups; bare tasks at job level get an implicit group per task
    # (parse.go behavior).
    for name, group_body in _labeled_blocks(body.get("group")):
        job.task_groups.append(_parse_group(name, group_body))
    for name, task_body in _labeled_blocks(body.get("task")):
        job.task_groups.append(
            TaskGroup(name=name, count=1, tasks=[_parse_task(name, task_body)])
        )
    job.canonicalize()
    return job


def _labeled_blocks(node: Any):
    """Yield (label, body) for possibly-repeated labeled blocks."""
    if node is None:
        return
    for item in _listify(node):
        if not isinstance(item, dict):
            raise ValueError(f"expected labeled block, got {item!r}")
        for label, body in item.items():
            yield label, body


def _parse_constraints(node: Any) -> List[Constraint]:
    out = []
    for block in _listify(node):
        _check_keys(
            block,
            ["attribute", "operator", "value", "version", "regexp",
             "distinct_hosts"],
            "constraint",
        )
        c = Constraint(
            ltarget=block.get("attribute", ""),
            rtarget=str(block.get("value", "")),
            operand=block.get("operator", "="),
        )
        if "version" in block:
            c.operand = consts.CONSTRAINT_VERSION
            c.rtarget = str(block["version"])
        elif "regexp" in block:
            c.operand = consts.CONSTRAINT_REGEX
            c.rtarget = str(block["regexp"])
        elif block.get("distinct_hosts"):
            c.operand = consts.CONSTRAINT_DISTINCT_HOSTS
        out.append(c)
    return out


def _parse_group(name: str, body: Dict[str, Any]) -> TaskGroup:
    _check_keys(
        body,
        ["count", "constraint", "restart", "meta", "task", "ephemeral_disk",
         "gang"],
        f"group {name!r}",
    )
    tg = TaskGroup(
        name=name,
        count=int(body.get("count", 1)),
        meta={k: str(v) for k, v in (body.get("meta") or {}).items()},
    )
    tg.constraints = _parse_constraints(body.get("constraint"))
    if "restart" in body:
        r = body["restart"]
        _check_keys(r, ["attempts", "interval", "delay", "mode"], "restart")
        tg.restart_policy = RestartPolicy(
            attempts=int(r.get("attempts", 0)),
            interval=parse_duration(r.get("interval", 0)),
            delay=parse_duration(r.get("delay", 0)),
            mode=r.get("mode", consts.RESTART_POLICY_MODE_FAIL),
        )
    if "ephemeral_disk" in body:
        d = body["ephemeral_disk"]
        _check_keys(d, ["sticky", "migrate", "size"], "ephemeral_disk")
        tg.ephemeral_disk = EphemeralDisk(
            sticky=bool(d.get("sticky", False)),
            migrate=bool(d.get("migrate", False)),
            size_mb=int(d.get("size", 300)),
        )
    if "gang" in body:
        g = body["gang"] or {}
        _check_keys(g, ["slice", "affinity", "spread"], "gang")
        tg.gang = Gang(
            slice=str(g.get("slice", "")),
            affinity=str(g.get("affinity", "")),
            spread=str(g.get("spread", "")),
        )
    for task_name, task_body in _labeled_blocks(body.get("task")):
        tg.tasks.append(_parse_task(task_name, task_body))
    return tg


def _parse_task(name: str, body: Dict[str, Any]) -> Task:
    _check_keys(
        body,
        ["driver", "user", "config", "env", "service", "constraint", "meta",
         "resources", "kill_timeout", "logs", "artifact", "template", "vault"],
        f"task {name!r}",
    )
    task = Task(
        name=name,
        driver=body.get("driver", ""),
        user=body.get("user", ""),
        config=dict(body.get("config") or {}),
        env={k: str(v) for k, v in (body.get("env") or {}).items()},
        meta={k: str(v) for k, v in (body.get("meta") or {}).items()},
        kill_timeout=parse_duration(body.get("kill_timeout", 5)),
    )
    task.constraints = _parse_constraints(body.get("constraint"))
    if "resources" in body:
        task.resources = _parse_resources(body["resources"])
    if "logs" in body:
        lg = body["logs"]
        _check_keys(lg, ["max_files", "max_file_size"], "logs")
        task.log_config = LogConfig(
            max_files=int(lg.get("max_files", 10)),
            max_file_size_mb=int(lg.get("max_file_size", 10)),
        )
    for svc in _listify(body.get("service")):
        task.services.append(_parse_service(task.name, svc))
    for art in _listify(body.get("artifact")):
        _check_keys(art, ["source", "options", "destination"], "artifact")
        task.artifacts.append(
            TaskArtifact(
                getter_source=art.get("source", ""),
                getter_options={
                    k: str(v) for k, v in (art.get("options") or {}).items()
                },
                relative_dest=art.get("destination", "local/"),
            )
        )
    for tmpl in _listify(body.get("template")):
        _check_keys(
            tmpl,
            ["source", "destination", "data", "change_mode", "change_signal",
             "splay"],
            "template",
        )
        task.templates.append(
            Template(
                source_path=tmpl.get("source", ""),
                dest_path=tmpl.get("destination", ""),
                embedded_tmpl=tmpl.get("data", ""),
                change_mode=tmpl.get("change_mode", "restart"),
                change_signal=tmpl.get("change_signal", ""),
                splay=parse_duration(tmpl.get("splay", 5)),
            )
        )
    if "vault" in body:
        v = body["vault"]
        _check_keys(v, ["policies", "env", "change_mode", "change_signal"], "vault")
        task.vault = Vault(
            policies=_listify(v.get("policies")),
            env=bool(v.get("env", True)),
            change_mode=v.get("change_mode", "restart"),
            change_signal=v.get("change_signal", ""),
        )
    return task


def _parse_resources(body: Dict[str, Any]) -> Resources:
    _check_keys(body, ["cpu", "memory", "disk", "iops", "network"], "resources")
    res = Resources(
        cpu=int(body.get("cpu", Resources.DEFAULT_CPU)),
        memory_mb=int(body.get("memory", Resources.DEFAULT_MEMORY_MB)),
        disk_mb=int(body.get("disk", 0)),
        iops=int(body.get("iops", 0)),
    )
    for net in _listify(body.get("network")):
        _check_keys(net, ["mbits", "port"], "network")
        nr = NetworkResource(mbits=int(net.get("mbits", 10)))
        for label, port_body in _labeled_blocks(net.get("port")):
            port_body = port_body or {}
            _check_keys(port_body, ["static"], f"port {label!r}")
            if "static" in port_body:
                nr.reserved_ports.append(Port(label, int(port_body["static"])))
            else:
                nr.dynamic_ports.append(Port(label, 0))
        res.networks.append(nr)
    return res


def _parse_service(task_name: str, body: Dict[str, Any]) -> Service:
    _check_keys(body, ["name", "tags", "port", "check"], "service")
    svc = Service(
        name=body.get("name", f"{task_name}-service"),
        port_label=str(body.get("port", "")),
        tags=[str(t) for t in _listify(body.get("tags"))],
    )
    for check in _listify(body.get("check")):
        _check_keys(
            check,
            ["name", "type", "command", "args", "path", "protocol", "port",
             "interval", "timeout", "initial_status"],
            "check",
        )
        svc.checks.append(
            ServiceCheck(
                name=check.get("name", f"{svc.name}-check"),
                type=check.get("type", ""),
                command=check.get("command", ""),
                args=[str(a) for a in _listify(check.get("args"))],
                path=check.get("path", ""),
                protocol=check.get("protocol", ""),
                port_label=str(check.get("port", "")),
                interval=parse_duration(check.get("interval", 0)),
                timeout=parse_duration(check.get("timeout", 0)),
                initial_status=check.get("initial_status", ""),
            )
        )
    return svc
