"""Host sequential gang placement: the CPU twin of ops/gang.py.

Used three ways (all demanding identical SEMANTICS, not identical
scores):

- **parity target**: the dense program's hard masks (slice
  contiguity, spread caps, distinct-hosts, all-K-or-nothing) must
  agree with this path — tests/test_gang.py compares them on
  hand-built topologies;
- **oracle**: the differential rig's ``judge_gang_plan`` judges dense
  placements against host-derived group feasibility;
- **fallback**: an open device breaker or a device fault routes gang
  evals here with the atomicity contract intact (the same
  ``Plan.gang_groups`` leg is staged, so the applier treats both
  paths identically).

Slice selection mirrors the device policy: the TIGHTEST topology
group whose estimated member capacity covers all K is tried first
(consume the fragment that fits, don't crack open the emptiest rack);
the host path then walks remaining sufficient groups — a luxury the
one-shot dense program doesn't have, and the reason the host leg is
the oracle rather than the optimum.

Everything stages through ``Plan.append_gang_alloc`` and unwinds with
``Plan.pop_gang``: a partial gang never survives this module.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from ..structs import Allocation, Node, Resources, TaskGroup, consts
from ..utils.ids import generate_uuid
from . import (
    gang_distinct_hosts,
    gang_key,
    gang_mode,
    gang_spec,
    spread_cap,
)

from ..models.topology import TOPOLOGY_META_KEYS


def _group_of(node: Node, level: str) -> Optional[str]:
    return node.meta.get(TOPOLOGY_META_KEYS[level]) or None


def _gang_ask(tg: TaskGroup) -> Tuple[float, float, float, float]:
    """(cpu, mem, disk, iops) of one gang member."""
    cpu = mem = iops = 0.0
    disk = float(tg.ephemeral_disk.size_mb if tg.ephemeral_disk else 0)
    for task in tg.tasks:
        r = task.resources
        cpu += r.cpu
        mem += r.memory_mb
        disk += r.disk_mb
        iops += r.iops
    return cpu, mem, disk, iops


def estimate_member_units(state, plan, node: Node, tg: TaskGroup,
                          distinct_hosts: bool = False) -> int:
    """How many gang members this node could hold from its proposed
    free capacity — the host analog of ops/gang.py _member_units,
    shared with the rig's judge. Estimation only (ordering +
    sufficiency): the member placements themselves run the full
    iterator stack."""
    from ..scheduler.util import proposed_allocs_for_node
    from ..structs import allocs_fit

    proposed = proposed_allocs_for_node(state, plan, node.id)
    _fit, _dim, used = allocs_fit(node, proposed)
    r = node.resources
    free = (r.cpu - used.cpu, r.memory_mb - used.memory_mb,
            r.disk_mb - used.disk_mb, r.iops - used.iops)
    ask = _gang_ask(tg)
    units = None
    for have, want in zip(free, ask):
        if want <= 0:
            continue
        dim_units = int(math.floor(have / want))
        units = dim_units if units is None else min(units, dim_units)
    if units is None:
        units = len(proposed) + 1  # zero-ask gang: capacity-unbounded
    units = max(units, 0)
    if distinct_hosts:
        units = min(units, 1)
    return units


def place_gang_host(sched, tg: TaskGroup,
                    missing: List) -> bool:
    """Stage ALL of one gang's placements on sched.plan through the
    host iterator stack, or stage nothing. `sched` is a
    GenericScheduler mid-_compute_placements (ctx/stack/plan/job set);
    `missing` the gang's AllocTuples (the whole-gang promotion in
    scheduler/generic.py guarantees it is the complete member set).
    Returns True when the gang staged."""
    from ..ops.gang import (
        GANG_MODE_AFFINITY,
        GANG_MODE_SLICE,
        GANG_MODE_SPREAD,
    )

    spec = gang_spec(tg)
    mode, level = gang_mode(spec)
    k = len(missing)
    key = gang_key(sched.job.id, tg.name)
    dh = gang_distinct_hosts(sched.job, tg)

    nodes = [n for n in sched.state.nodes()
             if n.ready() and n.datacenter in sched.job.datacenters]

    if mode == GANG_MODE_SLICE:
        groups: Dict[str, List[Node]] = {}
        for node in nodes:
            g = _group_of(node, level)
            if g is not None:
                groups.setdefault(g, []).append(node)
        # Tightest sufficient slice first (device policy), group name
        # as the deterministic tie-break.
        sufficient = []
        for name, members in groups.items():
            units = sum(
                estimate_member_units(sched.state, sched.plan, n, tg, dh)
                for n in members)
            if units >= k:
                sufficient.append((units, name, members))
        sufficient.sort(key=lambda ent: (ent[0], ent[1]))
        for _units, _name, members in sufficient:
            if _stage_members(sched, tg, missing, key,
                             lambda placed, m=members: list(m)):
                return True
        return False

    if mode == GANG_MODE_SPREAD:
        groups = {}
        for node in nodes:
            g = _group_of(node, level) or f"__node__{node.id}"
            groups.setdefault(g, []).append(node)
        eligible = sum(
            1 for members in groups.values()
            if any(estimate_member_units(sched.state, sched.plan, n,
                                         tg, dh) >= 1 for n in members))
        cap = spread_cap(k, eligible)
        counts: Dict[str, int] = {}

        def allowed(placed):
            out = []
            for g, members in groups.items():
                if counts.get(g, 0) < cap:
                    out.extend(members)
            return out

        def note(node):
            g = _group_of(node, level) or f"__node__{node.id}"
            counts[g] = counts.get(g, 0) + 1

        return _stage_members(sched, tg, missing, key, allowed,
                              on_place=note)

    if mode == GANG_MODE_AFFINITY:
        used_groups: set = set()

        def allowed(placed):
            if not used_groups:
                return list(nodes)
            # Prefer co-located: nodes in groups already holding
            # members first; _stage_members falls back to the full
            # set when the preferred subset cannot place.
            pref = [n for n in nodes
                    if (_group_of(n, level) or f"__node__{n.id}")
                    in used_groups]
            return pref or list(nodes)

        def note(node):
            used_groups.add(_group_of(node, level) or f"__node__{node.id}")

        return _stage_members(sched, tg, missing, key, allowed,
                              on_place=note, fallback_nodes=nodes)

    # free mode: atomicity only.
    return _stage_members(sched, tg, missing, key,
                          lambda placed: list(nodes))


def _stage_members(sched, tg: TaskGroup, missing: List, key: str,
                   node_source, on_place=None,
                   fallback_nodes: Optional[List[Node]] = None) -> bool:
    """Place every member against node_source(placed_so_far) through
    the stack, staging each on the gang leg so later members see
    earlier claims; unwind the whole gang on any failure."""
    placed = 0
    for tup in missing:
        candidates = node_source(placed)
        option = None
        if candidates:
            sched.stack.set_nodes(list(candidates))
            option, _size = sched.stack.select(tg)
        if option is None and fallback_nodes:
            sched.stack.set_nodes(list(fallback_nodes))
            option, _size = sched.stack.select(tg)
        if option is None:
            sched.plan.pop_gang(key)
            return False
        alloc = Allocation(
            id=generate_uuid(),
            eval_id=sched.eval.id,
            name=tup.name,
            job_id=sched.job.id,
            task_group=tg.name,
            metrics=sched.ctx.metrics,
            node_id=option.node.id,
            task_resources=option.task_resources,
            desired_status=consts.ALLOC_DESIRED_RUN,
            client_status=consts.ALLOC_CLIENT_PENDING,
            shared_resources=Resources(
                disk_mb=tg.ephemeral_disk.size_mb
                if tg.ephemeral_disk else 0),
        )
        if tup.alloc is not None and tup.alloc.id:
            alloc.previous_allocation = tup.alloc.id
        sched.plan.append_gang_alloc(key, alloc)
        if on_place is not None:
            on_place(option.node)
        placed += 1
    return True
