"""Gang scheduling: all-or-nothing multi-node placement on the
node-topology tensor.

A task group carrying a ``gang`` stanza (structs/job.py ``Gang``)
places its ``count`` members ATOMICALLY — all K or none:

- the dense leg (ops/gang.py) runs the all-K feasibility pass over
  the device-resident cluster base: per-node member capacity ->
  topology-group cumulative capacity -> contiguous-slice selection ->
  K-step member assignment, with all-K enforcement on device;
- the host leg (gang/host.py) mirrors the semantics through the
  sequential iterator stack — parity target, oracle for the
  differential rig (kernels/differential.py ``judge_gang_plan``), and
  the breaker/device-fault fallback;
- atomic commit: members stage through ``Plan.append_gang_alloc``
  into the ``gang_groups`` leg, and the plan applier rejects the WHOLE
  gang when any member's node fails verification
  (server/plan_apply.py) — nothing partial ever commits;
- whole-gang replacement: losing one member invalidates the gang
  (a multi-node DL job cannot run at K-1), so the scheduler stops the
  survivors and re-places all K as a unit
  (scheduler/generic.py ``promote_gang_replacements``).

This module holds the shared spec/routing helpers both scheduler
paths, the executive's cohort fast path, the applier, and the rig
import — it never touches the state store (gang terminals only ever
stamp through the raft funnel).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Tuple

from ..structs import Job, TaskGroup, consts

from ..structs.job import Gang  # noqa: F401 (re-export)

__all__ = [
    "Gang",
    "gang_spec",
    "gang_task_groups",
    "is_gang_job",
    "gang_key",
    "gang_mode",
    "build_gang_config",
    "build_gang_state",
    "gang_distinct_hosts",
    "note_gang_result",
    "gang_stats",
    "reset_gang_stats",
    "spread_cap",
]


def gang_spec(tg: TaskGroup) -> Optional[Gang]:
    """The task group's gang stanza, or None. getattr-shielded so jobs
    decoded from pre-gang wire payloads (no field) behave as plain
    groups."""
    return getattr(tg, "gang", None)


def gang_task_groups(job: Optional[Job]) -> List[TaskGroup]:
    if job is None:
        return []
    return [tg for tg in job.task_groups if gang_spec(tg) is not None]


def is_gang_job(job: Optional[Job]) -> bool:
    return bool(gang_task_groups(job))


def gang_key(job_id: str, tg_name: str) -> str:
    """The Plan.gang_groups key for one gang: a (job, task group)
    pair — a gang is a TG-scoped unit."""
    return f"{job_id}/{tg_name}"


def gang_mode(gang: Gang) -> Tuple[str, str]:
    """(mode, topology level) for the dense/host programs. ``free``
    keeps atomicity with no topology policy; its level defaults to
    "rack" only so a column exists to thread (the program ignores
    it)."""
    from ..ops.gang import (
        GANG_MODE_AFFINITY,
        GANG_MODE_FREE,
        GANG_MODE_SLICE,
        GANG_MODE_SPREAD,
    )

    if gang.slice:
        return GANG_MODE_SLICE, gang.slice
    if gang.spread:
        return GANG_MODE_SPREAD, gang.spread
    if gang.affinity:
        return GANG_MODE_AFFINITY, gang.affinity
    return GANG_MODE_FREE, "rack"


def gang_distinct_hosts(job: Job, tg: TaskGroup) -> bool:
    dh = any(c.operand == consts.CONSTRAINT_DISTINCT_HOSTS
             for c in job.constraints)
    return dh or any(c.operand == consts.CONSTRAINT_DISTINCT_HOSTS
                     for c in tg.constraints)


def build_gang_config(job: Job, tg: TaskGroup, topo_groups: int):
    """The static GangConfig for one (job, gang TG) against a topology
    column with ``topo_groups`` groups. Every field is hashable and
    bucketed, so each (mode, dh, g_pad, penalty) pair is exactly one
    compiled program per shape bucket."""
    from ..models.topology import topo_group_pad
    from ..ops.gang import GangConfig
    from ..scheduler.stack import (
        BATCH_JOB_ANTI_AFFINITY_PENALTY,
        SERVICE_JOB_ANTI_AFFINITY_PENALTY,
    )

    mode, _level = gang_mode(gang_spec(tg))
    return GangConfig(
        anti_affinity_penalty=(
            BATCH_JOB_ANTI_AFFINITY_PENALTY
            if job.type == consts.JOB_TYPE_BATCH
            else SERVICE_JOB_ANTI_AFFINITY_PENALTY),
        mode=mode,
        distinct_hosts=gang_distinct_hosts(job, tg),
        g_pad=topo_group_pad(topo_groups),
    )


def build_gang_state(matrix, job: Job, tg: TaskGroup):
    """(GangState, active [K_pad], ask (res, bw, ports), config) for
    one gang dispatch against a ClusterMatrix. Reuses the matrix's
    memoized feasibility mask and overlay counts — the gang pass adds
    no per-eval host recomputation beyond slicing them."""
    import numpy as np

    from ..models.matrix import ASK_BUCKETS, bucket_size
    from ..ops.gang import GANG_MODE_SLICE, make_gang_state

    gi = next(i for i, g in enumerate(job.task_groups)
              if g.name == tg.name)
    k = tg.count
    k_pad = bucket_size(max(k, 1), ASK_BUCKETS)
    active = np.zeros(k_pad, bool)
    active[:k] = True

    # Uniform member ask from the matrix's shared group-size builder
    # (one row; gang members are identical by construction).
    resources, bw, ports, _tgi, _act, _jdh, _tdh = \
        matrix.build_asks([gi])
    ask_res, ask_bw, ask_ports = resources[0], bw[0], ports[0]

    mode, level = gang_mode(gang_spec(tg))
    topo = matrix.topology
    if mode == GANG_MODE_SLICE:
        topo_ids = topo.column(level)
        topo_groups = topo.counts[level]
    else:
        topo_ids, topo_groups = topo.singleton_column(level)

    feas_row = matrix.feasible[:, gi] & matrix.node_ok
    dh = gang_distinct_hosts(job, tg)
    job_dh = any(c.operand == consts.CONSTRAINT_DISTINCT_HOSTS
                 for c in job.constraints)
    if dh:
        dh_presence = (matrix.job_count if job_dh
                       else matrix.tg_count[:, gi])
    else:
        dh_presence = np.zeros(matrix.n, np.int32)

    state = make_gang_state(
        matrix.capacity, matrix.sched_capacity, matrix.util,
        matrix.bw_avail, matrix.bw_used, matrix.ports_free,
        feas_row, matrix.job_count, dh_presence, topo_ids)
    config = build_gang_config(job, tg, topo_groups)
    return state, active, (ask_res, ask_bw, ask_ports), config


# ---------------------------------------------------------------- stats

_stats_lock = threading.Lock()
_stats: Dict[str, int] = {}


def note_gang_result(placed: bool, members: int, path: str) -> None:
    """Count one gang attempt's outcome (leaf lock, constant work).
    ``path`` is "device" | "host" | "executive"."""
    with _stats_lock:
        _stats["gangs_placed" if placed else "gangs_rejected"] = (
            _stats.get("gangs_placed" if placed else "gangs_rejected", 0)
            + 1)
        if placed:
            _stats["members_placed"] = (
                _stats.get("members_placed", 0) + members)
        key = f"path_{path}"
        _stats[key] = _stats.get(key, 0) + 1


def gang_stats() -> Dict[str, int]:
    with _stats_lock:
        return dict(_stats)


def reset_gang_stats() -> None:
    with _stats_lock:
        _stats.clear()


def spread_cap(k: int, eligible_groups: int) -> int:
    """The spread mode's per-group member cap (shared by the host leg
    and the rig's judge so they can never disagree with the device
    formula)."""
    return int(math.ceil(k / max(eligible_groups, 1)))
