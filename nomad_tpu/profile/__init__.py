"""The contention observatory: always-on lock/GIL/pipeline profiler.

Always-on like the flight recorder (nomad_tpu/trace): the observatory
turns thread/lock/GIL contention and device-pipeline convoys into
first-class telemetry instead of inferences from percentile gaps.
Three instruments, one process-global Profiler:

- **ProfiledLock / ProfiledRLock / ProfiledCondition** (locks.py):
  drop-in threading primitives recording per-declaration-site
  acquire-wait and hold time into the shared log-bucket histograms.
  Wired into the hot locks: the placement batcher, the dispatch
  pipeline, the eval broker, the cluster-matrix position index, and
  the trace recorder's stripes.
- **GIL-pressure sampler** (sampler.py): a thread measuring
  sleep-overshoot — requested vs actual wake, a direct proxy for
  interpreter scheduling delay — plus per-worker run-queue delay
  stamped at broker drain and batch-park points (record_runq).
- **Pipeline timeline + convoy detector** (timeline.py): a bounded
  ring of batch-lifecycle events and an online tracker reporting the
  width and duration of thread pile-ups at the batch boundary — the
  specific pathology ROADMAP open item 1 names.

Exposure: ``server.stats()["profile"]``, ``/v1/agent/profile`` (with
``?lock=`` / ``?thread=`` drill-down), ``/v1/metrics`` (Prometheus
histograms/gauges), lock-wait annotations on trace spans, and the
Chrome trace-event (Perfetto-loadable) export in export.py.

Overhead discipline: the uncontended lock path pays one counter bump
and one clock read; everything on the record path is arithmetic +
preallocated-slot writes under leaf locks (machine-enforced: ntalint's
``record-path-blocking`` walks the ``NTA_RECORD_PATH`` manifests here
and in locks.py/timeline.py). bench.py's ``--profile-ab`` arm proves
the whole observatory costs < 5% paired e2e (the --check gate refuses
numbers otherwise).
"""

from __future__ import annotations

import collections
import threading
import weakref
from typing import Dict, List, Optional

from ..utils.metrics import (
    HIST_BUCKETS,
    hist_bucket_upper,
    hist_percentile,
)
from .locks import (  # noqa: F401
    ProfiledCondition,
    ProfiledLock,
    ProfiledRLock,
    _SiteStats,
    _WaitHist,
)
from .sampler import GilSampler
from .timeline import ConvoyTracker, Timeline

# Bounds: everything the profiler stores is capped at registration
# time, so the record paths never grow anything.
MAX_LOCK_INSTANCES = 1024   # registered lock objects (per process)
MAX_THREADS = 256           # per-thread drill-down entries
MAX_PARK_SITES = 16         # convoy trackers
RUNQ_SITES = ("broker_drain", "batch_park")

# ntalint record-path manifest (analysis/robustness.py): the profiler
# record entrypoints the hot locks, the broker, and the dispatcher
# thread run through. Everything reachable from these must never park
# (leaf `with lock:` around constant work only) and never grow a
# container (preallocated slots / capped subscript assignment only).
NTA_RECORD_PATH = (
    "Profiler.record_runq",
    "Profiler.park",
    "Profiler.unpark",
    "Profiler.event",
    "Profiler._note_thread_wait",
)


class _ThreadStats:
    """Per-thread contention totals. Each entry is written only by its
    own thread (registered via a threading.local), so plain attributes
    never tear."""

    __slots__ = ("name", "wait_ms", "waits", "runq_ms", "runqs",
                 "top_site", "top_site_ms")

    def __init__(self, name: str):
        self.name = name
        self.wait_ms = 0.0
        self.waits = 0
        self.runq_ms = 0.0
        self.runqs = 0
        self.top_site = ""
        self.top_site_ms = 0.0

    def to_dict(self) -> dict:
        return {
            "lock_wait_ms": round(self.wait_ms, 3),
            "lock_waits": self.waits,
            "runq_delay_ms": round(self.runq_ms, 3),
            "runq_samples": self.runqs,
            "hottest_site": self.top_site,
            "hottest_site_wait_ms": round(self.top_site_ms, 3),
        }


class Profiler:
    def __init__(self):
        # Plain attribute read on every record call (the bench
        # --profile-off arm and tests flip it); no lock — a racing
        # record lands or not, either is fine.
        self.enabled = True
        self._reg_lock = threading.Lock()
        # site -> list of LIVE _SiteStats (one per lock instance);
        # bounded by MAX_LOCK_INSTANCES total, and a dead lock's stats
        # RETIRE: a weakref.finalize on the lock folds its counts into
        # the site's retired aggregate and frees the live slot, so a
        # churny site (e.g. per-ClusterBase position locks, one per
        # snapshot) neither exhausts the cap nor accretes dead
        # histograms the read side must walk forever.
        self._lock_sites: Dict[str, List[_SiteStats]] = {}
        self._lock_retired: Dict[str, _SiteStats] = {}  # guarded-by: _reg_lock
        self._lock_instances = 0  # guarded-by: _reg_lock
        # Dead locks' stats land here from weakref finalizers, which
        # run DURING garbage collection — possibly on a thread that
        # already holds _reg_lock mid-allocation, so the callback must
        # be lock-free (deque.append is atomic). Registry mutation
        # happens at the next drain under the lock.
        self._retired_queue: collections.deque = collections.deque()
        self.timeline = Timeline()
        self._park_lock = threading.Lock()
        self._parks: Dict[str, ConvoyTracker] = {}  # guarded-by: _park_lock
        self.gil = GilSampler()
        self._runq_lock = threading.Lock()
        self._runq: Dict[str, _WaitHist] = {  # fixed keys, hists swap on reset
            site: _WaitHist() for site in RUNQ_SITES
        }
        self._tls = threading.local()
        self._threads_lock = threading.Lock()
        self._threads: Dict[str, _ThreadStats] = {}  # guarded-by: _threads_lock

    # ------------------------------------------------- registration

    def _register_lock(self, lock, site: str, kind: str) -> _SiteStats:
        """Called at lock CONSTRUCTION (never on the record path).
        Past the live-instance cap, stats still exist but are not
        exported — the lock keeps working, the table stays bounded.
        When the lock is garbage-collected its stats retire into the
        site's aggregate (no more writers can exist, so the merge
        cannot tear) and the live slot frees."""
        stats = _SiteStats(site, kind)
        self._drain_retired()
        with self._reg_lock:
            if self._lock_instances >= MAX_LOCK_INSTANCES:
                return stats
            self._lock_instances += 1
            self._lock_sites.setdefault(site, []).append(stats)
        weakref.finalize(lock, self._retired_queue.append, (site, stats))
        return stats

    def _drain_retired(self) -> None:
        """Fold queued dead-lock stats into their sites' retired
        aggregates. The dead stats have no writers left, so the merge
        cannot tear. Runs at registration and read time — never inside
        a GC finalizer (which may fire on a thread that already holds
        _reg_lock; the finalizer itself only appends to the lock-free
        queue)."""
        while True:
            try:
                site, stats = self._retired_queue.popleft()
            except IndexError:
                return
            with self._reg_lock:
                live = self._lock_sites.get(site)
                if live is None or stats not in live:
                    continue  # never exported (cap) or already reset
                live.remove(stats)
                self._lock_instances -= 1
                retired = self._lock_retired.get(site)
                if retired is None:
                    retired = self._lock_retired[site] = _SiteStats(
                        site, stats.kind)
                retired.acquires += stats.acquires
                retired.contended += stats.contended
                retired.cond_waits += stats.cond_waits
                for field in ("wait", "hold", "cond_wait"):
                    dst = getattr(retired, field)
                    src = getattr(stats, field)
                    dst.count += src.count
                    dst.total += src.total
                    if src.max > dst.max:
                        dst.max = src.max
                    for i, c in enumerate(src.buckets):
                        if c:
                            dst.buckets[i] += c

    def _register_thread(self) -> Optional[_ThreadStats]:
        name = threading.current_thread().name
        with self._threads_lock:
            st = self._threads.get(name)
            if st is None:
                if len(self._threads) >= MAX_THREADS:
                    return None
                st = _ThreadStats(name)
                self._threads[name] = st
            return st

    def _thread_stats(self) -> Optional[_ThreadStats]:
        tls = self._tls
        st = getattr(tls, "stats", None)
        if st is None:
            st = self._register_thread()
            if st is not None:
                tls.stats = st
        return st

    # -------------------------------------------------- record path

    def _note_thread_wait(self, site: str, wait_ms: float) -> None:
        """Contended lock wait attribution onto the waiting thread
        (called by ProfiledLock while the lock is held)."""
        st = self._thread_stats()
        if st is None:
            return
        st.wait_ms += wait_ms
        st.waits += 1
        if wait_ms > st.top_site_ms:
            st.top_site = site
            st.top_site_ms = wait_ms

    def record_runq(self, site: str, delay_ms: float) -> None:
        """Run-queue delay: ready-work-published -> worker actually
        running, stamped at broker drain and batch park points."""
        if not self.enabled or delay_ms < 0.0:
            return
        h = self._runq.get(site)
        if h is None:
            return  # fixed vocabulary; unknown sites don't grow it
        with self._runq_lock:
            h.observe(delay_ms)
        st = self._thread_stats()
        if st is not None:
            st.runq_ms += delay_ms
            st.runqs += 1

    def park(self, site: str, thread: str = "") -> bool:
        """A thread parked at a batch boundary; feeds the convoy
        tracker + timeline. Returns True when the park was COUNTED —
        the caller must unpark() exactly when it was (a park taken
        while enabled must decrement even if the profiler is disabled
        mid-park, or the width gauge leaks a phantom pile-up forever).
        Tracker registration is capped (a missing tracker past the cap
        means the park is counted nowhere — a bounded-memory tradeoff,
        same shape as the recorder's active-cap eviction)."""
        if not self.enabled:
            return False
        with self._park_lock:
            tracker = self._parks.get(site)
            if tracker is None:
                if len(self._parks) >= MAX_PARK_SITES:
                    return False
                tracker = ConvoyTracker()
                self._parks[site] = tracker
        w = tracker.park()
        self.timeline.push("park", thread, w, site)
        return True

    def unpark(self, site: str, thread: str = "") -> None:
        """Balance a COUNTED park(). Deliberately not gated on
        `enabled`: the width must come back down even when recording
        was switched off while the thread was parked."""
        with self._park_lock:
            tracker = self._parks.get(site)
        if tracker is None:
            return
        w = tracker.unpark()
        if self.enabled:
            self.timeline.push("unpark", thread, w, site)

    def event(self, kind: str, thread: str = "", a=0, b=0) -> None:
        """Publish one batch-lifecycle event into the timeline ring."""
        if not self.enabled:
            return
        self.timeline.push(kind, thread, a, b)

    # ----------------------------------------------------- read side

    def thread_wait_ms(self) -> float:
        """Cumulative contended lock-wait of the CALLING thread (ms) —
        call sites bracket a stage with two reads and annotate the
        delta onto its trace span."""
        st = getattr(self._tls, "stats", None)
        return st.wait_ms if st is not None else 0.0

    def _site_stats_lists(self) -> Dict[str, List[_SiteStats]]:
        """site -> live instances + the retired aggregate (read-side
        merge input; one consistent cut under the registry lock)."""
        self._drain_retired()
        with self._reg_lock:
            out = {site: list(instances)
                   for site, instances in self._lock_sites.items()
                   if instances}
            for site, retired in self._lock_retired.items():
                out.setdefault(site, []).append(retired)
        return out

    def _aggregate_site(self, instances: List[_SiteStats]) -> dict:
        out: dict = {
            "kind": instances[0].kind,
            "instances": len(instances),
            "acquires": sum(s.acquires for s in instances),
            "contended": sum(s.contended for s in instances),
            "cond_waits": sum(s.cond_waits for s in instances),
        }
        for field in ("wait", "hold", "cond_wait"):
            count, total, mx = 0, 0.0, 0.0
            buckets = [0] * HIST_BUCKETS
            for s in instances:
                count, total, mx = getattr(s, field).merge_into(
                    count, total, mx, buckets)
            if count:
                out[field] = {
                    "count": count,
                    "total_ms": round(total, 3),
                    "mean_ms": round(total / count, 4),
                    "max_ms": round(mx, 3),
                    "p50_ms": round(
                        hist_percentile(buckets, count, 0.50), 4),
                    "p95_ms": round(
                        hist_percentile(buckets, count, 0.95), 4),
                    "p99_ms": round(
                        hist_percentile(buckets, count, 0.99), 4),
                }
        return out

    def lock_table(self) -> Dict[str, dict]:
        """Per-declaration-site lock stats: live instances plus the
        site's retired (garbage-collected locks) aggregate."""
        return {site: self._aggregate_site(instances)
                for site, instances in self._site_stats_lists().items()}

    def lock_site_buckets(self, field: str = "wait"):
        """(site -> (count, dense buckets)) for one histogram family —
        the Prometheus exposition and the bench aggregation read this
        so their percentiles come off the same ladder as snapshot()."""
        out = {}
        for site, instances in self._site_stats_lists().items():
            count, total, mx = 0, 0.0, 0.0
            buckets = [0] * HIST_BUCKETS
            for s in instances:
                count, total, mx = getattr(s, field).merge_into(
                    count, total, mx, buckets)
            if count:
                out[site] = (count, total, buckets)
        return out

    def runq_table(self) -> Dict[str, dict]:
        with self._runq_lock:
            return {site: h.stats() for site, h in self._runq.items()
                    if h.count}

    def convoy_table(self) -> dict:
        with self._park_lock:
            trackers = dict(self._parks)
        sites = {site: t.stats() for site, t in trackers.items()}
        max_width = max((s["max_width"] for s in sites.values()),
                        default=0)
        recent: List[dict] = []
        for site, t in trackers.items():
            for c in t.recent():
                recent.append(dict(c, site=site))
        recent.sort(key=lambda c: c["start_unix"], reverse=True)
        return {
            "max_width": max_width,
            "convoys": sum(s["convoys"] for s in sites.values()),
            "sites": sites,
            "recent": recent[:32],
        }

    def threads_table(self) -> Dict[str, dict]:
        with self._threads_lock:
            entries = list(self._threads.values())
        return {st.name: st.to_dict() for st in entries}

    def snapshot(self, threads: bool = False) -> dict:
        out = {
            "enabled": self.enabled,
            "locks": self.lock_table(),
            "gil": self.gil.stats(),
            "runq": self.runq_table(),
            "convoys": self.convoy_table(),
            "timeline": self.timeline.stats(),
        }
        if threads:
            out["threads"] = self.threads_table()
        return out

    def format_prometheus(self, prefix: str = "nomad_tpu_profile") -> str:
        """Prometheus text exposition (0.0.4) of the observatory:
        lock wait/hold/cond-wait and runq-delay histograms as labelled
        ``site=`` series over the shared log-bucket ladder, the GIL
        overshoot histogram, and the convoy gauges. Appended to the
        telemetry registry's exposition at /v1/metrics — conformance is
        covered by the same line-level parser test."""
        from ..utils.metrics import _prom_num, emit_histogram_family

        lines: List[str] = []

        def hist_family(name: str, help_text: str, series: dict) -> None:
            """series: site label (or "" for unlabelled) ->
            (count, total, dense bucket list); the shared registry
            emitter does the 0.0.4 encoding."""
            emit_histogram_family(lines, name, help_text, series)

        hist_family(f"{prefix}_lock_wait_ms",
                    "contended lock acquire-wait per site (milliseconds)",
                    self.lock_site_buckets("wait"))
        hist_family(f"{prefix}_lock_hold_ms",
                    "lock hold time per site (milliseconds)",
                    self.lock_site_buckets("hold"))
        hist_family(f"{prefix}_cond_wait_ms",
                    "condition wait park per site (milliseconds)",
                    self.lock_site_buckets("cond_wait"))
        gil = self.gil.hist
        if gil.count:
            hist_family(
                f"{prefix}_gil_overshoot_ms",
                "sleep overshoot: interpreter scheduling delay "
                "(milliseconds)",
                {"": (gil.count, gil.total, list(gil.buckets))})
        with self._runq_lock:
            runq = {site: (h.count, h.total, list(h.buckets))
                    for site, h in self._runq.items() if h.count}
        hist_family(f"{prefix}_runq_delay_ms",
                    "ready-work to thread-running delay per stamp site "
                    "(milliseconds)", runq)
        convoys = self.convoy_table()
        for name, help_text, value, kind in (
            ("convoy_width", "threads currently parked at the widest "
             "site", max((s["width"] for s in convoys["sites"].values()),
                         default=0), "gauge"),
            ("convoy_max_width", "high-water parked-thread pile-up "
             "width", convoys["max_width"], "gauge"),
            ("convoys_total", "completed convoys (width >= threshold)",
             convoys["convoys"], "counter"),
        ):
            p = f"{prefix}_{name}"
            lines.append(f"# HELP {p} {help_text}")
            lines.append(f"# TYPE {p} {kind}")
            lines.append(f"{p} {_prom_num(value)}")
        return "\n".join(lines) + "\n" if lines else ""

    # ------------------------------------------------------- control

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)

    def ensure_sampler(self) -> None:
        if self.enabled:
            self.gil.start()

    def configure(self, enabled: Optional[bool] = None,
                  sampler_interval: Optional[float] = None) -> None:
        if enabled is not None:
            self.set_enabled(enabled)
        if sampler_interval is not None and sampler_interval > 0:
            # <= 0 is ignored explicitly (a zero interval would spin);
            # disabling the sampler is `enabled=False`, not interval 0.
            self.gil.interval = sampler_interval
        if self.enabled:
            self.gil.start()
        else:
            self.gil.stop()

    def reset(self) -> None:
        """Drop accumulated stats (bench A/B arms and test isolation;
        not on the record path). Racing writers may lose a sample into
        a just-replaced histogram — benign for an A/B reset."""
        self._drain_retired()
        with self._reg_lock:
            instances = [s for lst in self._lock_sites.values()
                         for s in lst]
            self._lock_retired = {}
        for s in instances:
            s.acquires = 0
            s.contended = 0
            s.cond_waits = 0
            s.wait = _WaitHist()
            s.hold = _WaitHist()
            s.cond_wait = _WaitHist()
        self.timeline.reset()
        with self._park_lock:
            trackers = list(self._parks.values())
        for t in trackers:
            t.reset()
        self.gil.reset()
        with self._runq_lock:
            for site in list(self._runq):
                self._runq[site] = _WaitHist()
        with self._threads_lock:
            entries = list(self._threads.values())
        for st in entries:
            st.wait_ms = 0.0
            st.waits = 0
            st.runq_ms = 0.0
            st.runqs = 0
            st.top_site = ""
            st.top_site_ms = 0.0


# The process-wide profiler every instrumentation site uses; module
# level so the disabled check is two attribute loads + a branch (same
# shape as trace._recorder / chaos.enabled).
_profiler = Profiler()


def get_profiler() -> Profiler:
    return _profiler


def park(site: str, thread: str = "") -> bool:
    return _profiler.park(site, thread)


def unpark(site: str, thread: str = "") -> None:
    _profiler.unpark(site, thread)


def event(kind: str, thread: str = "", a=0, b=0) -> None:
    _profiler.event(kind, thread, a, b)


def record_runq(site: str, delay_ms: float) -> None:
    _profiler.record_runq(site, delay_ms)


def thread_wait_ms() -> float:
    return _profiler.thread_wait_ms()


def ensure_sampler() -> None:
    _profiler.ensure_sampler()
