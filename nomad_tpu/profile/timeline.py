"""Pipeline timeline ring + convoy detector.

The timeline is a bounded, preallocated ring of batch-lifecycle events
(accumulate open/close, launch, submit, ack, prefetch, park/unpark per
worker). Events are immutable tuples built fully BEFORE publication
into a ring slot — a reader can never observe a torn event — and the
slot index advances under a small leaf lock (constant work only, the
sanctioned record-path synchronization).

The convoy detector answers the specific question ROADMAP open item 1
asks: how wide and how long do eval threads pile up at the batch
boundary? A *convoy* is a maximal interval during which the number of
threads simultaneously parked at one site is >= CONVOY_MIN_WIDTH; the
tracker maintains the live width online (O(1) at park/unpark) and
keeps the last CONVOY_KEEP completed convoys (start, duration, peak
width) in a drop-oldest ring.

Event tuple layout: ``(t_mono, wall, kind, thread, a, b)`` where `a`
and `b` are small kind-specific scalars (batch size, eval count, site
name...). Kept positional so the concurrent-writer stress test can
checksum them.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

RING_CAP = 4096
CONVOY_KEEP = 64
# A pile-up only counts as a convoy once this many threads are parked
# at the same site at once: below it, parks are the pipeline breathing
# (one dispatcher + a straggler), not the batch-boundary pathology.
CONVOY_MIN_WIDTH = 4

# Event kinds (the timeline's closed vocabulary; the chrome exporter
# and the README table read off this tuple).
EVENT_KINDS = (
    "accumulate_open",   # a = pending at open
    "accumulate_close",  # a = batch size, b = batch ordinal
    "launch",            # a = batch size, b = route_host
    "submit",            # a = 1 (plan submit completed)
    "ack",               # a = 1 acked / 0 nacked
    "prefetch",          # a = bytes shipped
    "park",              # a = width after park,   b = site
    "unpark",            # a = width after unpark, b = site
)

# ntalint record-path manifest (analysis/robustness.py): timeline and
# convoy updates run under the dispatcher thread and inside hot-lock
# critical sections — constant work under a leaf lock only.
NTA_RECORD_PATH = ("Timeline.push", "ConvoyTracker.park",
                   "ConvoyTracker.unpark")


class Timeline:
    def __init__(self, cap: int = RING_CAP):
        self.cap = cap
        self._lock = threading.Lock()
        self._ring: List[Optional[tuple]] = [None] * cap
        self._idx = 0  # guarded-by: _lock (monotonic; slot = idx % cap)

    def push(self, kind: str, thread: str = "", a=0, b=0) -> None:
        # Tuple fully built before publication; the critical section is
        # two subscript ops and an increment.
        evt = (time.monotonic(), time.time(), kind, thread, a, b)
        with self._lock:
            self._ring[self._idx % self.cap] = evt
            self._idx += 1

    def events(self, limit: int = 0) -> List[tuple]:
        """Stored events, oldest first. ``limit`` bounds to the newest
        N (0 = all stored)."""
        with self._lock:
            n = min(self._idx, self.cap)
            start = self._idx - n
            out = [self._ring[(start + k) % self.cap] for k in range(n)]
        out = [e for e in out if e is not None]
        if limit and len(out) > limit:
            out = out[-limit:]
        return out

    def stats(self) -> dict:
        with self._lock:
            return {"events": self._idx,
                    "stored": min(self._idx, self.cap),
                    "capacity": self.cap}

    def reset(self) -> None:
        with self._lock:
            self._ring = [None] * self.cap
            self._idx = 0


class ConvoyTracker:
    """Online width tracking for thread pile-ups at a park site.

    park()/unpark() are O(1) under a leaf lock; a convoy OPENS when the
    live width crosses CONVOY_MIN_WIDTH and CLOSES when it falls back
    below, recording ``(start_wall, duration_ms, peak_width)`` into a
    drop-oldest ring of CONVOY_KEEP slots.
    """

    def __init__(self, min_width: int = CONVOY_MIN_WIDTH,
                 keep: int = CONVOY_KEEP):
        self.min_width = min_width
        self.keep = keep
        self._lock = threading.Lock()
        self.width = 0  # guarded-by: _lock (live parked count)
        self.max_width = 0  # guarded-by: _lock (lifetime high-water)
        self.convoys = 0  # guarded-by: _lock (completed convoy count)
        self._open_at = 0.0  # guarded-by: _lock (monotonic; 0 = closed)
        self._open_wall = 0.0  # guarded-by: _lock
        self._open_peak = 0  # guarded-by: _lock
        self._ring: List[Optional[tuple]] = [None] * keep
        self._ring_idx = 0  # guarded-by: _lock

    def park(self) -> int:
        """A thread parked; returns the width AFTER the park."""
        now = time.monotonic()
        with self._lock:
            self.width += 1
            w = self.width
            if w > self.max_width:
                self.max_width = w
            if self._open_at == 0.0 and w >= self.min_width:
                self._open_at = now
                self._open_wall = time.time()
                self._open_peak = w
            elif self._open_at and w > self._open_peak:
                self._open_peak = w
            return w

    def unpark(self) -> int:
        """A thread resumed; returns the width AFTER the unpark."""
        now = time.monotonic()
        with self._lock:
            if self.width > 0:
                self.width -= 1
            w = self.width
            if self._open_at and w < self.min_width:
                done = (round(self._open_wall, 6),
                        round((now - self._open_at) * 1000.0, 3),
                        self._open_peak)
                self._ring[self._ring_idx % self.keep] = done
                self._ring_idx += 1
                self.convoys += 1
                self._open_at = 0.0
                self._open_peak = 0
            return w

    def recent(self) -> List[dict]:
        """Completed convoys, newest first."""
        with self._lock:
            n = min(self._ring_idx, self.keep)
            slots = [self._ring[(self._ring_idx - 1 - k) % self.keep]
                     for k in range(n)]
        return [{"start_unix": s[0], "duration_ms": s[1], "width": s[2]}
                for s in slots if s is not None]

    def stats(self) -> dict:
        with self._lock:
            open_width = self._open_peak if self._open_at else 0
            open_for = ((time.monotonic() - self._open_at) * 1000.0
                        if self._open_at else 0.0)
            return {
                "width": self.width,
                "max_width": self.max_width,
                "convoys": self.convoys,
                "min_width": self.min_width,
                "open_width": open_width,
                "open_for_ms": round(open_for, 3),
            }

    def reset(self) -> None:
        with self._lock:
            # The live width is real (threads are still parked); only
            # the history and high-water reset.
            self.max_width = self.width
            self.convoys = 0
            self._ring = [None] * self.keep
            self._ring_idx = 0
            if self._open_at == 0.0 and self.width >= self.min_width:
                self._open_at = time.monotonic()
                self._open_wall = time.time()
                self._open_peak = self.width
