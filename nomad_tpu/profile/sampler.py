"""GIL-pressure sampler: measure interpreter scheduling delay directly.

A daemon thread repeatedly requests a short sleep and measures the
*overshoot* — actual wake minus requested wake. On an idle interpreter
the overshoot is the OS timer slack (tens of microseconds); when N
runnable threads contend for the GIL the sleeper must wait for a
GIL handoff after its timer fires, so the overshoot distribution IS
the interpreter scheduling delay every other thread experiences. This
is the measurement BENCH_r10 inferred from a percentile gap: "GIL
queuing of 64 eval threads around the batch boundary" becomes a
histogram, not a guess.

The sampler owns its histogram (single writer — the sampler thread;
readers snapshot monotonic counters, benign mid-update reads). The
sample loop is the only place in the profiler allowed to sleep; it is
NOT on the record-path manifest.

Complementing the sampler, per-worker *run-queue delay* is stamped at
the two points where ready work waits for a thread to actually run
(profile/__init__.py record_runq): broker drain (work announced to the
dispatch accumulator -> dispatcher wakes) and batch park (device
results published -> parked worker resumes).
"""

from __future__ import annotations

import threading
import time

from .locks import _WaitHist

# 5ms: long enough that the sleep itself is cheap (200 wakes/s), short
# enough that a batch-boundary stall (tens of ms) lands many samples.
SAMPLE_INTERVAL_S = 0.005


class GilSampler:
    def __init__(self, interval: float = SAMPLE_INTERVAL_S):
        self.interval = interval
        self.hist = _WaitHist()  # overshoot ms; sampler thread only
        self.samples = 0  # sampler thread only (mirrors hist.count)
        self._stop = threading.Event()
        self._thread = None
        self._lock = threading.Lock()  # start/stop serialization

    def start(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="gil-sampler", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            self._stop.set()
            t = self._thread
            self._thread = None
        if t is not None:
            t.join(timeout=2.0)

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def _run(self) -> None:
        stop = self._stop
        while True:
            # Re-read per tick: configure(sampler_interval=...) on a
            # RUNNING sampler must take effect without a restart
            # (start() is a no-op while the thread is alive).
            interval = self.interval
            t0 = time.monotonic()
            if stop.wait(interval):
                return
            overshoot_ms = (time.monotonic() - t0 - interval) * 1000.0
            if overshoot_ms < 0.0:
                overshoot_ms = 0.0  # clock granularity can undershoot
            self.hist.observe(overshoot_ms)
            self.samples += 1

    def stats(self) -> dict:
        out = self.hist.stats()
        out["running"] = self.running()
        out["interval_ms"] = self.interval * 1000.0
        return out

    def reset(self) -> None:
        # Single-writer hist: swap wholesale (the sampler thread will
        # write into the new one from its next tick).
        self.hist = _WaitHist()
        self.samples = 0
