"""Chrome trace-event (Perfetto-loadable) export.

Merges flight-recorder span trees (nomad_tpu/trace) with the
profiler's pipeline timeline and completed convoys into one JSON
object in the Trace Event Format — the ``{"traceEvents": [...]}``
shape chrome://tracing and https://ui.perfetto.dev load directly.

Mapping:

- each EVAL is a track (``tid``): one ``M`` thread_name metadata event
  naming it, then one ``X`` (complete) event per span — ``ts`` is
  absolute wall-clock microseconds (trace ``start_unix`` + the span's
  relative offset), ``dur`` the span length. Annotations and fault
  attributions ride in ``args``.
- the PIPELINE timeline rides track 0 as ``i`` (instant) events —
  accumulate open/close, launch, submit, ack, prefetch, park/unpark.
- completed CONVOYS ride a dedicated track as ``X`` events named by
  their width, so the pile-up interval is visible under the eval spans
  that caused it.

Timeline event tuples carry both monotonic and wall stamps
(timeline.py); the export uses the wall stamp so every event source
shares one absolute axis. Served at ``/v1/agent/trace?format=chrome``
and by ``tools/traceconv.py`` for saved dumps.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

PID = 1
TID_PIPELINE = 0
TID_CONVOYS = 1
TID_EVAL_BASE = 10  # eval tracks start here; 0/1 are system tracks


def _span_args(span: dict) -> dict:
    args = {}
    if span.get("annotations"):
        args.update(span["annotations"])
    if span.get("faults"):
        args["faults"] = span["faults"]
    if span.get("parent"):
        args["parent"] = span["parent"]
    return args


def trace_events(traces: Iterable[dict],
                 timeline: Optional[Iterable[tuple]] = None,
                 convoys: Optional[Iterable[dict]] = None) -> List[dict]:
    """The flat traceEvents list. ``traces`` are recorder dicts
    (recorder.py _finalize_locked shape), deduped by eval_id with the
    first occurrence winning (callers pass tail-kept traces first when
    they want the outliers to survive the dedup)."""
    events: List[dict] = [
        {"ph": "M", "pid": PID, "tid": TID_PIPELINE, "name": "thread_name",
         "args": {"name": "pipeline timeline"}},
        {"ph": "M", "pid": PID, "tid": TID_CONVOYS, "name": "thread_name",
         "args": {"name": "convoys (parked-thread pile-ups)"}},
    ]
    seen: Dict[str, int] = {}
    tid = TID_EVAL_BASE
    for trace in traces:
        eval_id = trace.get("eval_id", "")
        if not eval_id or eval_id in seen:
            continue
        seen[eval_id] = tid
        base_us = trace["start_unix"] * 1e6
        label = f"eval {eval_id[:12]} [{trace.get('status', '?')}]"
        if trace.get("tail_kept"):
            label += " (tail)"
        events.append({"ph": "M", "pid": PID, "tid": tid,
                       "name": "thread_name", "args": {"name": label}})
        for span in trace.get("spans", ()):
            events.append({
                "ph": "X", "pid": PID, "tid": tid, "cat": "eval",
                "name": span["name"],
                "ts": base_us + span["start_ms"] * 1e3,
                "dur": max(0.0, span["duration_ms"] * 1e3),
                "args": _span_args(span),
            })
        tid += 1
    for evt in timeline or ():
        _t_mono, wall, kind, thread, a, b = evt
        events.append({
            "ph": "i", "s": "t", "pid": PID, "tid": TID_PIPELINE,
            "cat": "pipeline", "name": kind, "ts": wall * 1e6,
            "args": {"thread": thread, "a": a, "b": b},
        })
    for convoy in convoys or ():
        events.append({
            "ph": "X", "pid": PID, "tid": TID_CONVOYS, "cat": "convoy",
            "name": f"convoy width={convoy['width']}",
            "ts": convoy["start_unix"] * 1e6,
            "dur": max(0.0, convoy["duration_ms"] * 1e3),
            "args": {"width": convoy["width"],
                     "site": convoy.get("site", "")},
        })
    return events


def chrome_trace(traces: Iterable[dict],
                 timeline: Optional[Iterable[tuple]] = None,
                 convoys: Optional[Iterable[dict]] = None) -> dict:
    """The full Perfetto-loadable document."""
    return {
        "traceEvents": trace_events(traces, timeline, convoys),
        "displayTimeUnit": "ms",
        "otherData": {"source": "nomad_tpu contention observatory"},
    }


def validate_chrome_trace(doc: dict) -> List[str]:
    """Schema check for the export (the round-trip test and traceconv
    --validate both run this): returns a list of violations, empty when
    the document is loadable. Checks the fields Perfetto's importer
    actually requires, not a full spec."""
    errors: List[str] = []
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return ["document is not an object with a traceEvents list"]
    events = doc["traceEvents"]
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    for i, e in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(e, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = e.get("ph")
        if ph not in ("X", "M", "i", "b", "e"):
            errors.append(f"{where}: bad ph {ph!r}")
            continue
        if not isinstance(e.get("name"), str) or not e["name"]:
            errors.append(f"{where}: missing name")
        if not isinstance(e.get("pid"), int):
            errors.append(f"{where}: missing integer pid")
        if not isinstance(e.get("tid"), int):
            errors.append(f"{where}: missing integer tid")
        if ph == "M":
            if e.get("name") == "thread_name" and not (
                    isinstance(e.get("args"), dict)
                    and isinstance(e["args"].get("name"), str)):
                errors.append(f"{where}: thread_name without args.name")
            continue
        ts = e.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"{where}: bad ts {ts!r}")
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"{where}: bad dur {dur!r}")
        if ph == "i" and e.get("s") not in ("t", "p", "g"):
            errors.append(f"{where}: instant without scope s")
        if "args" in e and not isinstance(e["args"], dict):
            errors.append(f"{where}: args not an object")
    return errors
