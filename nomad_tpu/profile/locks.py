"""Profiled synchronization primitives: drop-in ``threading.Lock`` /
``RLock`` / ``Condition`` replacements that record per-declaration-site
acquire-wait and hold-time into the shared log-bucket histograms
(utils/metrics.py bucket math — the same ladder the flight recorder
reads percentiles off).

Design constraints, in order:

- **The uncontended path must stay cheap.** ``acquire`` first tries a
  non-blocking grab of the raw primitive; on success it pays one
  counter bump and one clock read. Only a CONTENDED acquire measures
  its wait (two clock reads) — the common case never times a wait that
  was zero.
- **Stats are guarded by the profiled lock itself.** Wait is recorded
  *after* acquisition, hold *before* release — both while the lock is
  held, so the per-instance ``_SiteStats`` needs no lock of its own and
  can never tear under concurrent writers. Instances sharing a
  declaration site (e.g. the trace recorder's 8 stripes) each own
  their stats; the profiler aggregates per site at READ time.
- **The record path never parks and never grows** (ntalint
  ``record-path-blocking``, manifest in profile/__init__.py): observes
  are arithmetic + subscript writes into preallocated bucket arrays.
- **ntalint still understands the locks.** ``ProfiledLock`` /
  ``ProfiledRLock`` / ``ProfiledCondition`` are registered lock
  constructors in analysis/locks.py, so ``# guarded-by:`` contracts,
  ``Condition(self._lock)`` aliasing, the lock-order deadlock detector
  and the dispatcher rule all keep working over wrapped call sites.

A reader snapshotting stats without the lock sees monotonic counters
mid-update — worst case a percentile is off by the one in-flight
sample, the same benign tear the recorder's ``enabled`` flag accepts.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..utils.metrics import LatencyHist, hist_percentile

_monotonic = time.monotonic


class _WaitHist(LatencyHist):
    """The shared fixed-size log-bucket histogram (utils/metrics.py
    LatencyHist — one implementation for the recorder AND the
    profiler; its observe leaf carries the record-path manifest) plus
    the profiler's read-side merge/stats helpers. Single-writer by
    construction wherever it is used (see module docstring)."""

    __slots__ = ()

    def merge_into(self, count, total, mx, buckets):
        """Accumulate this hist into running aggregates (read side)."""
        for i, c in enumerate(self.buckets):
            if c:
                buckets[i] += c
        return (count + self.count, total + self.total,
                max(mx, self.max))

    def stats(self) -> dict:
        count = self.count
        if not count:
            return {"count": 0}
        return {
            "count": count,
            "total_ms": round(self.total, 3),
            "mean_ms": round(self.total / count, 4),
            "max_ms": round(self.max, 3),
            "p50_ms": round(hist_percentile(self.buckets, count, 0.50), 4),
            "p95_ms": round(hist_percentile(self.buckets, count, 0.95), 4),
            "p99_ms": round(hist_percentile(self.buckets, count, 0.99), 4),
        }


class _SiteStats:
    """Per-lock-instance counters + histograms. Mutated only while the
    owning profiled lock is held (never torn); aggregated across
    same-site instances by the profiler's read side."""

    __slots__ = ("site", "kind", "acquires", "contended", "wait",
                 "hold", "cond_waits", "cond_wait")

    def __init__(self, site: str, kind: str):
        self.site = site
        self.kind = kind
        self.acquires = 0
        self.contended = 0
        self.wait = _WaitHist()       # contended acquire-wait (ms)
        self.hold = _WaitHist()       # critical-section hold (ms)
        self.cond_waits = 0
        self.cond_wait = _WaitHist()  # Condition.wait park (ms)


class ProfiledLock:
    """Drop-in ``threading.Lock`` recording acquire-wait + hold time.

    ``site`` names the DECLARATION site (e.g. ``"server.broker"``);
    instances sharing a site aggregate in the profiler's read side.
    """

    __slots__ = ("_lock", "stats", "_acquired_at", "_profiler",
                 "__weakref__")

    _KIND = "lock"

    def __init__(self, site: str = ""):
        self._lock = self._make_raw()
        self._acquired_at = 0.0
        from . import get_profiler

        # Bound once: the profiler is a process-lifetime singleton,
        # and re-resolving it through the import machinery on every
        # acquire/release of the hottest locks is measurable overhead
        # on exactly the paths the 5% budget gates.
        self._profiler = get_profiler()
        self.stats = self._profiler._register_lock(
            self, site or "anonymous", self._KIND)

    @staticmethod
    def _make_raw():
        return threading.Lock()

    def _raw(self):
        """The raw threading primitive (ProfiledCondition backs its
        threading.Condition with this so wait/notify semantics are the
        interpreter's own)."""
        return self._lock

    # ------------------------------------------------------- lock API

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        prof = self._profiler
        if not prof.enabled:
            return self._lock.acquire(blocking, timeout)
        st = self.stats
        if self._lock.acquire(False):
            # Uncontended: one clock read (the hold stamp), no wait
            # measurement — recording a zero costs more than it tells.
            st.acquires += 1
            self._acquired_at = _monotonic()
            return True
        if not blocking:
            return False
        t0 = _monotonic()
        got = self._lock.acquire(True, timeout)
        if not got:
            return False
        now = _monotonic()
        st.acquires += 1
        st.contended += 1
        wait_ms = (now - t0) * 1000.0
        st.wait.observe(wait_ms)
        prof._note_thread_wait(st.site, wait_ms)
        self._acquired_at = now
        return True

    def release(self) -> None:
        if self._profiler.enabled and self._acquired_at:
            self.stats.hold.observe(
                (_monotonic() - self._acquired_at) * 1000.0)
        # Cleared UNCONDITIONALLY: a stamp surviving a
        # disabled-profiler release would be read by a later
        # enabled-again release as one giant hold spanning the whole
        # disabled window (the bench A/B flips exactly this way).
        self._acquired_at = 0.0
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "ProfiledLock":
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()

    # ---------------------------------------- Condition.wait plumbing

    def _pause_hold(self):
        """Close the current hold interval (ProfiledCondition.wait is
        about to release the raw lock); returns opaque resume state."""
        if self._profiler.enabled and self._acquired_at:
            self.stats.hold.observe(
                (_monotonic() - self._acquired_at) * 1000.0)
        self._acquired_at = 0.0
        return None

    def _resume_hold(self, _state) -> None:
        """Reopen hold accounting after the raw lock was re-acquired
        inside Condition.wait."""
        self._acquired_at = _monotonic()


class ProfiledRLock(ProfiledLock):
    """Drop-in ``threading.RLock``: reentrant, hold time measured on
    the OUTERMOST hold. Owner/depth bookkeeping is wrapper-level (the
    raw RLock keeps its own) because ``Condition._release_save`` can
    release the raw lock underneath us — state is saved/restored around
    waits by ProfiledCondition via _pause_hold/_resume_hold."""

    __slots__ = ("_owner", "_depth")

    _KIND = "rlock"

    def __init__(self, site: str = ""):
        super().__init__(site)
        self._owner: Optional[int] = None
        self._depth = 0

    @staticmethod
    def _make_raw():
        return threading.RLock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        prof = self._profiler
        if not prof.enabled:
            got = self._lock.acquire(blocking, timeout)
            if got:
                me = threading.get_ident()
                if self._owner == me:
                    self._depth += 1
                else:
                    self._owner = me
                    self._depth = 1
            return got
        me = threading.get_ident()
        st = self.stats
        if self._owner == me:
            # Reentrant: raw acquire cannot block for the owner.
            self._lock.acquire()
            self._depth += 1
            st.acquires += 1
            return True
        if self._lock.acquire(False):
            st.acquires += 1
            self._owner = me
            self._depth = 1
            self._acquired_at = _monotonic()
            return True
        if not blocking:
            return False
        t0 = _monotonic()
        got = self._lock.acquire(True, timeout)
        if not got:
            return False
        now = _monotonic()
        st.acquires += 1
        st.contended += 1
        wait_ms = (now - t0) * 1000.0
        st.wait.observe(wait_ms)
        prof._note_thread_wait(st.site, wait_ms)
        self._owner = me
        self._depth = 1
        self._acquired_at = now
        return True

    def release(self) -> None:
        self._depth -= 1
        if self._depth == 0:
            self._owner = None
            if self._profiler.enabled and self._acquired_at:
                self.stats.hold.observe(
                    (_monotonic() - self._acquired_at) * 1000.0)
            self._acquired_at = 0.0
        self._lock.release()

    def locked(self) -> bool:
        # _thread.RLock grew .locked() only in 3.14; the drop-in
        # contract needs it everywhere. Owned-by-me answers without
        # touching the raw lock (a reentrant probe would succeed and
        # lie); otherwise a non-blocking probe settles it.
        if self._owner == threading.get_ident():
            return True
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def __enter__(self) -> "ProfiledRLock":
        self.acquire()
        return self

    def _pause_hold(self):
        state = (self._owner, self._depth)
        super()._pause_hold()
        self._owner = None
        self._depth = 0
        return state

    def _resume_hold(self, state) -> None:
        self._owner, self._depth = state
        self._acquired_at = _monotonic()


class ProfiledCondition:
    """Drop-in ``threading.Condition`` over a ProfiledLock/RLock.

    ``ProfiledCondition(self._lock, "site")`` aliases to its backing
    lock exactly like ``threading.Condition(self._lock)`` does (and
    ntalint's Condition-aliasing treats it the same way): entering the
    condition acquires — and profiles — the shared lock. ``wait``
    pauses the lock's hold accounting (the raw lock is released while
    parked), records the park duration into the site's cond-wait
    histogram, and resumes hold accounting on wake.
    """

    def __init__(self, lock=None, site: str = ""):
        if lock is None:
            lock = ProfiledLock(site or "anonymous.cond")
        if not isinstance(lock, ProfiledLock):
            raise TypeError(
                "ProfiledCondition requires a ProfiledLock/ProfiledRLock "
                "(wrap the backing lock too, or use threading.Condition)")
        self._plock = lock
        self._cond = threading.Condition(lock._raw())
        self.stats = lock.stats

    # Lock interface delegates to the profiled lock.
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return self._plock.acquire(blocking, timeout)

    def release(self) -> None:
        self._plock.release()

    def __enter__(self) -> "ProfiledCondition":
        self._plock.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._plock.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        prof = self._plock._profiler
        if not prof.enabled:
            state = self._plock._pause_hold()
            try:
                return self._cond.wait(timeout)
            finally:
                self._plock._resume_hold(state)
        st = self.stats
        state = self._plock._pause_hold()
        t0 = _monotonic()
        try:
            got = self._cond.wait(timeout)
        finally:
            # Raw lock re-acquired by Condition.wait; restore wrapper
            # ownership before anything else can observe it.
            self._plock._resume_hold(state)
        st.cond_waits += 1
        st.cond_wait.observe((_monotonic() - t0) * 1000.0)
        return got

    # No-timeout wait_for parks in bounded slices (unbounded-wait
    # discipline: the primitive itself must not hide a forever-park;
    # Condition semantics permit spurious wakeups, so re-checking the
    # predicate each slice is contract-clean).
    WAIT_FOR_SLICE_S = 1.0

    def wait_for(self, predicate, timeout: Optional[float] = None):
        """threading.Condition.wait_for semantics over profiled
        waits."""
        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = _monotonic() + timeout
                waittime = endtime - _monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait(self.WAIT_FOR_SLICE_S)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()
