"""Central dispatch pipeline: a leader-side placement service that
fills the device lanes.

The per-worker drain-then-place loop (server/worker.py) caps dispatch
occupancy at whatever one worker happens to find ready at its own
dequeue moment, and pays a full device round-trip per plan-conflict
retry. This package centralizes the dense path the way continuous-
batching inference servers centralize request admission: one drain,
full batches, pipelined submits, conflict retries folded back into the
accumulating batch. See pipeline.py for the stage breakdown.
"""

from .pipeline import DispatchPipeline, PipelineSession

__all__ = ["DispatchPipeline", "PipelineSession"]
