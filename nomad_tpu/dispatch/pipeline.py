"""The central placement pipeline for dense-path evaluations.

Three stages, pipelined the way the plan applier pipelines verify and
commit (reference nomad/plan_apply.go:19-39), applied one layer up to
device dispatch:

- **central drain** — every worker that dequeues a dense-factory eval
  hands it here instead of draining its own slice of the broker; the
  dispatcher tops the accumulating batch up with ONE
  broker.dequeue_many across everything ready, so a storm packs toward
  MAX_BATCH lanes instead of fragmenting into per-worker groups
  (measured r05: 9.4 of 64 lanes per dispatch).
- **pipelined launch** — a closed batch is fanned out to the stage
  pool and the dispatcher immediately resumes accumulating; up to
  `dispatch_max_inflight` batches run concurrently, so the next
  batch's evals build matrices and upload overlays WHILE the previous
  batch's device sync and plan submits are still in flight. Plan
  submission + ack runs on the stage/result threads, never on the
  dispatcher.
- **conflict requeue** — a plan the applier partially rejects
  (RefreshIndex) does not replan alone on a fresh snapshot (a 1-3
  alloc retry that pays a full round-trip, r05's retry tax); the eval
  is folded back into the ACCUMULATING batch and replans with the next
  full dispatch. In-batch collisions are already pre-resolved on
  device (ops/binpack.py PlacementConfig.pre_resolve), so requeues are
  the cross-batch residue only.

The pipeline preserves the worker path's contracts: per-job broker
serialization (a drained batch is always over distinct jobs), the
latency-aware host routing for sub-`dense_min_batch` batches, eval
ack/nack with the original broker token, and the nack-clock pause
while a plan waits in the plan queue.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import List, Optional, Tuple

from .. import profile, trace
from ..chaos import chaos
from ..profile import ProfiledCondition, ProfiledLock
from ..scheduler import new_scheduler
from ..server.worker import EvalSession
from ..structs import Evaluation, Plan, PlanResult, consts
from ..utils import metrics
from ..utils.backoff import poll_until

DEQUEUE_TOPUP_SLICE = 0.002  # cond-wait granularity while accumulating
SLOT_WAIT_SLICE = 0.02  # cond-wait granularity while all slots busy
WAIT_INDEX_TIMEOUT = 5.0

# ntalint lock-discipline manifest: functions reachable from these
# entrypoints run on the dispatcher thread and must never block (the
# accumulator IS the pipeline's clock — a blocked dispatcher stops
# batches from closing for every worker at once). Bounded cond-waits on
# the pipeline's own lock are the sanctioned scheduling primitive;
# everything slow (FSM catch-up, snapshotting, plan submit, device
# sync) belongs on the stage threads.
NTA_DISPATCHER_ENTRYPOINTS = ("DispatchPipeline._run",)


class _RequeueConflict(Exception):
    """Raised out of PipelineSession.submit_plan to abort the eval's
    current scheduling attempt: the plan was (partially) rejected and
    the eval should replan as part of the pipeline's accumulating
    batch instead of alone on a fresh snapshot."""


class _Pending:
    __slots__ = ("eval", "token", "requeues", "enqueued_at", "min_index")

    def __init__(self, ev: Evaluation, token: str, requeues: int = 0):
        self.eval = ev
        self.token = token
        self.requeues = requeues
        self.enqueued_at = time.monotonic()
        # Lowest FSM index this entry may replan against: a conflict
        # requeue records its plan's RefreshIndex here, so the relaunch
        # snapshot provably includes the eval's OWN partial commit (a
        # follower's FSM can lag the leader commit; replanning before
        # it replicates would double-place the committed allocs).
        self.min_index = 0


class PipelineSession(EvalSession):
    """Per-eval Planner for pipeline-processed evals. Inherits the
    whole Planner contract (pause-nack framing, eval updates, reblock,
    pre_resolve wiring) from server/worker.py EvalSession — one
    implementation to keep in sync — and overrides only the
    plan-conflict handling: refreshes raise _RequeueConflict (bounded,
    side-effect-guarded) so the retry rides the ACCUMULATING batch
    instead of replanning alone."""

    def __init__(self, pipeline: "DispatchPipeline", entry: _Pending,
                 announced: bool = False):
        # EvalSession only needs `.server` and `._wait_for_index` from
        # its worker — the pipeline provides both.
        super().__init__(pipeline, entry.eval, entry.token)
        self.pipeline = pipeline
        self.entry = entry
        # True while this eval is counted in the batcher's announced
        # cohort (add_cohort); consumed at place() time or repaid on
        # host fallback (scheduler/tpu.py) / eval completion
        # (_repay_unconsumed).
        self.announced_cohort = announced
        # Evals created this attempt (blocked / rolling follow-ups):
        # once any exist, aborting the attempt would re-create them on
        # the requeued run — fall back to the inline retry instead.
        self.created_evals = 0

    def submit_plan(self, plan: Plan) -> Tuple[PlanResult, Optional[object]]:
        start = time.monotonic()
        plan.eval_token = self.token
        if chaos.enabled:
            # 'error' = the submit RPC fails (leader flap mid-batch);
            # the eval nacks and redelivers. 'delay' = a slow plan
            # queue.
            chaos.fire("dispatch.submit", eval_id=self.eval.id)
        try:
            self.server.eval_pause_nack(self.eval.id, self.token)
        except ValueError:
            pass
        try:
            result = self.server.plan_submit(plan)
        finally:
            try:
                self.server.eval_resume_nack(self.eval.id, self.token)
            except ValueError:
                pass
        self.pipeline._note_submit(start)
        trace.record_span(self.eval.id, trace.STAGE_PLAN_SUBMIT, start,
                          trace_id=self.eval.trace_id)
        if result.refresh_index:
            self.pipeline._note_conflict()
            if (self.created_evals == 0
                    and self.entry.requeues < self.pipeline.max_requeues):
                # Replan as part of the next packed batch — which must
                # snapshot at or past this plan's partial commit.
                self.entry.min_index = max(self.entry.min_index,
                                           result.refresh_index)
                raise _RequeueConflict()
            # Bounded out (or side effects exist): classic inline
            # retry — catch local state up, hand back a fresh snapshot.
            self.pipeline._note_inline_retry()
            self.pipeline._wait_for_index(
                result.refresh_index, WAIT_INDEX_TIMEOUT)
            return result, self.server.fsm.state.snapshot()
        return result, None

    def create_eval(self, ev: Evaluation) -> None:
        self.created_evals += 1
        super().create_eval(ev)


class DispatchPipeline:
    def __init__(self, server):
        self.server = server
        cfg = server.config
        self.logger = logging.getLogger("nomad_tpu.dispatch")
        self.max_batch = max(1, cfg.eval_batch_size)
        self.max_inflight = max(1, cfg.dispatch_max_inflight)
        self.window = cfg.dispatch_window
        self.idle_grace = cfg.dispatch_idle_grace
        self.max_requeues = cfg.dispatch_max_requeues
        self.pre_resolve = cfg.dense_pre_resolve
        # The eval types whose factories are dense — what the central
        # drain pulls from the broker.
        from ..server.worker import is_dense_factory

        self.types: List[str] = [
            t for t in cfg.enabled_schedulers
            if is_dense_factory(cfg.factory_for(t))
        ]
        # The scheduler executive (server/executive.py) supersedes the
        # pipeline when enabled: both own the central dense drain, and
        # two drains racing the broker would split every storm into
        # half-filled cohorts.
        self.enabled = bool(
            cfg.dispatch_pipeline and self.types
            and cfg.eval_batch_size > 1 and not cfg.scheduler_executive
        )

        # Profiled (nomad_tpu/profile): the accumulator lock every
        # worker handoff and batch cut crosses.
        self._lock = ProfiledLock("dispatch.pipeline")
        self._cond = ProfiledCondition(self._lock, "dispatch.pipeline")
        self._pending: List[_Pending] = []  # guarded-by: _lock
        self._inflight = 0  # guarded-by: _lock
        # Run-queue delay measurement (work announced -> dispatcher
        # actually running): _admit stamps _notified_at ONLY while the
        # dispatcher is parked on the seed wait (_drain_waiting) — a
        # notify that lands mid-top-up wakes nothing, and timing it
        # would read the whole accumulation window as scheduling delay.
        self._notified_at = 0.0  # guarded-by: _lock
        self._drain_waiting = False  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.drained = 0  # guarded-by: _lock (evals requeued by drain())
        self.finish_dropped = 0  # guarded-by: _lock (chaos dispatch.finish)
        self.expired_dropped = 0  # guarded-by: _lock (deadline at launch)
        self.breaker_routed = 0  # guarded-by: _lock (host via open breaker)

        # ---- stats ----
        self.evals_in = 0  # guarded-by: _lock (handed off / requeued)
        self.batches = 0  # guarded-by: _lock (batches launched)
        self.dispatched_evals = 0  # guarded-by: _lock (sum batch sizes)
        self.largest_batch = 0  # guarded-by: _lock
        self.routed_host = 0  # guarded-by: _lock (sent to host factory)
        self.acked = 0  # guarded-by: _lock
        self.nacked = 0  # guarded-by: _lock
        self.plan_conflicts = 0  # guarded-by: _lock (RefreshIndex'd)
        self.requeues = 0  # guarded-by: _lock (retries via accumulator)
        self.requeues_batched = 0  # guarded-by: _lock (joined a batch)
        self.inline_retries = 0  # guarded-by: _lock (classic retries)
        self.prefetches = 0  # guarded-by: _lock (base prefetch calls)
        self.prefetch_bytes = 0  # guarded-by: _lock (host->device bytes)
        self.t_drain = 0.0  # guarded-by: _lock (time in accumulator)
        self.t_process = 0.0  # guarded-by: _lock (scheduler invoke)
        self.t_submit = 0.0  # guarded-by: _lock (plan queue + commit)

    # ------------------------------------------------------- lifecycle

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="dispatch-pipeline", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        # Accumulated evals must not die with the pipeline: hand their
        # leases back so another server's workers redeliver them.
        self.drain()

    def drain(self) -> int:
        """Leadership loss (or shutdown): requeue every accumulated
        eval into the broker by nacking its outstanding token, so
        nothing a batch had in hand is lost and nothing double-places.

        Extends the PR 1 requeue path one failure class further: a
        conflict requeue re-enters the ACCUMULATING batch with its
        token still outstanding; a drain gives the token BACK — on the
        (old) leader the nack re-readies the eval immediately, and when
        the broker is already disabled/flushed (a real flap) the nack
        fails cleanly and the new leader re-seeds the eval from raft
        state (_restore_evals), since an undelivered eval is still
        status=pending there. In-flight-but-unacked batch members need
        no sweep: their stage threads' acks fail against the flushed
        broker and the same restore covers them, while the plan-queue
        token guard (plan_submit checks the OUTSTANDING token) keeps a
        stale batch from committing a double placement."""
        with self._cond:
            pending, self._pending = self._pending, []
            self._cond.notify_all()
        for entry in pending:
            self._finish(entry, acked=False)
        if pending:
            with self._lock:
                self.drained += len(pending)
            self.logger.info(
                "drained %d accumulated evals back to the broker",
                len(pending))
        return len(pending)

    # ------------------------------------------------------ admission

    def submit(self, ev: Evaluation, token: str) -> None:
        """Hand a dequeued dense-path eval to the pipeline (worker
        handoff, and the conflict-requeue re-entry)."""
        self._admit(_Pending(ev, token))

    def _admit(self, entry: _Pending) -> None:
        entry.enqueued_at = time.monotonic()
        with self._cond:
            self._pending.append(entry)
            self.evals_in += 1
            if self._drain_waiting and not self._notified_at:
                # Stamped HERE, lock held, right before the notify —
                # not entry.enqueued_at: the admitter's own wait for
                # this lock is already measured by the lock's wait
                # histogram, and folding it in would double-count
                # admit-side contention as dispatcher wake latency.
                self._notified_at = time.monotonic()
            self._cond.notify_all()

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def saturated(self) -> bool:
        """Intake-backpressure signal for the worker handoff
        (server/worker.py): True while the accumulator already holds
        two full batches' worth of evals. A saturated pipeline must not
        keep draining the broker — evals held here are invisible to the
        bounded ready queues (nomad_tpu/admission), so an unbounded
        drain would reopen exactly the intake the depth caps close."""
        with self._lock:
            return len(self._pending) >= 2 * self.max_batch

    # ------------------------------------------------------ dispatcher

    def _run(self) -> None:
        while not self._stop.is_set():
            batch = self._accumulate()
            if batch:
                # The launch prologue BLOCKS — _wait_for_index
                # sleep-polls the FSM for up to WAIT_INDEX_TIMEOUT and
                # snapshotting walks every table — so it runs on a
                # stage thread. The dispatcher goes straight back to
                # accumulating: the next batch keeps filling while this
                # one catches up to its snapshot index (previously a
                # follower lagging the leader commit froze ALL lanes
                # for the duration, not just this batch's).
                # _accumulate already took the in-flight slot, so the
                # pipelining bound still holds while the launch is in
                # hand-off.
                self.server.eval_pool.submit(self._launch, batch)

    def _accumulate(self) -> List[_Pending]:
        """Pack the next batch: wait for a seed eval, then top up with
        one central broker drain per pass. Close rules: a FULL batch
        closes immediately; an idle pipeline closes after `idle_grace`
        (a lone interactive eval must not marinate); while batches are
        in flight the accumulator keeps filling for `window` — the
        in-flight round-trip is exactly the budget this wait amortizes
        — and when every slot is busy it simply keeps accumulating
        until one frees."""
        with self._cond:
            self._drain_waiting = True
            try:
                while not self._pending and not self._stop.is_set():
                    self._cond.wait(0.25)
            finally:
                self._drain_waiting = False
            if not self._pending:
                self._notified_at = 0.0
                return []
            # Run-queue delay at the broker-drain point: notify-while-
            # parked -> this thread actually running — the dispatcher's
            # wake latency under GIL pressure, nothing else (the top-up
            # window and slot waits are deliberate batching time and
            # are measured by t_drain, not here).
            if self._notified_at:
                profile.record_runq(
                    "broker_drain",
                    (time.monotonic() - self._notified_at) * 1000.0)
                self._notified_at = 0.0
            profile.event("accumulate_open", "dispatcher",
                          a=len(self._pending))
        start = time.monotonic()
        while not self._stop.is_set():
            with self._lock:
                room = self.max_batch - len(self._pending)
            if room > 0:
                # The central drain: everything ready across the
                # broker, not one worker's slice.
                got = self.server.eval_dequeue_many(self.types, room)
                if got:
                    now = time.monotonic()
                    with self._cond:
                        for ev, token in got:
                            entry = _Pending(ev, token)
                            entry.enqueued_at = now
                            self._pending.append(entry)
                            self.evals_in += 1
            with self._cond:
                elapsed = time.monotonic() - start
                if len(self._pending) >= self.max_batch:
                    break
                if self._inflight == 0:
                    if elapsed >= self.idle_grace:
                        break
                elif (self._inflight < self.max_inflight
                      and elapsed >= self.window):
                    break
                self._cond.wait(DEQUEUE_TOPUP_SLICE)
        # Wait for an in-flight slot; late arrivals keep joining the
        # pending list while we wait (that IS the adaptive window).
        with self._cond:
            while (self._inflight >= self.max_inflight
                   and not self._stop.is_set()):
                self._cond.wait(SLOT_WAIT_SLICE)
            batch = self._pending[: self.max_batch]
            del self._pending[: len(batch)]
            if not batch:
                return []
            self._inflight += 1
            self.batches += 1
            self.dispatched_evals += len(batch)
            self.largest_batch = max(self.largest_batch, len(batch))
            now = time.monotonic()
            for entry in batch:
                self.t_drain += now - entry.enqueued_at
                if entry.requeues and len(batch) > 1:
                    self.requeues_batched += 1
            profile.event("accumulate_close", "dispatcher",
                          a=len(batch), b=self.batches)
        metrics.add_sample(("dispatch", "batch_size"), len(batch))
        return batch

    def _launch(self, batch: List[_Pending]) -> None:
        # Trace: the accumulate stage closes when the batch is cut.
        # Recorded HERE (stage thread) rather than in _accumulate so
        # the dispatcher thread carries zero extra work per batch.
        t_launch = time.monotonic()
        # Deadline enforcement BEFORE any matrix build or cohort
        # announcement: an expired eval must not burn a device lane on
        # a plan its submitter already gave up on (nomad_tpu/admission
        # deadline semantics; the broker enforces the same bound at
        # dequeue, this covers time spent accumulating).
        batch = self._drop_expired(batch, t_launch)
        if not batch:
            with self._cond:
                self._inflight -= 1
                self._cond.notify_all()
            return
        for entry in batch:
            trace.record_span(
                entry.eval.id, trace.STAGE_DISPATCH_ACCUMULATE,
                entry.enqueued_at, t_launch,
                ann={"batch": len(batch), "requeues": entry.requeues},
                trace_id=entry.eval.trace_id)
        # The whole prologue is guarded: it runs on a pool thread now,
        # where an escaped exception dies into an unread PoolFuture —
        # and the slot _accumulate took would leak, wedging the
        # accumulator once max_inflight failed launches pile up.
        try:
            prologue = self._launch_prologue(batch)
        except Exception:
            self.logger.exception(
                "batch launch failed; nacking %d evals", len(batch))
            prologue = None
        for entry in batch:
            trace.record_span(
                entry.eval.id, trace.STAGE_DISPATCH_LAUNCH, t_launch,
                ann=({"failed": True} if prologue is None else None),
                trace_id=entry.eval.trace_id)
        # Single abort call site: an abort raising INSIDE the try must
        # never be re-entered by the except path (double slot release).
        if prologue is None:
            self._abort_batch(batch)
            return
        # Fan-out needs no guard: WorkPool.submit enqueues then NEVER
        # raises (a failed worker spawn is swallowed and retried on the
        # next submit — utils/pool.py), so every entry is handed off
        # exactly once and releases the slot via `remaining`. A
        # partial-fan-out cleanup here would double-finish entries the
        # pool still runs.
        snapshot, route_host = prologue
        profile.event("launch", "stage", a=len(batch), b=int(route_host))
        remaining = [len(batch)]
        for entry in batch:
            self.server.eval_pool.submit(
                self._process_entry, entry, snapshot, route_host,
                remaining)

    def _drop_expired(self, batch: List[_Pending],
                      t_launch: float) -> List[_Pending]:
        """Split out entries whose deadline passed while accumulating,
        terminalize them (status=failed with a structured reason +
        ack), and return the live remainder. Runs on a stage thread."""
        now = time.time()
        live: List[_Pending] = []
        expired: List[_Pending] = []
        for entry in batch:
            if entry.eval.expired(now):
                expired.append(entry)
            else:
                live.append(entry)
        if not expired:
            return batch
        with self._lock:
            self.expired_dropped += len(expired)
        metrics.incr_counter(("dispatch", "expired_dropped"),
                             len(expired))
        for entry in expired:
            trace.record_span(
                entry.eval.id, trace.STAGE_DISPATCH_ACCUMULATE,
                entry.enqueued_at, t_launch,
                ann={"expired": True, "deadline": entry.eval.deadline},
                trace_id=entry.eval.trace_id)
            self._finish_expired(entry)
        return live

    def _finish_expired(self, entry: _Pending) -> None:
        """Persist the structured terminal outcome for one expired
        entry, then release its broker lease. On a leader flap either
        write can fail — the nack timer redelivers and the broker's
        dequeue-side deadline check parks it structured there instead,
        so the eval still reaches exactly one terminal outcome."""
        upd = entry.eval.copy()
        upd.status = consts.EVAL_STATUS_FAILED
        upd.status_description = (
            f"deadline expired before dispatch: deadline "
            f"{entry.eval.deadline:.3f} passed while accumulating "
            f"(originally triggered by {entry.eval.triggered_by!r})")
        try:
            self.server.eval_update([upd])
        except Exception:
            self.logger.warning(
                "expired-eval terminal write for %s failed; broker "
                "deadline check will re-park it", entry.eval.id,
                exc_info=True)
            self._finish(entry, acked=False)
            return
        self._finish(entry, acked=True)

    def _abort_batch(self, batch: List[_Pending]) -> None:
        """Nack every entry and release the in-flight slot
        _accumulate took for this batch. The release is in a finally:
        aborts run exactly when the leader is unreachable, so the
        nacks themselves may fail — a slot leak here would wedge the
        accumulator after max_inflight failed aborts."""
        try:
            for entry in batch:
                self._finish(entry, acked=False)
        finally:
            with self._cond:
                self._inflight -= 1
                self._cond.notify_all()

    def _launch_prologue(self, batch: List[_Pending]):
        """(snapshot, route_host) for a launchable batch, None when the
        FSM never caught up to the batch's snapshot index. Returned,
        not stored: concurrent launches each carry their own."""
        if chaos.enabled:
            # 'error' = the launch prologue dies (snapshot/catch-up
            # failure): _launch aborts the batch, every eval nacks and
            # redelivers. 'delay' = a follower lagging the leader.
            chaos.fire("dispatch.launch", batch=len(batch))
        cfg = self.server.config
        # Latency-aware routing, centralized: a batch too small to
        # amortize the device dispatch runs on the host factories with
        # identical placement semantics (parity-tested).
        route_host = len(batch) < cfg.dense_min_batch
        if not route_host:
            # Device-path circuit breaker (admission/breaker.py): an
            # OPEN breaker inside its cool-down routes the whole batch
            # to the host factories up front — no matrix build against
            # a sick device path, no cohort announcement to repay.
            # This is the NON-consuming hint: once the cool-down
            # elapses it goes quiet and the dense path's acquire() gate
            # (scheduler/tpu.py) sends exactly one half-open probe.
            from ..admission import get_breaker

            if get_breaker().should_route_host():
                route_host = True
                with self._lock:
                    self.breaker_routed += len(batch)
                metrics.incr_counter(
                    ("dispatch", "breaker_route_host"), len(batch))
        if route_host:
            with self._lock:
                self.routed_host += len(batch)
            metrics.incr_counter(("dispatch", "route_host"), len(batch))
        # One MVCC snapshot for the whole batch: every member plans
        # against the same cluster state so their ClusterMatrix bases
        # share one token, one device upload, and (pre_resolve) one
        # serialized claim scan. Same invariant as the worker drain
        # path; optimistic concurrency keeps it safe.
        max_index = max(max(e.eval.modify_index, e.min_index)
                        for e in batch)
        if not self._wait_for_index(max_index, WAIT_INDEX_TIMEOUT):
            return None
        snapshot = self.server.fsm.state.snapshot()
        if not route_host:
            # Announce the fan-out to the batcher: its dispatch window
            # then waits for this whole batch's place() calls (their
            # matrix builds stagger under the GIL) instead of shipping
            # fragmented, third-full device dispatches. System-dense
            # evals are excluded — DenseSystemScheduler's vectorized
            # pass never touches the batcher, so announcing them would
            # only stretch the window (the hint self-heals either way,
            # COHORT_WAIT_MAX). Generic dense evals that fall back to
            # the host path repay their announcement in
            # scheduler/tpu.py.
            announce = sum(
                1 for e in batch
                if e.eval.type != consts.JOB_TYPE_SYSTEM)
            if announce:
                from ..scheduler.batcher import get_batcher

                get_batcher().add_cohort(announce)
            self._prefetch_bases(batch, snapshot)
        return snapshot, route_host

    def _prefetch_bases(self, batch: List[_Pending], snapshot) -> None:
        """Async double-buffering, host side: make this batch's cluster
        base(s) device-resident NOW — on this stage thread, while the
        PREVIOUS batch's device compute and plan submits are still in
        flight (`dispatch_max_inflight` overlaps them) — so the batch's
        evals find their base token already cached at place() time and
        the (tiny) delta transfer hides under compute instead of
        serializing in front of its own dispatch. The base is
        job-independent; distinct datacenter sets across the batch's
        jobs each resolve one base. Failures are non-fatal: place()
        falls back to uploading synchronously, exactly as before."""
        from ..models.matrix import prefetch_cluster_base
        from ..models.resident import get_tracker
        from ..scheduler.batcher import get_batcher

        if not get_tracker().is_enabled():
            return
        dc_sets = {}
        for entry in batch:
            if entry.eval.type == consts.JOB_TYPE_SYSTEM:
                # DenseSystemScheduler builds its matrix over explicit
                # pinned nodes (a different cache family) and never
                # touches the batcher — same exclusion as the cohort
                # announce above.
                continue
            job = snapshot.job_by_id(entry.eval.job_id)
            if job is None:
                continue
            dc_sets.setdefault(
                tuple(sorted(job.datacenters or [])), []).append(entry)
        batcher = get_batcher()
        for dcs, entries in dc_sets.items():
            t0 = time.monotonic()
            try:
                view, kind = prefetch_cluster_base(snapshot, list(dcs))
                nbytes = batcher.prefetch_base(view) if view else 0
            except Exception:
                self.logger.warning(
                    "base prefetch failed; place() will upload "
                    "synchronously", exc_info=True)
                continue
            with self._lock:
                self.prefetches += 1
                self.prefetch_bytes += nbytes
            metrics.incr_counter(("dispatch", "prefetch_bytes"), nbytes)
            profile.event("prefetch", "stage", a=int(nbytes))
            # One span per eval riding this base: stage attribution for
            # the new path (the bytes shipped are the batch's WHOLE
            # host->device traffic when the delta path holds).
            for entry in entries:
                trace.record_span(
                    entry.eval.id, trace.STAGE_DEVICE_TRANSFER, t0,
                    ann={"bytes": nbytes, "kind": kind},
                    trace_id=entry.eval.trace_id)

    # ---------------------------------------------------------- stages

    def _process_entry(self, entry: _Pending, snapshot, route_host: bool,
                       remaining: List[int]) -> None:
        ev, token = entry.eval, entry.token
        start = time.monotonic()
        # Lock-wait attribution for this stage: the profiler keeps a
        # per-thread contended-wait total; the delta across the
        # scheduler invoke lands on the span so a slow scheduler.process
        # can be read as "blocked on locks" vs "actually computing".
        wait0 = profile.thread_wait_ms()
        session = PipelineSession(
            self, entry,
            announced=(not route_host
                       and ev.type != consts.JOB_TYPE_SYSTEM))
        try:
            if chaos.enabled:
                # 'delay' = a stalled stage consumer (a wedged
                # scheduler thread): the eval sits in process, the e2e
                # p99 inflates, and the pressure monitor must see it —
                # the overload soak forces consumer stalls through this
                # site. 'error' = the consumer dies; the eval nacks and
                # redelivers via the except path below.
                chaos.fire("admission.slow_consumer", eval_id=ev.id)
            factory = self.server.config.factory_for(ev.type)
            if route_host:
                from ..server.worker import host_factory

                factory = host_factory(factory)
            # Independent PRNG per eval (see worker.py: correlated
            # tie-break streams spike plan conflicts).
            rng = random.Random(int.from_bytes(os.urandom(8), "little"))
            sched = new_scheduler(
                factory, self.logger, snapshot, session, rng=rng)
            sched.process_eval(ev)
        except _RequeueConflict:
            with self._lock:
                self.requeues += 1
                self.t_process += time.monotonic() - start
            trace.record_span(ev.id, trace.STAGE_SCHED_PROCESS, start,
                              ann={"path": "pipeline", "requeued": True},
                              trace_id=ev.trace_id)
            metrics.incr_counter(("dispatch", "requeue"))
            self._repay_unconsumed(session)
            # Back into the ACCUMULATING batch; the broker token stays
            # outstanding, so per-job serialization still holds.
            entry.requeues += 1
            self._release_slot(remaining)
            self._admit(entry)
            return
        except Exception:
            self.logger.exception("pipeline eval %s failed", ev.id)
            with self._lock:
                self.t_process += time.monotonic() - start
            trace.record_span(ev.id, trace.STAGE_SCHED_PROCESS, start,
                              ann={"path": "pipeline", "failed": True},
                              trace_id=ev.trace_id)
            self._repay_unconsumed(session)
            self._finish(entry, acked=False)
            self._release_slot(remaining)
            return
        with self._lock:
            self.t_process += time.monotonic() - start
        trace.record_span(
            ev.id, trace.STAGE_SCHED_PROCESS, start,
            ann={"path": "pipeline", "route_host": route_host,
                 "lock_wait_ms": round(
                     profile.thread_wait_ms() - wait0, 3)},
            trace_id=ev.trace_id)
        self._repay_unconsumed(session)
        self._finish(entry, acked=True)
        self._release_slot(remaining)

    def _repay_unconsumed(self, session: PipelineSession) -> None:
        """Repay a cohort unit this eval announced but never consumed:
        placement-less evals (job stop, scale-down, in-place-only
        update) and failed schedulers never reach the batcher, and an
        unrepaid announcement stretches every subsequent partial
        dispatch toward COHORT_WAIT_MAX. The dense scheduler flips
        announced_cohort off right before its place() call, so a
        consumed announcement is never repaid twice."""
        if session.announced_cohort:
            session.announced_cohort = False
            from ..scheduler.batcher import get_batcher

            get_batcher().cohort_cancel(1)

    def _finish(self, entry: _Pending, acked: bool) -> None:
        if chaos.enabled and chaos.fire(
                "dispatch.finish", eval_id=entry.eval.id) == "drop":
            # Injected worker crash holding an unacked eval: neither
            # ack nor nack goes out — the broker's nack timer is the
            # recovery path and MUST reclaim it (asserted by the soak).
            with self._lock:
                self.finish_dropped += 1
            return
        try:
            if acked:
                self.server.eval_ack(entry.eval.id, entry.token)
            else:
                self.server.eval_nack(entry.eval.id, entry.token)
        except ValueError:
            pass  # nack timer fired concurrently
        except Exception:
            # On a follower the ack/nack is an RPC to the leader and
            # fails exactly when aborts happen (leader flap). The
            # broker's nack timer reclaims the eval either way; raising
            # out of a stage thread would leak slot accounting instead.
            self.logger.warning(
                "eval %s %s failed; nack timer will reclaim",
                entry.eval.id, "ack" if acked else "nack",
                exc_info=True)
        with self._lock:
            if acked:
                self.acked += 1
            else:
                self.nacked += 1
        profile.event("ack", a=int(acked))

    def _release_slot(self, remaining: List[int]) -> None:
        with self._cond:
            remaining[0] -= 1
            if remaining[0] == 0:
                self._inflight -= 1
                self._cond.notify_all()

    # ------------------------------------------------------- plumbing

    def _wait_for_index(self, index: int, timeout: float) -> bool:
        # Runs on stage threads only (never the dispatcher); the shared
        # jittered-backoff poll replaces the ad-hoc doubling loop.
        return poll_until(
            lambda: self.server.fsm.state.latest_index() >= index,
            timeout, stop=self._stop, base=0.001, max_delay=0.1)

    def _note_submit(self, start: float) -> None:
        dt = time.monotonic() - start
        with self._lock:
            self.t_submit += dt
        metrics.measure_since(("dispatch", "submit_plan"), start)
        profile.event("submit", a=round(dt * 1000.0, 3))

    def _note_conflict(self) -> None:
        with self._lock:
            self.plan_conflicts += 1
        metrics.incr_counter(("dispatch", "plan_conflict"))

    def _note_inline_retry(self) -> None:
        with self._lock:
            self.inline_retries += 1

    # ----------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            batches = self.batches
            dispatched = self.dispatched_evals
            done = self.acked + self.nacked
            retries = self.requeues + self.inline_retries
            return {
                "enabled": self.enabled,
                "max_batch": self.max_batch,
                "batches": batches,
                "dispatched_evals": dispatched,
                # Lanes filled per launched batch (the r05 headline
                # bottleneck: 9.4/64).
                "occupancy": round(dispatched / batches, 2) if batches else 0.0,
                "occupancy_frac": round(
                    dispatched / (batches * self.max_batch), 4
                ) if batches else 0.0,
                "largest_batch": self.largest_batch,
                "in_flight": self._inflight,
                "pending": len(self._pending),
                "evals_in": self.evals_in,
                "acked": self.acked,
                "nacked": self.nacked,
                "routed_host": self.routed_host,
                "plan_conflicts": self.plan_conflicts,
                "requeues": self.requeues,
                "requeues_batched": self.requeues_batched,
                "inline_retries": self.inline_retries,
                "drained": self.drained,
                "finish_dropped": self.finish_dropped,
                "expired_dropped": self.expired_dropped,
                "breaker_routed": self.breaker_routed,
                "prefetches": self.prefetches,
                "prefetch_bytes": self.prefetch_bytes,
                "retries_per_eval": round(retries / done, 4) if done else 0.0,
                # Cumulative stage latencies (divide by the matching
                # counters for per-unit): microseconds, like the
                # batcher's breakdown.
                "drain_us": int(self.t_drain * 1e6),
                "process_us": int(self.t_process * 1e6),
                "submit_us": int(self.t_submit * 1e6),
            }
