"""`${var}` interpolation for job fields.

Reference: helper/args (ReplaceEnv) + client/driver/env/env.go
(ParseAndReplace) — task env values, driver config strings, and service
names/tags may reference `${NOMAD_*}` variables (and node attributes in
constraint targets, scheduler/feasible.py). Unknown variables are left
verbatim, matching the reference's behavior.
"""

from __future__ import annotations

import re
from typing import Any, Dict

_VAR_RE = re.compile(r"\$\{([^}]+)\}")


def replace_env(text: str, env: Dict[str, str]) -> str:
    def sub(m: re.Match) -> str:
        val = env.get(m.group(1).strip())
        return val if val is not None else m.group(0)

    return _VAR_RE.sub(sub, text)


def interpolate_value(value: Any, env: Dict[str, str]) -> Any:
    """Recursively interpolate strings inside config-shaped values."""
    if isinstance(value, str):
        return replace_env(value, env)
    if isinstance(value, list):
        return [interpolate_value(v, env) for v in value]
    if isinstance(value, dict):
        return {k: interpolate_value(v, env) for k, v in value.items()}
    return value
