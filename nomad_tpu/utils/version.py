"""Version parsing and constraint checking for `version` constraints.

Reference behavior: hashicorp/go-version used by scheduler/feasible.go:380
(checkVersionConstraint). Supports constraint strings like
">= 1.2, < 2.0", "= 1.2.3", "~> 1.2" (pessimistic operator).
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

_VERSION_RE = re.compile(
    r"^v?(\d+(?:\.\d+)*)(?:-([0-9A-Za-z.\-~]+))?(?:\+([0-9A-Za-z.\-~]+))?$"
)


class Version:
    __slots__ = ("segments", "prerelease", "raw")

    def __init__(self, raw: str):
        m = _VERSION_RE.match(raw.strip())
        if not m:
            raise ValueError(f"malformed version: {raw!r}")
        self.raw = raw
        segs = [int(p) for p in m.group(1).split(".")]
        while len(segs) < 3:
            segs.append(0)
        self.segments = tuple(segs)
        self.prerelease = m.group(2) or ""

    def __lt__(self, other: "Version") -> bool:
        if self.segments != other.segments:
            return self.segments < other.segments
        if bool(self.prerelease) != bool(other.prerelease):
            return bool(self.prerelease)  # prerelease < release
        return _prerelease_lt(self.prerelease, other.prerelease)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Version)
            and self.segments == other.segments
            and self.prerelease == other.prerelease
        )

    def __le__(self, other):
        return self == other or self < other

    def __gt__(self, other):
        return not self <= other

    def __ge__(self, other):
        return not self < other

    def __hash__(self):
        return hash((self.segments, self.prerelease))

    def __repr__(self):
        return f"Version({self.raw!r})"


def _prerelease_lt(a: str, b: str) -> bool:
    """Semver prerelease ordering: dot-separated identifiers compare
    per-identifier, numeric ones numerically and below alphanumeric
    (so rc.9 < rc.10 and beta.2 < beta.11)."""
    for ai, bi in zip(a.split("."), b.split(".")):
        a_num, b_num = ai.isdigit(), bi.isdigit()
        if a_num and b_num:
            if int(ai) != int(bi):
                return int(ai) < int(bi)
        elif a_num != b_num:
            return a_num  # numeric identifiers sort below alphanumeric
        elif ai != bi:
            return ai < bi
    return len(a.split(".")) < len(b.split("."))


_CONSTRAINT_RE = re.compile(r"^\s*(=|!=|>=|<=|>|<|~>)?\s*([^\s,]+)\s*$")


class Constraint:
    __slots__ = ("op", "version", "precision")

    def __init__(self, raw: str):
        m = _CONSTRAINT_RE.match(raw)
        if not m:
            raise ValueError(f"malformed constraint: {raw!r}")
        self.op = m.group(1) or "="
        ver_str = m.group(2)
        # Track how many segments were written, for the pessimistic operator.
        core = ver_str.lstrip("v").split("-")[0].split("+")[0]
        self.precision = len(core.split("."))
        self.version = Version(ver_str)

    def check(self, v: Version) -> bool:
        c = self.version
        if self.op == "=":
            return v == c
        if self.op == "!=":
            return v != c
        if self.op == ">":
            return v > c
        if self.op == "<":
            return v < c
        if self.op == ">=":
            return v >= c
        if self.op == "<=":
            return v <= c
        if self.op == "~>":
            # ~> 1.2   allows >= 1.2, < 2.0
            # ~> 1.2.3 allows >= 1.2.3, < 1.3.0
            if v < c:
                return False
            lock = max(self.precision - 1, 1)
            return v.segments[:lock] == c.segments[:lock]
        return False


class Constraints:
    """A comma-separated conjunction of constraints."""

    def __init__(self, raw: str):
        parts = [p for p in raw.split(",") if p.strip()]
        if not parts:
            raise ValueError("empty constraint")
        self.constraints = [Constraint(p) for p in parts]

    def check(self, v: Version) -> bool:
        return all(c.check(v) for c in self.constraints)


def parse_version(raw: str) -> Optional[Version]:
    try:
        return Version(raw)
    except ValueError:
        return None


def parse_constraints(raw: str) -> Optional[Constraints]:
    try:
        return Constraints(raw)
    except ValueError:
        return None
