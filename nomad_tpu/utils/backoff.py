"""Jittered exponential backoff with per-attempt and overall deadlines.

The one retry/pacing primitive for the recovery paths: transport
redials, follower->leader forwarding, FSM catch-up polls in the
dispatch pipeline and workers, and the executor launch wait all ride
this instead of hand-rolled ``time.sleep`` loops (each of which had
its own cap, no jitter, and no shutdown check). Jitter matters at
fleet scale: a leader flap makes every follower retry at once, and
un-jittered exponential backoff keeps them synchronized into thundering
herds forever.

Defaults: base 20ms doubling to a 2s cap, ±25% jitter.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

DEFAULT_BASE = 0.02
DEFAULT_FACTOR = 2.0
DEFAULT_MAX_DELAY = 2.0
DEFAULT_JITTER = 0.25


class Backoff:
    """Stateful backoff: each sleep() waits base*factor^n (capped at
    max_delay, ±jitter) and returns False once the overall deadline has
    passed, the attempt budget is spent, or `stop` is set — the
    caller's retry loop is `while bo.sleep(): ...`.

    Not thread-safe: one Backoff per retry loop (they are cheap)."""

    __slots__ = ("base", "factor", "max_delay", "jitter", "_deadline",
                 "_attempts_left", "_stop", "_rng", "_attempt")

    def __init__(self, base: float = DEFAULT_BASE,
                 factor: float = DEFAULT_FACTOR,
                 max_delay: float = DEFAULT_MAX_DELAY,
                 jitter: float = DEFAULT_JITTER,
                 deadline: Optional[float] = None,
                 attempts: Optional[int] = None,
                 stop: Optional[threading.Event] = None,
                 rng: Optional[random.Random] = None):
        self.base = base
        self.factor = factor
        self.max_delay = max_delay
        self.jitter = jitter
        self._deadline = (None if deadline is None
                          else time.monotonic() + deadline)
        self._attempts_left = attempts
        self._stop = stop
        self._rng = rng if rng is not None else random
        self._attempt = 0

    def reset(self) -> None:
        """Back to the base delay (a success in a long-lived loop)."""
        self._attempt = 0

    def expired(self) -> bool:
        if self._stop is not None and self._stop.is_set():
            return True
        if self._attempts_left is not None and self._attempts_left <= 0:
            return True
        return (self._deadline is not None
                and time.monotonic() >= self._deadline)

    def next_delay(self) -> float:
        """The next attempt's delay (advances the attempt counter)."""
        delay = min(self.base * (self.factor ** self._attempt),
                    self.max_delay)
        self._attempt += 1
        if self.jitter:
            delay *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        if self._deadline is not None:
            # Never sleep past the overall deadline; the final slice
            # still runs so the caller gets one last check at expiry.
            delay = min(delay, max(0.0, self._deadline - time.monotonic()))
        return max(delay, 0.0)

    def sleep(self) -> bool:
        """Sleep one backoff step. True = retry; False = give up
        (deadline hit, attempt budget spent, or stop set). Interrupted
        immediately when `stop` fires mid-sleep."""
        if self.expired():
            return False
        delay = self.next_delay()
        if self._attempts_left is not None:
            self._attempts_left -= 1
        if self._stop is not None:
            if self._stop.wait(delay):
                return False
        elif delay > 0:
            time.sleep(delay)
        # The deadline may have landed DURING the (deadline-clamped)
        # sleep: still grant the post-sleep retry — callers poll state
        # that can have become true while we slept, and the NEXT sleep()
        # reports expiry. Stop is the exception: shutdown wins now.
        return not (self._stop is not None and self._stop.is_set())


def sleep_jittered(delay: float, jitter: float = DEFAULT_JITTER,
                   rng: Optional[random.Random] = None) -> None:
    """One jittered sleep for fixed-interval retry loops that need no
    growth (a worker pacing its next dequeue attempt): ±jitter spreads
    a fleet's synchronized retries so a recovering leader is not hit by
    every follower on the same tick."""
    r = rng if rng is not None else random
    time.sleep(max(0.0, delay * (1.0 + jitter * (2.0 * r.random() - 1.0))))


def poll_until(predicate: Callable[[], bool], timeout: float,
               stop: Optional[threading.Event] = None,
               base: float = 0.001, factor: float = DEFAULT_FACTOR,
               max_delay: float = 0.1,
               jitter: float = DEFAULT_JITTER) -> bool:
    """Poll `predicate` under jittered backoff until it returns True or
    `timeout` elapses (or `stop` is set). Returns the final predicate
    verdict — including one last check at the deadline, so a condition
    that became true during the final sleep is not reported missed."""
    if predicate():
        return True
    bo = Backoff(base=base, factor=factor, max_delay=max_delay,
                 jitter=jitter, deadline=timeout, stop=stop)
    while bo.sleep():
        if predicate():
            return True
    if stop is not None and stop.is_set():
        return False
    return predicate()
