"""Generic dataclass <-> plain-dict codec for the wire protocol.

The reference uses msgpack with hand-registered Go structs
(reference nomad/structs/structs.go:63-77 Encode/Decode). Here every
struct is a Python dataclass and the codec is derived from type hints,
so the HTTP API, the replicated log, and client state persistence all
share one serialization path.
"""

from __future__ import annotations

import dataclasses
import json
import typing
from enum import Enum
from typing import Any, Optional, get_args, get_origin, get_type_hints

_HINTS_CACHE: dict[type, dict[str, Any]] = {}


def _hints(cls: type) -> dict[str, Any]:
    h = _HINTS_CACHE.get(cls)
    if h is None:
        h = get_type_hints(cls)
        _HINTS_CACHE[cls] = h
    return h


def to_dict(obj: Any) -> Any:
    """Recursively convert dataclasses/lists/dicts into JSON-able values."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, Enum):
        return obj.value
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            out[f.name] = to_dict(getattr(obj, f.name))
        return out
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [to_dict(v) for v in obj]
    if isinstance(obj, bytes):
        return obj.hex()
    raise TypeError(f"cannot encode {type(obj)!r}")


def from_dict(cls: Any, data: Any) -> Any:
    """Reconstruct a value of annotated type `cls` from plain data."""
    if data is None:
        return None
    origin = get_origin(cls)
    if origin is typing.Union:  # Optional[X] and unions
        args = [a for a in get_args(cls) if a is not type(None)]
        if len(args) == 1:
            return from_dict(args[0], data)
        return data  # ambiguous union: pass through
    if origin in (list, tuple, set):
        (item_t,) = get_args(cls) or (Any,)
        seq = [from_dict(item_t, v) for v in data]
        return origin(seq) if origin is not list else seq
    if origin is dict:
        args = get_args(cls)
        val_t = args[1] if len(args) == 2 else Any
        return {k: from_dict(val_t, v) for k, v in data.items()}
    if isinstance(cls, type) and issubclass(cls, Enum):
        return cls(data)
    if dataclasses.is_dataclass(cls):
        hints = _hints(cls)
        kwargs = {}
        names = {f.name for f in dataclasses.fields(cls)}
        for key, value in data.items():
            if key in names:
                kwargs[key] = from_dict(hints.get(key, Any), value)
        return cls(**kwargs)
    if cls in (Any, object) or cls is None:
        return data
    if isinstance(cls, type) and isinstance(data, cls):
        return data
    if cls is float and isinstance(data, int):
        return float(data)
    return data


def encode(obj: Any) -> bytes:
    return json.dumps(to_dict(obj), separators=(",", ":"), sort_keys=True).encode()


def decode(cls: Any, raw: bytes) -> Any:
    return from_dict(cls, json.loads(raw))
