"""Keep-alive HTTP connection pool.

Reference: nomad/pool.go:144 (ConnPool) — the reference keeps one
yamux-multiplexed TCP connection per server pair and every RPC
(including long-poll blocking queries) rides a stream on it, so a 10k
client cluster holds 10k sockets, not 10k reconnects per heartbeat
interval. HTTP/1.1 has no stream multiplexing, so the TPU-native
equivalent is a keep-alive pool: one socket per CONCURRENT request,
reused across sequential requests (a blocking-query wakeup loop runs
on a single socket forever). TLS (task: rpc.go:23-30 rpcTLS) slots in
via the `ssl_context` parameter.
"""

from __future__ import annotations

import http.client
import select
import socket
import ssl
import threading
import urllib.parse
from typing import Dict, List, Optional, Tuple


class PoolError(Exception):
    """Transport-level failure (unreachable, reset mid-request)."""


class HTTPPool:
    """Connection pool for one base address (scheme://host:port).

    request() checks a connection out of the idle list (or dials), runs
    one request/response cycle on it, and returns it if the response
    permits reuse. A request that fails on a POOLED connection is
    retried once on a fresh dial: the server may have closed the idle
    socket between our requests (keep-alive race) — indistinguishable
    from a dead server except by redialling.
    """

    def __init__(self, address: str, timeout: float = 305.0,
                 max_idle: int = 8,
                 ssl_context: Optional[ssl.SSLContext] = None):
        parsed = urllib.parse.urlsplit(address)
        self.scheme = parsed.scheme or "http"
        self.host = parsed.hostname or "127.0.0.1"
        self.port = parsed.port or (443 if self.scheme == "https" else 80)
        self.timeout = timeout
        self.max_idle = max_idle
        self.ssl_context = ssl_context
        self._idle: List[http.client.HTTPConnection] = []
        self._lock = threading.Lock()
        self._dials = 0  # sockets ever opened (observability/tests)
        self._closed = False

    # ------------------------------------------------------------ conns

    def _dial(self, timeout: float) -> http.client.HTTPConnection:
        with self._lock:
            self._dials += 1
        if self.scheme == "https":
            ctx = self.ssl_context
            if ctx is None:
                ctx = ssl.create_default_context()
            return http.client.HTTPSConnection(
                self.host, self.port, timeout=timeout, context=ctx)
        return http.client.HTTPConnection(
            self.host, self.port, timeout=timeout)

    def _checkout(self, timeout: float) -> Tuple[http.client.HTTPConnection, bool]:
        """Returns (conn, pooled): pooled connections get one retry."""
        with self._lock:
            while self._idle:
                conn = self._idle.pop()
                try:
                    # Timeouts are per-request (blocking queries pass
                    # their own); update the live socket too.
                    conn.timeout = timeout
                    if conn.sock is not None:
                        conn.sock.settimeout(timeout)
                        # A healthy idle HTTP socket has nothing to
                        # read; readable means the peer closed (EOF) or
                        # broke framing. Detecting it HERE matters for
                        # non-idempotent requests, which are never
                        # retried after their bytes go out. poll, not
                        # select: select() rejects fds >= FD_SETSIZE
                        # (1024), which a busy agent exceeds.
                        poller = select.poll()
                        poller.register(conn.sock, select.POLLIN)
                        if poller.poll(0):
                            conn.close()
                            continue
                except OSError:
                    conn.close()  # socket died while idle; skip it
                    continue
                return conn, True
        return self._dial(timeout), False

    def _checkin(self, conn: http.client.HTTPConnection) -> None:
        with self._lock:
            # A request in flight when close() ran must not park its
            # socket in a pool nobody will drain again (the SDK swaps
            # pools on address change mid-request).
            if not self._closed and len(self._idle) < self.max_idle:
                self._idle.append(conn)
                return
        conn.close()

    @property
    def dials(self) -> int:
        with self._lock:
            return self._dials

    def close(self) -> None:
        with self._lock:
            self._closed = True
            idle, self._idle = self._idle, []
        for conn in idle:
            conn.close()

    # --------------------------------------------------------- requests

    def request(
        self,
        method: str,
        path: str,
        body: Optional[bytes] = None,
        headers: Optional[Dict[str, str]] = None,
        timeout: Optional[float] = None,
    ) -> Tuple[int, Dict[str, str], bytes]:
        """One request/response cycle; returns (status, headers, body).

        The response body is always fully read (framing: the next
        request on this socket must start clean)."""
        t = self.timeout if timeout is None else timeout
        attempts = 0
        while True:
            conn, pooled = self._checkout(t)
            sent = False
            try:
                conn.request(method, path, body=body, headers=headers or {})
                sent = True
                resp = conn.getresponse()
                payload = resp.read()
            except (http.client.HTTPException, ConnectionError,
                    socket.timeout, TimeoutError, OSError) as e:
                conn.close()
                # A stale pooled socket fails instantly on first use —
                # retry once on a fresh dial. ONLY when that cannot
                # double-execute the request: either the request bytes
                # never went out (server can't have acted), or the
                # method is idempotent (GET). A PUT that failed after
                # send may have been applied (plan submit, job
                # register) — re-sending it would turn at-most-once
                # RPCs into at-least-once; let the caller decide.
                # Timeouts burned the caller's wait budget: never retry.
                is_timeout = isinstance(e, (socket.timeout, TimeoutError))
                retryable = (not sent) or method in ("GET", "HEAD")
                if pooled and attempts == 0 and retryable and not is_timeout:
                    attempts += 1
                    continue
                raise PoolError(
                    f"{method} {self.scheme}://{self.host}:{self.port}"
                    f"{path}: {e}") from e
            resp_headers = dict(resp.getheaders())
            if resp.will_close:
                conn.close()
            else:
                self._checkin(conn)
            return resp.status, resp_headers, payload


