"""Bounded daemon-thread work pool.

The reference's concurrency units are goroutines — cheap enough that a
timer callback, a drained eval, or a migration fetch each gets its own
(heartbeat.go:84 expiries, worker.go:101 eval loops). Python threads
are OS threads; spawning one per event makes storm behavior (10k node
TTLs expiring, 16-eval drain batches on every broker visit) an
allocation storm of its own and hides leaks. This pool gives a fixed
ceiling: up to `size` lazily-spawned daemon workers drain a shared
queue; submit() never blocks and returns a waitable future.

Unlike concurrent.futures.ThreadPoolExecutor, workers are daemon
threads and nothing registers atexit joins — a wedged callback can
never hang interpreter shutdown (the wheel and the schedulers submit
callbacks that may block on raft applies during leader loss).
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, List, Optional

logger = logging.getLogger("nomad_tpu.pool")


class PoolFuture:
    """Minimal waitable result: done event + value-or-exception."""

    __slots__ = ("_event", "_result", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("pool future not done")
        if self._error is not None:
            raise self._error
        return self._result


class WorkPool:
    """Fixed-ceiling daemon-thread pool. Threads spawn on demand up to
    `size` and then persist, blocking on the queue when idle."""

    def __init__(self, size: int, name: str = "workpool"):
        self.size = max(1, size)
        self.name = name
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self._idle = 0  # workers currently blocked on the queue

    def submit(self, fn: Callable, *args) -> PoolFuture:
        fut = PoolFuture()
        self._queue.put((fn, args, fut))
        with self._lock:
            # Spawn when queued work exceeds idle capacity (not just
            # idle==0: a worker between get() and its idle decrement
            # would otherwise suppress a needed spawn and strand this
            # item behind a long-blocking task). Erring toward spawning
            # is safe — the ceiling bounds it.
            self._threads = [t for t in self._threads if t.is_alive()]
            if self._queue.qsize() > self._idle and len(self._threads) < self.size:
                # Thread.start can fail under OS thread pressure —
                # AFTER the item was enqueued. Raising would hand
                # callers an item that is both "failed" and still due
                # to run (double accounting in callers' in-flight
                # tracking); running it inline would block submitters
                # that must never block (the dispatch pipeline hands
                # off EXACTLY to avoid that). So: retry once for
                # transient pressure, else leave the item queued —
                # qsize() reports it honestly, live workers drain it,
                # and EVERY future submit re-fires this spawn trigger.
                for attempt in (0, 1):
                    t = threading.Thread(
                        target=self._work,
                        name=f"{self.name}-{len(self._threads)}",
                        daemon=True)
                    try:
                        t.start()
                    except RuntimeError:
                        if attempt:
                            logger.warning(
                                "%s: worker spawn failed twice "
                                "(%d live, %d queued); queued work "
                                "waits for the next submit's retry",
                                self.name, len(self._threads),
                                self._queue.qsize(), exc_info=True)
                    else:
                        # Appended only on success: a never-started
                        # Thread would count toward the size ceiling
                        # until the next is_alive() prune.
                        self._threads.append(t)
                        break
        return fut

    def _work(self) -> None:
        while True:
            with self._lock:
                self._idle += 1
            try:
                item = self._queue.get()
            finally:
                with self._lock:
                    self._idle -= 1
            fn, args, fut = item
            try:
                fut._result = fn(*args)
            except BaseException as e:  # noqa: BLE001 - delivered via future
                fut._error = e
                logger.debug("pool task failed", exc_info=True)
            finally:
                fut._event.set()

    def queued(self) -> int:
        return self._queue.qsize()

    def worker_count(self) -> int:
        with self._lock:
            return sum(1 for t in self._threads if t.is_alive())
