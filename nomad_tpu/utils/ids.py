import os
import threading

# One getrandom() syscall buys 256 ids: the per-call syscall (which
# also releases the GIL, stalling the scheduler hot loop under
# contention) showed up as a top sample in control-plane profiles.
_BATCH_IDS = 256
_local = threading.local()


def _reset_after_fork() -> None:
    # A forked child inherits the surviving thread's hexbuf/pos and
    # would replay up to 255 of the parent's upcoming ids — colliding
    # eval/alloc ids across processes. Force a fresh urandom draw.
    _local.pos = 0


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reset_after_fork)


def generate_uuid() -> str:
    """Random identifier for jobs-internal objects (allocs, evals, nodes).

    Same shape as the reference's structs.GenerateUUID
    (reference nomad/structs/structs.go uses crypto/rand hex-8-4-4-4-12).
    Entropy is drawn in thread-local batches; each id is an independent
    16-byte slice, so ids stay crypto-random and collision-free across
    threads and processes.
    """
    pos = getattr(_local, "pos", 0)
    if pos == 0:
        _local.hexbuf = os.urandom(16 * _BATCH_IDS).hex()
    h = _local.hexbuf
    off = pos * 32
    _local.pos = (pos + 1) % _BATCH_IDS
    return (
        f"{h[off:off + 8]}-{h[off + 8:off + 12]}-{h[off + 12:off + 16]}"
        f"-{h[off + 16:off + 20]}-{h[off + 20:off + 32]}"
    )
