import uuid


def generate_uuid() -> str:
    """Random identifier for jobs-internal objects (allocs, evals, nodes).

    Same shape as the reference's structs.GenerateUUID
    (reference nomad/structs/structs.go uses crypto/rand hex-8-4-4-4-12).
    """
    return str(uuid.uuid4())
