"""mTLS context construction for the wire protocols.

Reference: helper/tlsutil/config.go — the reference builds one
tls.Config used by both the RPC listener and outgoing conns
(VerifyIncoming/VerifyOutgoing, CA + node cert/key). Here the same
triple (ca, cert, key) produces a pair of stdlib ssl contexts:

- server_context: terminates TLS and REQUIRES a client cert signed by
  the CA (mutual auth — a plaintext or unauthenticated peer fails the
  handshake, nomad/rpc.go:23-30's rpcTLS discipline);
- client_context: presents the node cert and verifies the server chain
  against the same CA. Hostname checking is off: cluster certs are
  issued per role, peers are addressed by ephemeral host:port
  (config.go VerifyServerHostname defaults false).
"""

from __future__ import annotations

import ssl
from typing import Optional


class TLSConfigError(Exception):
    pass


def _load(ctx: ssl.SSLContext, ca_file: str, cert_file: str,
          key_file: str) -> ssl.SSLContext:
    try:
        ctx.load_cert_chain(cert_file, key_file)
        ctx.load_verify_locations(ca_file)
    except (OSError, ssl.SSLError) as e:
        raise TLSConfigError(f"loading TLS material: {e}") from e
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    return ctx


def server_context(ca_file: str, cert_file: str, key_file: str,
                   verify_client: bool = True) -> ssl.SSLContext:
    """verify_client=True is the raft-transport discipline (mutual
    auth, rpc.go VerifyIncoming); the HTTP API defaults to server-only
    TLS like the reference (VerifyHTTPSClient false)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    if verify_client:
        ctx.verify_mode = ssl.CERT_REQUIRED
    return _load(ctx, ca_file, cert_file, key_file)


def client_context(ca_file: str, cert_file: str,
                   key_file: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_REQUIRED
    return _load(ctx, ca_file, cert_file, key_file)


def contexts_from_block(
    tls,
) -> "tuple[Optional[ssl.SSLContext], Optional[ssl.SSLContext], Optional[ssl.SSLContext]]":
    """(rpc_server_ctx, http_server_ctx, client_ctx) from an agent
    TLSBlock (cli/agent_config.py); all None when TLS is off. The raft
    channel is mutual, the HTTP channel server-only, and the client
    context serves both outgoing HTTP and outgoing raft."""
    if not getattr(tls, "enabled", False):
        return None, None, None
    ca, cert, key = tls.ca_file, tls.cert_file, tls.key_file
    if not (ca and cert and key):
        raise TLSConfigError(
            "tls.enabled requires ca_file, cert_file and key_file")
    rpc_ctx = (server_context(ca, cert, key, verify_client=True)
               if tls.rpc else None)
    http_ctx = (server_context(ca, cert, key, verify_client=False)
                if tls.http else None)
    return rpc_ctx, http_ctx, client_context(ca, cert, key)
