"""Shared timer wheel: many logical timers on one firing thread.

The reference leans on Go's runtime timers, which are cheap (a heap
inside the scheduler, no thread per timer) and fire each callback on
its own goroutine. Python's threading.Timer spawns a whole OS thread
per timer — at hundreds of eval dequeues per second (one nack timer
each, eval_broker.go:365) plus one heartbeat TTL timer per node
(heartbeat.go:14, 10k+ nodes), that's untenable. This wheel gives the
Go cost model: schedule/cancel are O(log n) heap ops on one shared
firing thread.

Callback execution is decoupled from firing: the firing thread only
pops due handles and hands them to a small bounded WorkPool, so one
slow callback (a heartbeat expiry doing a raft apply during leader
loss) cannot delay every other timer in the process — Go's
run-on-own-goroutine property, at bounded thread cost. Known-slow
callbacks should still offload their heavy part to their own pool
(server/heartbeat.py) so a storm of them cannot occupy all dispatch
workers and head-of-line-block fast timers like broker nacks.

Cancellation is a flag check at fire time; a cancelled handle's entry
just drains out of the heap. Callbacks run outside the wheel lock, so
they may freely take subsystem locks (broker, heartbeat) that
themselves call schedule()/cancel().
"""

from __future__ import annotations

import heapq
import itertools
import logging
import threading
import time
from typing import Callable, List, Optional, Tuple

from .pool import WorkPool

logger = logging.getLogger("nomad_tpu.timer")

DISPATCH_WORKERS = 4


class TimerHandle:
    """Cancelable scheduled callback (threading.Timer's cancel API)."""

    __slots__ = ("fn", "args", "cancelled")

    def __init__(self, fn: Callable, args: tuple):
        self.fn = fn
        self.args = args
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class TimerWheel:
    def __init__(self, name: str = "timer-wheel",
                 dispatch_workers: int = DISPATCH_WORKERS):
        self._name = name
        self._cond = threading.Condition()
        self._heap: List[Tuple[float, int, TimerHandle]] = []
        self._counter = itertools.count()
        self._thread: Optional[threading.Thread] = None
        self._pool = WorkPool(dispatch_workers, name=f"{name}-cb")

    def schedule(self, delay: float, fn: Callable, *args) -> TimerHandle:
        handle = TimerHandle(fn, args)
        deadline = time.monotonic() + max(delay, 0.0)
        with self._cond:
            heapq.heappush(self._heap, (deadline, next(self._counter), handle))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name=self._name, daemon=True
                )
                self._thread.start()
            # Wake the thread iff the new timer is now the earliest.
            if self._heap[0][2] is handle:
                self._cond.notify()
        return handle

    def _run(self) -> None:
        while True:
            with self._cond:
                while True:
                    now = time.monotonic()
                    # Drop cancelled entries at the front eagerly.
                    while self._heap and self._heap[0][2].cancelled:
                        heapq.heappop(self._heap)
                    if self._heap and self._heap[0][0] <= now:
                        _, _, handle = heapq.heappop(self._heap)
                        break
                    timeout = (
                        self._heap[0][0] - now if self._heap else 3600.0
                    )
                    self._cond.wait(timeout)
            if handle.cancelled:
                continue
            # Hand off: the firing thread never runs user code, so a
            # blocked callback cannot make other timers fire late.
            self._pool.submit(self._fire, handle)

    @staticmethod
    def _fire(handle: TimerHandle) -> None:
        if handle.cancelled:
            return
        try:
            handle.fn(*handle.args)
        except Exception:  # noqa: BLE001 - one bad timer can't kill the wheel
            logger.exception("timer callback failed")

    def pending(self) -> int:
        with self._cond:
            return sum(1 for _, _, h in self._heap if not h.cancelled)


_default: Optional[TimerWheel] = None
_default_lock = threading.Lock()


def default_wheel() -> TimerWheel:
    """Process-wide shared wheel (multiple in-process servers in tests
    share it; handles are independent)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = TimerWheel()
        return _default
