"""Telemetry: counters, gauges, and timing samples with pluggable sinks.

Reference: the go-metrics fanout wired in command/agent/command.go:570
(setupTelemetry) — an in-memory interval sink (signal-dumpable) plus
optional statsd/statsite UDP sinks — and the `MeasureSince` calls
sprinkled through worker.go:152,248,290, plan_apply.go:168,195, fsm.go
per-handler, and rpc.go:168-172.
"""

from __future__ import annotations

import math
import re
import socket
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

# ------------------------------------------------------ histogram math
#
# Log-bucketed latency histograms (milliseconds). Shared by the inmem
# sink's samples and the trace flight recorder (nomad_tpu/trace) so
# both report percentiles off the same bucket ladder. Layout:
#
#   bucket 0              v <= 0            (upper bound 0)
#   bucket 1              0 < v <= 1e-3 ms  (sub-microsecond floor)
#   bucket i >= 2         upper = 1e-3 * RATIO^(i-1)
#
# RATIO = 2^(1/4) (~19% bucket width): a percentile read off a bucket's
# upper bound overstates the true value by at most one ratio step. 200
# buckets span 1e-3 ms .. ~8e8 ms (~9 days) — everything past the top
# clamps into the last bucket.

HIST_MIN_MS = 1e-3
HIST_RATIO = 2.0 ** 0.25
_HIST_LOG_RATIO = math.log(HIST_RATIO)
HIST_BUCKETS = 200


def hist_bucket(v: float) -> int:
    """Bucket index for a millisecond value (extremes well-defined:
    zero/negative -> 0, sub-floor -> 1, huge -> last bucket)."""
    if v <= 0.0:
        return 0
    if v <= HIST_MIN_MS:
        return 1
    b = 2 + int(math.log(v / HIST_MIN_MS) / _HIST_LOG_RATIO)
    return b if b < HIST_BUCKETS else HIST_BUCKETS - 1


def hist_bucket_upper(i: int) -> float:
    """Inclusive upper bound (ms) of bucket `i`."""
    if i <= 0:
        return 0.0
    if i == 1:
        return HIST_MIN_MS
    return HIST_MIN_MS * HIST_RATIO ** (i - 1)


def hist_percentile(buckets, count: int, q: float) -> float:
    """The q-quantile read off bucket counts: the upper bound of the
    bucket where the cumulative count crosses rank ceil(q * count).
    `buckets` is either a dense count list (flight recorder) or a
    sparse {bucket_index: count} dict (inmem samples) — one rank-walk
    serves both so the two surfaces cannot drift. Returns 0.0 on an
    empty histogram."""
    if count <= 0:
        return 0.0
    rank = max(1, math.ceil(q * count))
    items = (sorted(buckets.items()) if isinstance(buckets, dict)
             else enumerate(buckets))
    cum = 0
    last = 0
    for i, c in items:
        cum += c
        last = i
        if cum >= rank:
            return hist_bucket_upper(i)
    return hist_bucket_upper(last)


# ntalint record-path manifest (analysis/robustness.py): observe is
# the leaf every flight-recorder span and every profiler lock/runq/GIL
# record lands in — arithmetic + preallocated-subscript writes only.
NTA_RECORD_PATH = ("LatencyHist.observe",)


class LatencyHist:
    """Fixed-size log-bucketed latency histogram (milliseconds) over
    the shared ladder above. The ONE histogram implementation the
    flight recorder (trace/recorder.py) and the contention observatory
    (nomad_tpu/profile) both store into, so their percentiles can
    never diverge from the ladder or from each other."""

    __slots__ = ("count", "total", "max", "buckets")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self.buckets = [0] * HIST_BUCKETS

    def observe(self, ms: float) -> None:
        self.count += 1
        self.total += ms
        if ms > self.max:
            self.max = ms
        self.buckets[hist_bucket(ms)] += 1


class _Interval:
    __slots__ = ("start", "counters", "gauges", "samples")

    def __init__(self, start: float):
        self.start = start
        self.counters: Dict[str, List[float]] = defaultdict(lambda: [0, 0.0])  # count, sum
        self.gauges: Dict[str, float] = {}
        # count, sum, min, max, {bucket: count} (log-bucketed, so
        # p50/p95/p99 are recoverable from any interval snapshot —
        # count/sum/min/max alone cannot reconstruct a percentile).
        self.samples: Dict[str, list] = defaultdict(
            lambda: [0, 0.0, float("inf"), float("-inf"), {}]
        )


class InmemSink:
    """Ring of aggregation intervals (go-metrics inmem.go analog)."""

    def __init__(self, interval: float = 10.0, retain: int = 60):
        self.interval = interval
        self.retain = retain
        self._lock = threading.Lock()
        self._intervals: List[_Interval] = [_Interval(time.time())]
        # Lifetime aggregates, never rotated: the Prometheus surface
        # reads THESE — counters and histogram buckets exposed from the
        # rolling intervals would DECREASE as old intervals rotate out,
        # and every decrease reads as a counter reset to rate().
        self._life = _Interval(time.time())

    def _current(self) -> _Interval:
        now = time.time()
        cur = self._intervals[-1]
        if now - cur.start >= self.interval:
            cur = _Interval(now)
            self._intervals.append(cur)
            if len(self._intervals) > self.retain:
                del self._intervals[: len(self._intervals) - self.retain]
        return cur

    def incr_counter(self, name: str, n: float) -> None:
        with self._lock:
            for c in (self._current().counters[name],
                      self._life.counters[name]):
                c[0] += 1
                c[1] += n

    def set_gauge(self, name: str, v: float) -> None:
        with self._lock:
            self._current().gauges[name] = v
            self._life.gauges[name] = v

    def add_sample(self, name: str, v: float) -> None:
        b = hist_bucket(v)
        with self._lock:
            for s in (self._current().samples[name],
                      self._life.samples[name]):
                s[0] += 1
                s[1] += v
                s[2] = min(s[2], v)
                s[3] = max(s[3], v)
                s[4][b] = s[4].get(b, 0) + 1

    @staticmethod
    def _sample_dict(v: list) -> dict:
        count = v[0]
        return {
            "count": count,
            "sum": v[1],
            "min": v[2] if count else 0.0,
            "max": v[3] if count else 0.0,
            "mean": (v[1] / count) if count else 0.0,
            "p50": hist_percentile(v[4], count, 0.50),
            "p95": hist_percentile(v[4], count, 0.95),
            "p99": hist_percentile(v[4], count, 0.99),
        }

    def snapshot(self, intervals: int = 2) -> List[dict]:
        """The most recent aggregation intervals, newest last."""
        with self._lock:
            out = []
            for iv in self._intervals[-intervals:]:
                out.append({
                    "start": iv.start,
                    "counters": {
                        k: {"count": v[0], "sum": v[1]} for k, v in iv.counters.items()
                    },
                    "gauges": dict(iv.gauges),
                    "samples": {
                        k: self._sample_dict(v)
                        for k, v in iv.samples.items()
                    },
                })
            return out

    def merged(self) -> dict:
        """The LIFETIME aggregates (never rotated) — the Prometheus
        exposition source. Exposing the rolling intervals instead would
        make _total/_count/_bucket values decrease as intervals rotate
        out, which rate()/increase() read as counter resets."""
        with self._lock:
            return {
                "counters": {k: list(v)
                             for k, v in self._life.counters.items()},
                "gauges": dict(self._life.gauges),
                "samples": {
                    k: [v[0], v[1], v[2], v[3], dict(v[4])]
                    for k, v in self._life.samples.items()
                },
            }


class StatsdSink:
    """Plain UDP statsd datagrams (`name:value|type`)."""

    def __init__(self, addr: str):
        host, _, port = addr.partition(":")
        self._addr = (host or "127.0.0.1", int(port or 8125))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def _send(self, payload: str) -> None:
        try:
            self._sock.sendto(payload.encode(), self._addr)
        except OSError:
            pass  # telemetry must never take the agent down

    def incr_counter(self, name: str, n: float) -> None:
        self._send(f"{name}:{n}|c")

    def set_gauge(self, name: str, v: float) -> None:
        self._send(f"{name}:{v}|g")

    def add_sample(self, name: str, v: float) -> None:
        self._send(f"{name}:{v}|ms")


class StatsiteSink:
    """Statsite speaks the statsd line protocol over a persistent TCP
    stream (go-metrics statsite.go). Reconnects lazily; telemetry
    errors never propagate."""

    def __init__(self, addr: str, timeout: float = 3.0):
        host, _, port = addr.partition(":")
        self._addr = (host or "127.0.0.1", int(port or 8125))
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _send(self, payload: str) -> None:
        with self._lock:
            try:
                if self._sock is None:
                    self._sock = socket.create_connection(
                        self._addr, timeout=self._timeout)
                self._sock.sendall((payload + "\n").encode())
            except OSError:
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None

    def incr_counter(self, name: str, n: float) -> None:
        self._send(f"{name}:{n}|c")

    def set_gauge(self, name: str, v: float) -> None:
        self._send(f"{name}:{v}|g")

    def add_sample(self, name: str, v: float) -> None:
        self._send(f"{name}:{v}|ms")

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


class CirconusSink:
    """Circonus httptrap submission (command.go:628-651 wires
    circonus-gometrics): metrics buffer locally and flush as one JSON
    document to the check's submission URL on an interval. Numeric
    gauges/counters/samples submit as numeric values;
    a failed flush drops the batch (telemetry must never block)."""

    def __init__(self, submission_url: str, flush_interval: float = 10.0):
        self.url = submission_url
        self.flush_interval = flush_interval
        self._lock = threading.Lock()
        self._pending: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="circonus-flush")
        self._thread.start()

    def _record(self, name: str, value: float) -> None:
        with self._lock:
            self._pending[name] = value

    def incr_counter(self, name: str, value: float) -> None:
        # Counters ACCUMULATE within a flush window (circonus-gometrics
        # does the same); only gauges/samples are last-write-wins.
        with self._lock:
            self._pending[name] = self._pending.get(name, 0.0) + value

    set_gauge = _record
    add_sample = _record

    def _run(self) -> None:
        while not self._stop.wait(self.flush_interval):
            self.flush()

    def flush(self) -> None:
        import json as _json
        import urllib.request

        with self._lock:
            if not self._pending:
                return
            batch, self._pending = self._pending, {}
        body = _json.dumps({k: {"_type": "n", "_value": v}
                            for k, v in batch.items()}).encode()
        req = urllib.request.Request(
            self.url, data=body, method="PUT",
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=5.0).read()
        except Exception:  # noqa: BLE001 - telemetry drops, never blocks
            pass

    def close(self) -> None:
        self._stop.set()
        self.flush()  # don't drop the final interval's metrics


class Metrics:
    """Fanout front-end; the module-global instance is what call sites
    use (go-metrics global metrics object)."""

    def __init__(self, prefix: str = "nomad_tpu", hostname: str = ""):
        self.prefix = prefix
        # go-metrics tags gauges with the hostname unless
        # disable_hostname is set (command.go:582-585).
        self.hostname = hostname
        self.inmem = InmemSink()
        self._sinks: List[object] = [self.inmem]
        self._statsd_addrs: set = set()

    def add_sink(self, sink) -> None:
        self._sinks.append(sink)

    def add_statsd(self, addr: str) -> None:
        """Attach a statsd sink once per address (servers and the CLI
        may both request the same target)."""
        if addr in self._statsd_addrs:
            return
        self._statsd_addrs.add(addr)
        self.add_sink(StatsdSink(addr))

    def _name(self, parts, tag_host: bool = False) -> str:
        head = (f"{self.prefix}.{self.hostname}"
                if tag_host and self.hostname else self.prefix)
        if isinstance(parts, str):
            return f"{head}.{parts}"
        return ".".join([head, *parts])

    def incr_counter(self, parts, n: float = 1) -> None:
        name = self._name(parts)
        for s in self._sinks:
            s.incr_counter(name, n)

    def set_gauge(self, parts, v: float) -> None:
        # Only gauges carry the hostname (go-metrics SetGauge applies
        # HostName; counters/samples stay cluster-aggregatable).
        name = self._name(parts, tag_host=True)
        for s in self._sinks:
            s.set_gauge(name, v)

    def add_sample(self, parts, v: float) -> None:
        name = self._name(parts)
        for s in self._sinks:
            s.add_sample(name, v)

    def measure_since(self, parts, start: float) -> None:
        """Record elapsed milliseconds since `start` (time.monotonic)."""
        self.add_sample(parts, (time.monotonic() - start) * 1000.0)

    def snapshot(self) -> List[dict]:
        return self.inmem.snapshot()


_global = Metrics()


def get_metrics() -> Metrics:
    return _global


def configure(prefix: Optional[str] = None, statsd_addr: Optional[str] = None,
              statsite_addr: Optional[str] = None,
              disable_hostname: bool = True,
              interval: Optional[float] = None,
              circonus_url: Optional[str] = None) -> Metrics:
    """Re-init the global registry from agent telemetry config
    (command.go:570 setupTelemetry): inmem sink always; statsd (UDP),
    statsite (TCP), and circonus (httptrap) fanout when configured;
    hostname tagging unless disabled."""
    import socket as _socket

    global _global
    hostname = "" if disable_hostname else _socket.gethostname()
    m = Metrics(prefix or "nomad_tpu", hostname=hostname)
    if interval:
        m.inmem.interval = interval
    if statsd_addr:
        m.add_statsd(statsd_addr)
    if statsite_addr:
        m.add_sink(StatsiteSink(statsite_addr))
    if circonus_url:
        m.add_sink(CirconusSink(circonus_url))
    # Swap FIRST, then release the old sinks off-thread: emitters racing
    # the swap can't resurrect a closed statsite socket, and a final
    # circonus flush to a blackholed URL (5s timeout) can't stall the
    # reconfigure caller.
    old = _global
    _global = m
    old_sinks = getattr(old, "_sinks", [])
    if any(getattr(s, "close", None) for s in old_sinks):
        def _release(sinks=old_sinks):
            for sink in sinks:
                closer = getattr(sink, "close", None)
                if closer is not None:
                    try:
                        closer()
                    except Exception:  # noqa: BLE001
                        pass

        threading.Thread(target=_release, daemon=True,
                         name="metrics-release").start()
    return m


def format_snapshot(snapshot: List[dict]) -> str:
    """Human-readable dump of inmem intervals (go-metrics InmemSignal
    output shape)."""
    lines = []
    for iv in snapshot:
        stamp = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(iv["start"]))
        lines.append(f"[{stamp}]")
        for name, c in sorted(iv["counters"].items()):
            lines.append(f"  counter {name}: count={c['count']} sum={c['sum']:g}")
        for name, v in sorted(iv["gauges"].items()):
            lines.append(f"  gauge {name}: {v:g}")
        for name, s in sorted(iv["samples"].items()):
            lines.append(
                f"  sample {name}: count={s['count']} mean={s['mean']:.3f} "
                f"min={s['min']:.3f} max={s['max']:.3f}")
    return "\n".join(lines)


def install_signal_dump(signum: Optional[int] = None) -> None:
    """SIGUSR1 dumps the recent telemetry intervals to stderr
    (command.go in-memory sink + InmemSignal). Main thread only."""
    import signal
    import sys

    signum = signum or signal.SIGUSR1

    def dump(_sig, _frame):
        print(format_snapshot(_global.snapshot()), file=sys.stderr)

    signal.signal(signum, dump)


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    out = _PROM_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_num(v: float) -> str:
    # Integral values print as integers (the common case for counts);
    # everything else as repr floats — both are valid exposition.
    # Non-finite values must spell the exposition tokens exactly
    # (Go's ParseFloat accepts "+Inf"/"-Inf"/"NaN", not Python's
    # repr "inf"/"nan" — and int(nan) raises outright).
    f = float(v)
    if f != f:
        return "NaN"
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    if f == int(f):
        return str(int(f))
    return repr(f)


def _prom_escape(v: str) -> str:
    """Label-value escaping per the exposition format."""
    return (v.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def emit_histogram_family(lines: List[str], name: str, help_text: str,
                          series: dict, label: str = "site") -> None:
    """Append ONE 0.0.4 histogram family to `lines`: HELP/TYPE, then
    per series cumulative le-ordered buckets ending in +Inf, _sum and
    _count. `series` maps a label value ("" = unlabelled) to
    ``(count, total, buckets)`` where buckets is a dense count list or
    a sparse {bucket_index: count} dict over the shared ladder. The
    single histogram emitter for the registry AND the contention
    observatory (nomad_tpu/profile), so a conformance fix can never
    apply to one half of /v1/metrics only."""
    if not series:
        return
    lines.append(f"# HELP {name} {help_text}")
    lines.append(f"# TYPE {name} histogram")
    for key in sorted(series):
        count, total, buckets = series[key]
        lbl = f'{label}="{_prom_escape(key)}",' if key else ""
        items = (sorted(buckets.items()) if isinstance(buckets, dict)
                 else enumerate(buckets))
        cum = 0
        for b, c in items:
            if not c:
                continue
            cum += c
            lines.append(
                f'{name}_bucket{{{lbl}le="{hist_bucket_upper(b):g}"}} '
                f"{cum}")
        lines.append(f'{name}_bucket{{{lbl}le="+Inf"}} {count}')
        tail = f"{{{lbl[:-1]}}}" if lbl else ""
        lines.append(f"{name}_sum{tail} {_prom_num(total)}")
        lines.append(f"{name}_count{tail} {count}")


def format_prometheus(metrics: Optional[Metrics] = None) -> str:
    """Prometheus text exposition (format 0.0.4) of the inmem sink,
    aggregated across every retained interval: counters as counters,
    gauges as gauges, timing samples as histograms over the shared
    log-bucket ladder (values are MILLISECONDS — measure_since's unit —
    stated in each HELP line). Served at /v1/agent's sibling route
    /v1/metrics (api/http.py)."""
    m = metrics or _global
    merged = m.inmem.merged()
    lines: List[str] = []
    # Family names must be unique across the whole exposition: two raw
    # names can sanitize to one prom name ("a.b" and "a_b"), and a
    # duplicate TYPE block is a parse error for every scraper. First
    # (sorted) name wins; later collisions are skipped, not emitted
    # twice.
    seen: set = set()

    def _family(p: str) -> bool:
        if p in seen:
            return False
        seen.add(p)
        return True

    for name in sorted(merged["counters"]):
        v = merged["counters"][name]
        p = _prom_name(name)
        if not _family(f"{p}_total"):
            continue
        lines.append(f"# HELP {p}_total aggregated counter {name}")
        lines.append(f"# TYPE {p}_total counter")
        lines.append(f"{p}_total {_prom_num(v[1])}")
    for name in sorted(merged["gauges"]):
        p = _prom_name(name)
        if not _family(p):
            continue
        lines.append(f"# HELP {p} gauge {name}")
        lines.append(f"# TYPE {p} gauge")
        lines.append(f"{p} {_prom_num(merged['gauges'][name])}")
    for name in sorted(merged["samples"]):
        v = merged["samples"][name]
        p = _prom_name(name)
        if not _family(p):
            continue
        emit_histogram_family(
            lines, p, f"timing sample {name} (milliseconds)",
            {"": (v[0], v[1], v[4])})
    return "\n".join(lines) + "\n"


def incr_counter(parts, n: float = 1) -> None:
    _global.incr_counter(parts, n)


def set_gauge(parts, v: float) -> None:
    _global.set_gauge(parts, v)


def add_sample(parts, v: float) -> None:
    _global.add_sample(parts, v)


def measure_since(parts, start: float) -> None:
    _global.measure_since(parts, start)
