from .codec import to_dict, from_dict, encode, decode
from .ids import generate_uuid

__all__ = ["to_dict", "from_dict", "encode", "decode", "generate_uuid"]
