"""Persistent XLA compilation cache.

Placement programs are compiled once per (node bucket, ask bucket,
batch bucket) shape; over a remote-device tunnel a single compile can
cost tens of seconds. The persistent cache makes that a one-time cost
per machine instead of per process (measured: 63s first compile,
0.4s from cache in a fresh process).

The reference has no analog — Go compiles ahead of time; this is the
TPU-runtime counterpart of shipping a compiled binary.
"""

from __future__ import annotations

import os

_enabled = False


def enable_compilation_cache() -> None:
    """Idempotent; call before the first jit dispatch. Cache lives in
    the repo (NOMAD_TPU_JAX_CACHE overrides) so nothing outside the
    tree is written."""
    global _enabled
    if _enabled:
        return
    _enabled = True
    try:
        import jax

        path = os.environ.get("NOMAD_TPU_JAX_CACHE")
        if not path:
            repo = os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            )
            path = os.path.join(repo, ".jax_cache")
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 - cache is an optimization only
        pass
