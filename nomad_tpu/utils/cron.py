"""Minimal 5-field cron schedule used by periodic jobs.

The reference relies on gorhill/cronexpr (nomad/periodic.go via
structs.PeriodicConfig.Next). Supported syntax here: "*", "*/n", lists
"a,b,c", ranges "a-b", and combinations, over minute hour day-of-month
month day-of-week.
"""

from __future__ import annotations

import calendar
import time
from typing import List, Set

_FIELD_RANGES = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 6)]


def _parse_field(expr: str, lo: int, hi: int) -> Set[int]:
    out: Set[int] = set()
    for part in expr.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
            if step < 1:
                raise ValueError(f"cron step must be >= 1, got {step}")
        if part == "*" or part == "":
            rng = range(lo, hi + 1)
        elif "-" in part:
            a, b = part.split("-", 1)
            rng = range(int(a), int(b) + 1)
        else:
            rng = range(int(part), int(part) + 1)
        start = rng.start  # steps anchor to the range start, per standard cron
        for v in rng:
            if not (lo <= v <= hi):
                raise ValueError(f"cron field value {v} out of range [{lo},{hi}]")
            if (v - start) % step == 0:
                out.add(v)
    if not out:
        raise ValueError(f"empty cron field {expr!r}")
    return out


class CronSchedule:
    def __init__(self, spec: str):
        fields = spec.split()
        if len(fields) != 5:
            raise ValueError(f"expected 5 cron fields, got {len(fields)}")
        self.minutes, self.hours, self.days, self.months, self.weekdays = (
            _parse_field(f, lo, hi) for f, (lo, hi) in zip(fields, _FIELD_RANGES)
        )
        self.any_day = fields[2] == "*"
        self.any_weekday = fields[4] == "*"

    def _day_match(self, year: int, month: int, day: int) -> bool:
        # cron semantics: if both dom and dow are restricted, either may match
        dom_ok = day in self.days
        # Python: Monday=0..Sunday=6; cron: Sunday=0..Saturday=6
        wd = (calendar.weekday(year, month, day) + 1) % 7
        dow_ok = wd in self.weekdays
        if self.any_day and self.any_weekday:
            return True
        if self.any_day:
            return dow_ok
        if self.any_weekday:
            return dom_ok
        return dom_ok or dow_ok

    def next_after(self, after: float) -> float:
        """Next matching time strictly after `after` (unix seconds, local)."""
        t = time.localtime(after + 60 - (after % 60))
        year, month, day = t.tm_year, t.tm_mon, t.tm_mday
        hour, minute = t.tm_hour, t.tm_min
        for _ in range(366 * 5 * 24 * 60):  # bounded search
            if month not in self.months:
                month += 1
                if month > 12:
                    month, year = 1, year + 1
                day, hour, minute = 1, 0, 0
                continue
            if day > calendar.monthrange(year, month)[1] or not self._day_match(year, month, day):
                day += 1
                hour, minute = 0, 0
                if day > calendar.monthrange(year, month)[1]:
                    day, month = 1, month + 1
                    if month > 12:
                        month, year = 1, year + 1
                continue
            if hour not in self.hours:
                hour += 1
                minute = 0
                if hour > 23:
                    hour = 0
                    day += 1
                continue
            if minute not in self.minutes:
                minute += 1
                if minute > 59:
                    minute = 0
                    hour += 1
                continue
            return time.mktime((year, month, day, hour, minute, 0, 0, 0, -1))
        raise ValueError("no matching time found within 5 years")
