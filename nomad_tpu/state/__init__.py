from .store import PeriodicLaunch, StateSnapshot, StateStore
from . import watch

__all__ = ["PeriodicLaunch", "StateSnapshot", "StateStore", "watch"]
