"""MVCC in-memory state store.

Reference: nomad/state/state_store.go:35 (StateStore over go-memdb's
immutable radix trees) and nomad/state/schema.go:18-40 (tables: nodes,
jobs, job_summary, periodic_launch, evals, allocs, index).

Design: tables are plain dicts treated as immutable-after-snapshot.
`snapshot()` marks every table shared and returns views in O(1); the
next write to a shared table copies it first (copy-on-write at table
granularity). Records are never mutated in place once inserted — writers
insert fresh copies — so snapshots are stable without locking, which is
what lets N scheduling workers read while the FSM writes (the
reference's lock-free MVCC property, SURVEY.md section 2.3).
"""

from __future__ import annotations

import copy as _copy
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set

from ..structs import (
    Allocation,
    Evaluation,
    Job,
    JobSummary,
    Node,
    TaskGroupSummary,
    consts,
)
from . import watch


@dataclass
class PeriodicLaunch:
    id: str = ""
    launch: float = 0.0  # unix time of last launch
    create_index: int = 0
    modify_index: int = 0


class _Table:
    __slots__ = ("data", "shared")

    def __init__(self):
        self.data: Dict[str, object] = {}
        self.shared = False

    def for_write(self) -> Dict[str, object]:
        if self.shared:
            self.data = dict(self.data)
            self.shared = False
        return self.data

    def share(self) -> Dict[str, object]:
        self.shared = True
        return self.data


class _Index:
    """Secondary index: key -> frozenset-ish of ids, copy-on-write.

    COW granularity is per-SET, not per-dict: `_fresh` names the keys
    whose set was created or copied since the last `share()` — no
    snapshot can hold those, so they mutate in place. Without this,
    every `add` under one hot key (500k allocs of one job) copies the
    whole growing set and a bulk load goes quadratic.
    """

    __slots__ = ("data", "shared", "_fresh")

    def __init__(self):
        self.data: Dict[str, Set[str]] = {}
        self.shared = False
        self._fresh: Set[str] = set()

    def _for_write(self) -> Dict[str, Set[str]]:
        if self.shared:
            self.data = {k: v for k, v in self.data.items()}
            self.shared = False
        return self.data

    def add(self, key: str, id_: str) -> None:
        data = self._for_write()
        cur = data.get(key)
        if cur is None:
            data[key] = {id_}
            self._fresh.add(key)
        elif key in self._fresh:
            cur.add(id_)  # private since last share(): mutate in place
        else:
            data[key] = cur | {id_}  # copy: snapshots may hold cur
            self._fresh.add(key)

    def remove(self, key: str, id_: str) -> None:
        data = self._for_write()
        cur = data.get(key)
        if cur and id_ in cur:
            if key in self._fresh:
                cur.discard(id_)
                if not cur:
                    del data[key]
                    self._fresh.discard(key)
            else:
                nxt = cur - {id_}
                if nxt:
                    data[key] = nxt
                    self._fresh.add(key)
                else:
                    del data[key]

    def share(self) -> Dict[str, Set[str]]:
        self.shared = True
        self._fresh.clear()
        return self.data


TABLES = (
    "nodes",
    "jobs",
    "job_summary",
    "periodic_launch",
    "evals",
    "allocs",
    "vault_accessors",
)


class StateSnapshot:
    """Immutable point-in-time view with the scheduler's read interface
    (scheduler.State, reference scheduler/scheduler.go:55)."""

    def __init__(self, tables, indexes, table_indexes, latest,
                 store_id: str = ""):
        self._t = tables
        self._i = indexes
        self._table_indexes = table_indexes
        self._latest = latest
        # Identity of the owning store: table indexes alone are not
        # unique across stores in one process (tests, multi-server),
        # so caches keyed on indexes must include this.
        self.store_id = store_id

    # -- index queries --
    def latest_index(self) -> int:
        return self._latest

    def index(self, table: str) -> int:
        return self._table_indexes.get(table, 0)

    # -- nodes --
    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self._t["nodes"].get(node_id)

    def nodes(self) -> List[Node]:
        return list(self._t["nodes"].values())

    # -- jobs --
    def job_by_id(self, job_id: str) -> Optional[Job]:
        return self._t["jobs"].get(job_id)

    def jobs(self) -> List[Job]:
        return list(self._t["jobs"].values())

    def jobs_by_scheduler(self, scheduler_type: str) -> List[Job]:
        return [j for j in self._t["jobs"].values() if j.type == scheduler_type]

    def jobs_by_periodic(self, periodic: bool = True) -> List[Job]:
        return [j for j in self._t["jobs"].values() if j.is_periodic() == periodic]

    def job_summary_by_id(self, job_id: str) -> Optional[JobSummary]:
        return self._t["job_summary"].get(job_id)

    # -- periodic launches --
    def periodic_launch_by_id(self, job_id: str) -> Optional[PeriodicLaunch]:
        return self._t["periodic_launch"].get(job_id)

    def periodic_launches(self) -> List[PeriodicLaunch]:
        return list(self._t["periodic_launch"].values())

    # -- evals --
    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self._t["evals"].get(eval_id)

    def evals(self) -> List[Evaluation]:
        return list(self._t["evals"].values())

    def evals_by_job(self, job_id: str) -> List[Evaluation]:
        ids = self._i["evals_by_job"].get(job_id, ())
        return [self._t["evals"][i] for i in ids]

    # -- allocs --
    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        return self._t["allocs"].get(alloc_id)

    def allocs(self) -> List[Allocation]:
        return list(self._t["allocs"].values())

    def alloc_count(self) -> int:
        """O(1) allocs-table size (delta caches detect GC deletions by
        comparing it; listing 50k allocs to count them would defeat the
        point)."""
        return len(self._t["allocs"])

    def allocs_by_job(self, job_id: str) -> List[Allocation]:
        ids = self._i["allocs_by_job"].get(job_id, ())
        return [self._t["allocs"][i] for i in ids]

    def allocs_by_node(self, node_id: str) -> List[Allocation]:
        ids = self._i["allocs_by_node"].get(node_id, ())
        return [self._t["allocs"][i] for i in ids]

    def allocs_by_node_terminal(self, node_id: str, terminal: bool) -> List[Allocation]:
        return [
            a for a in self.allocs_by_node(node_id) if a.terminal_status() == terminal
        ]

    def vault_accessors(self) -> List[object]:
        return list(self._t["vault_accessors"].values())

    def vault_accessors_by_alloc(self, alloc_id: str) -> List[object]:
        return [
            a for a in self._t["vault_accessors"].values()
            if a.alloc_id == alloc_id
        ]

    def allocs_by_eval(self, eval_id: str) -> List[Allocation]:
        ids = self._i["allocs_by_eval"].get(eval_id, ())
        return [self._t["allocs"][i] for i in ids]


class StateStore:
    """The authoritative replicated state. All writes come from the FSM
    applying log entries; every write bumps the per-table and global
    index and fires scoped watches."""

    def __init__(self):
        self._lock = threading.RLock()
        self._tables: Dict[str, _Table] = {name: _Table() for name in TABLES}
        self._indexes = {
            "evals_by_job": _Index(),
            "allocs_by_job": _Index(),
            "allocs_by_node": _Index(),
            "allocs_by_eval": _Index(),
        }
        self._table_indexes: Dict[str, int] = {}
        self._latest_index = 0
        # Per-watch-scope modify indexes (the reference's state_store.go
        # index-table device, at watch.Item granularity): one entry per
        # (kind, key) actually touched by a commit. Blocking queries
        # wake — and stamp X-Nomad-Index — off THEIR scope's index, not
        # the global one, so a write to job A never re-runs a watcher
        # of job B. Bounded by _SCOPE_CAP: pruning raises _scope_floor
        # so evicted scopes degrade to conservative (global-ish) wakes
        # instead of missed ones.
        self._scope_indexes: Dict[watch.Item, int] = {}
        self._scope_floor = 0
        self.notify = watch.NotifyGroup()
        from ..utils.ids import generate_uuid

        self.store_id = generate_uuid()

    # ------------------------------------------------------------------
    # snapshots & watches
    # ------------------------------------------------------------------

    def snapshot(self) -> StateSnapshot:
        with self._lock:
            tables = {name: t.share() for name, t in self._tables.items()}
            indexes = {name: i.share() for name, i in self._indexes.items()}
            return StateSnapshot(
                tables, indexes, dict(self._table_indexes),
                self._latest_index, store_id=self.store_id,
            )

    def latest_index(self) -> int:
        with self._lock:
            return self._latest_index

    def index(self, table: str) -> int:
        with self._lock:
            return self._table_indexes.get(table, 0)

    def watch(self, items) -> "threading.Event":
        return self.notify.watch(items)

    def stop_watch(self, items, ev) -> None:
        self.notify.stop_watch(items, ev)

    def scope_index(self, items) -> int:
        """Max modify index across the given watch scopes — the index a
        blocking query on `items` should compare against ?index=N and
        report as X-Nomad-Index. Never-stamped scopes fall back to the
        scope floor (0 on a fresh store; the restored latest index when
        the snapshot predates scope persistence, so correctness degrades
        to the old conservative global behavior, never to missed
        wakes)."""
        with self._lock:
            best = 0
            for item in items:
                idx = self._scope_indexes.get(item)
                if idx is None:
                    kind, key = item
                    if kind == "table":
                        idx = self._table_indexes.get(key, 0)
                    else:
                        idx = self._scope_floor
                if idx > best:
                    best = idx
            return best

    # Read API mirrors the snapshot's (reads go through a fresh snapshot
    # so they are consistent).
    def __getattr__(self, name):
        snap_methods = (
            "node_by_id",
            "nodes",
            "job_by_id",
            "jobs",
            "jobs_by_scheduler",
            "jobs_by_periodic",
            "job_summary_by_id",
            "periodic_launch_by_id",
            "periodic_launches",
            "eval_by_id",
            "evals",
            "evals_by_job",
            "alloc_by_id",
            "allocs",
            "alloc_count",
            "allocs_by_job",
            "allocs_by_node",
            "allocs_by_node_terminal",
            "allocs_by_eval",
            "vault_accessors",
            "vault_accessors_by_alloc",
        )
        if name in snap_methods:
            return getattr(self.snapshot(), name)
        raise AttributeError(name)

    # ------------------------------------------------------------------
    # write transactions (FSM-only)
    # ------------------------------------------------------------------

    # Scope entries ever stamped before pruning engages; prune drops
    # the oldest half and raises the floor to the highest dropped
    # index (conservative, not lossy).
    _SCOPE_CAP = 262144

    def _bump(self, index: int, *tables: str) -> None:
        for t in tables:
            self._table_indexes[t] = index
        self._latest_index = max(self._latest_index, index)

    def _stamp(self, index: int, items) -> None:
        """Record `index` as the modify index of every touched scope.
        Runs under self._lock, after the txn's table writes, so a
        reader never sees new data with a pre-txn scope index."""
        scopes = self._scope_indexes
        for item in items:
            scopes[item] = index
        if len(scopes) > self._SCOPE_CAP:
            by_age = sorted(scopes.items(), key=lambda kv: kv[1])
            cut = len(by_age) // 2
            for item, idx in by_age[:cut]:
                del scopes[item]
            if cut:
                self._scope_floor = max(self._scope_floor,
                                        by_age[cut - 1][1])

    def upsert_node(self, index: int, node: Node) -> None:
        items = [watch.table("nodes"), watch.node(node.id)]
        with self._lock:
            table = self._tables["nodes"].for_write()
            existing = table.get(node.id)
            node = node.copy()
            if existing is not None:
                node.create_index = existing.create_index
            else:
                node.create_index = index
            node.modify_index = index
            # Always recompute: a re-registering node may carry a stale
            # class alongside changed attributes.
            node.compute_class()
            table[node.id] = node
            self._bump(index, "nodes")
            self._stamp(index, items)
        self.notify.notify(items)

    def delete_node(self, index: int, node_id: str) -> None:
        items = [watch.table("nodes"), watch.node(node_id)]
        with self._lock:
            table = self._tables["nodes"].for_write()
            if node_id not in table:
                return
            del table[node_id]
            self._bump(index, "nodes")
            self._stamp(index, items)
        self.notify.notify(items)

    def update_node_status(self, index: int, node_id: str, status: str) -> None:
        items = [watch.table("nodes"), watch.node(node_id)]
        with self._lock:
            table = self._tables["nodes"].for_write()
            existing = table.get(node_id)
            if existing is None:
                raise KeyError(f"node {node_id} not found")
            node = existing.copy()
            node.status = status
            node.modify_index = index
            import time as _time

            node.status_updated_at = _time.time()
            table[node_id] = node
            self._bump(index, "nodes")
            self._stamp(index, items)
        self.notify.notify(items)

    def update_node_drain(self, index: int, node_id: str, drain: bool) -> None:
        items = [watch.table("nodes"), watch.node(node_id)]
        with self._lock:
            table = self._tables["nodes"].for_write()
            existing = table.get(node_id)
            if existing is None:
                raise KeyError(f"node {node_id} not found")
            node = existing.copy()
            node.drain = drain
            node.modify_index = index
            table[node_id] = node
            self._bump(index, "nodes")
            self._stamp(index, items)
        self.notify.notify(items)

    def upsert_job(self, index: int, job: Job) -> None:
        items = [watch.table("jobs"), watch.job(job.id), watch.job_summary(job.id)]
        with self._lock:
            table = self._tables["jobs"].for_write()
            existing = table.get(job.id)
            job = job.copy()
            if existing is not None:
                job.create_index = existing.create_index
                job.job_modify_index = index
            else:
                job.create_index = index
                job.job_modify_index = index
            job.modify_index = index
            table[job.id] = job
            self._ensure_job_summary(index, job)
            items.extend(self._set_job_status(index, job))
            self._bump(index, "jobs", "job_summary")
            self._stamp(index, items)
        self.notify.notify(items)

    def delete_job(self, index: int, job_id: str) -> None:
        items = [watch.table("jobs"), watch.job(job_id), watch.job_summary(job_id)]
        with self._lock:
            table = self._tables["jobs"].for_write()
            if job_id not in table:
                return
            del table[job_id]
            summary = self._tables["job_summary"].for_write()
            summary.pop(job_id, None)
            launches = self._tables["periodic_launch"].for_write()
            launches.pop(job_id, None)
            self._bump(index, "jobs", "job_summary", "periodic_launch")
            self._stamp(index, items)
        self.notify.notify(items)

    def upsert_periodic_launch(self, index: int, launch: PeriodicLaunch) -> None:
        items = [watch.table("periodic_launch")]
        with self._lock:
            table = self._tables["periodic_launch"].for_write()
            existing = table.get(launch.id)
            rec = PeriodicLaunch(
                id=launch.id,
                launch=launch.launch,
                create_index=existing.create_index if existing else index,
                modify_index=index,
            )
            table[launch.id] = rec
            self._bump(index, "periodic_launch")
            self._stamp(index, items)
        self.notify.notify(items)

    def delete_periodic_launch(self, index: int, job_id: str) -> None:
        items = [watch.table("periodic_launch")]
        with self._lock:
            table = self._tables["periodic_launch"].for_write()
            table.pop(job_id, None)
            self._bump(index, "periodic_launch")
            self._stamp(index, items)
        self.notify.notify(items)

    def upsert_vault_accessors(self, index: int, accessors) -> None:
        """Track derived vault tokens (state_store.go vault_accessors
        table; schema.go:18-40)."""
        items = [watch.table("vault_accessors")]
        with self._lock:
            table = self._tables["vault_accessors"].for_write()
            for acc in accessors:
                acc.create_index = index
                table[acc.accessor] = acc
            self._bump(index, "vault_accessors")
            self._stamp(index, items)
        self.notify.notify(items)

    def delete_vault_accessors(self, index: int, accessors: List[str]) -> None:
        items = [watch.table("vault_accessors")]
        with self._lock:
            table = self._tables["vault_accessors"].for_write()
            for acc in accessors:
                table.pop(acc, None)
            self._bump(index, "vault_accessors")
            self._stamp(index, items)
        self.notify.notify(items)

    def upsert_evals(self, index: int, evals: List[Evaluation]) -> None:
        items = [watch.table("evals")]
        with self._lock:
            table = self._tables["evals"].for_write()
            for ev in evals:
                items.append(watch.eval_item(ev.id))
                existing = table.get(ev.id)
                ev = ev.copy()
                if existing is not None:
                    ev.create_index = existing.create_index
                else:
                    ev.create_index = index
                    self._indexes["evals_by_job"].add(ev.job_id, ev.id)
                ev.modify_index = index
                table[ev.id] = ev
                # Propagate queued-alloc counts into the job summary
                # (state_store.go UpsertEvals -> updateSummaryWithEval).
                if ev.queued_allocations:
                    self._update_summary_queued(index, ev)
                job = self._tables["jobs"].data.get(ev.job_id)
                if job is not None:
                    items.extend(self._set_job_status(index, job))
                    items.append(watch.job_summary(ev.job_id))
            self._bump(index, "evals", "job_summary")
            self._stamp(index, items)
        self.notify.notify(items)

    def delete_evals(self, index: int, eval_ids: List[str], alloc_ids: List[str]) -> None:
        items = [watch.table("evals"), watch.table("allocs")]
        touched_jobs: Set[str] = set()
        with self._lock:
            evals = self._tables["evals"].for_write()
            for eid in eval_ids:
                ev = evals.pop(eid, None)
                if ev is not None:
                    self._indexes["evals_by_job"].remove(ev.job_id, eid)
                    items.append(watch.eval_item(eid))
                    touched_jobs.add(ev.job_id)
            allocs = self._tables["allocs"].for_write()
            for aid in alloc_ids:
                alloc = allocs.pop(aid, None)
                if alloc is not None:
                    self._indexes["allocs_by_job"].remove(alloc.job_id, aid)
                    self._indexes["allocs_by_node"].remove(alloc.node_id, aid)
                    self._indexes["allocs_by_eval"].remove(alloc.eval_id, aid)
                    touched_jobs.add(alloc.job_id)
                    items.extend(
                        [
                            watch.alloc(aid),
                            watch.alloc_job(alloc.job_id),
                            watch.alloc_node(alloc.node_id),
                            watch.alloc_eval(alloc.eval_id),
                        ]
                    )
            for job_id in touched_jobs:
                job = self._tables["jobs"].data.get(job_id)
                if job is not None:
                    items.extend(self._set_job_status(index, job, eval_delete=True))
            self._bump(index, "evals", "allocs")
            self._stamp(index, items)
        self.notify.notify(items)

    def upsert_allocs(self, index: int, allocs: List[Allocation]) -> None:
        """Scheduler/plan-apply driven alloc writes (state_store.go:922).
        Client-reported status on existing allocs is preserved."""
        items = [watch.table("allocs")]
        with self._lock:
            table = self._tables["allocs"].for_write()
            for alloc in allocs:
                existing = table.get(alloc.id)
                alloc = alloc.copy()
                if existing is not None:
                    alloc.create_index = existing.create_index
                    alloc.task_states = existing.task_states
                    # The client owns client_status — EXCEPT lost: the
                    # scheduler marks an alloc lost exactly because its
                    # node went down and the client can never report
                    # again (state_store.go:922 carves out the same
                    # case). Without this the node-down -> alloc-lost
                    # chain silently reverted to the stale 'running'.
                    if alloc.client_status != consts.ALLOC_CLIENT_LOST:
                        alloc.client_status = existing.client_status
                        alloc.client_description = existing.client_description
                else:
                    alloc.create_index = index
                    if not alloc.client_status:
                        alloc.client_status = consts.ALLOC_CLIENT_PENDING
                    self._indexes["allocs_by_job"].add(alloc.job_id, alloc.id)
                    self._indexes["allocs_by_node"].add(alloc.node_id, alloc.id)
                    self._indexes["allocs_by_eval"].add(alloc.eval_id, alloc.id)
                alloc.modify_index = index
                alloc.alloc_modify_index = index
                table[alloc.id] = alloc
                self._update_summary_with_alloc(index, alloc, existing)
                items.extend(
                    [
                        watch.alloc(alloc.id),
                        watch.alloc_job(alloc.job_id),
                        watch.alloc_node(alloc.node_id),
                        watch.alloc_eval(alloc.eval_id),
                        watch.job_summary(alloc.job_id),
                    ]
                )
            # Derived job status recomputes once per touched job, not
            # once per alloc (a system job upserts one alloc per node).
            for job_id in {a.job_id for a in allocs}:
                job = self._tables["jobs"].data.get(job_id)
                if job is not None:
                    items.extend(self._set_job_status(index, job))
            self._bump(index, "allocs", "job_summary")
            self._stamp(index, items)
        self.notify.notify(items)

    def update_allocs_from_client(self, index: int, allocs: List[Allocation]) -> None:
        """Client status sync (state_store.go:843): only client-owned
        fields change; alloc_modify_index is NOT bumped so the client's
        long-poll diff (keyed on it) ignores its own writes."""
        items = [watch.table("allocs")]
        with self._lock:
            table = self._tables["allocs"].for_write()
            for update in allocs:
                existing = table.get(update.id)
                if existing is None:
                    continue
                alloc = existing.copy()
                alloc.client_status = update.client_status
                alloc.client_description = update.client_description
                # Deep-copy: the caller keeps mutating its TaskState objects
                # and stored records must stay immutable for snapshots.
                alloc.task_states = {
                    k: _copy.deepcopy(v) for k, v in update.task_states.items()
                }
                alloc.modify_index = index
                table[alloc.id] = alloc
                self._update_summary_with_alloc(index, alloc, existing)
                job = self._tables["jobs"].data.get(alloc.job_id)
                if job is not None:
                    items.extend(self._set_job_status(index, job))
                items.extend(
                    [
                        watch.alloc(alloc.id),
                        watch.alloc_job(alloc.job_id),
                        watch.alloc_node(alloc.node_id),
                        watch.alloc_eval(alloc.eval_id),
                        watch.job_summary(alloc.job_id),
                    ]
                )
            self._bump(index, "allocs", "job_summary")
            self._stamp(index, items)
        self.notify.notify(items)

    # ------------------------------------------------------------------
    # derived state (job status + summaries)
    # ------------------------------------------------------------------

    def _ensure_job_summary(self, index: int, job: Job) -> None:
        summaries = self._tables["job_summary"].for_write()
        existing = summaries.get(job.id)
        summary = existing.copy() if existing else JobSummary(job_id=job.id, create_index=index)
        for tg in job.task_groups:
            summary.summary.setdefault(tg.name, TaskGroupSummary())
        summary.modify_index = index
        summaries[job.id] = summary

    def _update_summary_queued(self, index: int, ev: Evaluation) -> None:
        summaries = self._tables["job_summary"].for_write()
        existing = summaries.get(ev.job_id)
        if existing is None:
            return
        summary = existing.copy()
        for tg, queued in ev.queued_allocations.items():
            tgs = summary.summary.setdefault(tg, TaskGroupSummary())
            tgs.queued = queued
        summary.modify_index = index
        summaries[ev.job_id] = summary

    def _update_summary_with_alloc(
        self, index: int, alloc: Allocation, existing: Optional[Allocation]
    ) -> None:
        """Maintain per-task-group client-status counts
        (state_store.go:1552 updateSummaryWithAlloc)."""
        summaries = self._tables["job_summary"].for_write()
        cur = summaries.get(alloc.job_id)
        if cur is None:
            cur = JobSummary(job_id=alloc.job_id, create_index=index)
        summary = cur.copy()
        tgs = summary.summary.setdefault(alloc.task_group, TaskGroupSummary())

        def bucket(status: str) -> Optional[str]:
            return {
                consts.ALLOC_CLIENT_PENDING: "starting",
                consts.ALLOC_CLIENT_RUNNING: "running",
                consts.ALLOC_CLIENT_COMPLETE: "complete",
                consts.ALLOC_CLIENT_FAILED: "failed",
                consts.ALLOC_CLIENT_LOST: "lost",
            }.get(status)

        if existing is not None:
            old = bucket(existing.client_status)
            if old and getattr(tgs, old) > 0:
                setattr(tgs, old, getattr(tgs, old) - 1)
        new = bucket(alloc.client_status)
        if new:
            setattr(tgs, new, getattr(tgs, new) + 1)
        summary.modify_index = index
        summaries[alloc.job_id] = summary

    def _get_job_status(self, job: Job, eval_delete: bool) -> str:
        """Derive job status (state_store.go:1457 getJobStatus): running if
        any non-terminal alloc; pending if any non-terminal eval; dead when
        everything outstanding is terminal (or evals were GC'd); a brand-new
        job with nothing outstanding is pending (running if periodic)."""
        has_alloc = False
        for aid in self._indexes["allocs_by_job"].data.get(job.id, ()):
            alloc = self._tables["allocs"].data.get(aid)
            if alloc is None:
                continue
            has_alloc = True
            if not alloc.terminal_status():
                return consts.JOB_STATUS_RUNNING
        has_eval = False
        for eid in self._indexes["evals_by_job"].data.get(job.id, ()):
            ev = self._tables["evals"].data.get(eid)
            if ev is None:
                continue
            has_eval = True
            if not ev.terminal_status():
                return consts.JOB_STATUS_PENDING
        if eval_delete or has_eval or has_alloc:
            return consts.JOB_STATUS_DEAD
        # A periodic parent never gets allocs/evals of its own.
        if job.is_periodic():
            return consts.JOB_STATUS_RUNNING
        return consts.JOB_STATUS_PENDING

    def _set_job_status(self, index: int, job: Job, eval_delete: bool = False) -> list:
        """Recompute and store the derived job status (state_store.go:1417
        setJobStatus). Returns the watch items to notify (empty when the
        status is unchanged); a change also bumps the jobs table index."""
        status = self._get_job_status(job, eval_delete)
        stored = self._tables["jobs"].data.get(job.id)
        if stored is None or stored.status == status:
            return []  # avoid the jobs-table copy-on-write when unchanged
        jobs = self._tables["jobs"].for_write()
        updated = jobs[job.id].copy()
        updated.status = status
        updated.modify_index = index
        jobs[job.id] = updated
        self._bump(index, "jobs")
        return [watch.table("jobs"), watch.job(job.id)]

    # ------------------------------------------------------------------
    # persistence (FSM snapshot install/restore)
    # ------------------------------------------------------------------

    def persist(self) -> dict:
        from ..utils.codec import to_dict

        with self._lock:
            return {
                "nodes": [to_dict(n) for n in self._tables["nodes"].data.values()],
                "jobs": [to_dict(j) for j in self._tables["jobs"].data.values()],
                "job_summary": [
                    to_dict(s) for s in self._tables["job_summary"].data.values()
                ],
                "periodic_launch": [
                    to_dict(p) for p in self._tables["periodic_launch"].data.values()
                ],
                "evals": [to_dict(e) for e in self._tables["evals"].data.values()],
                "allocs": [to_dict(a) for a in self._tables["allocs"].data.values()],
                "vault_accessors": [
                    to_dict(v)
                    for v in self._tables["vault_accessors"].data.values()
                ],
                "table_indexes": dict(self._table_indexes),
                "latest_index": self._latest_index,
                "scope_indexes": [
                    [kind, key, idx]
                    for (kind, key), idx in self._scope_indexes.items()
                ],
                "scope_floor": self._scope_floor,
            }

    @classmethod
    def restore(cls, data: dict) -> "StateStore":
        from ..utils.codec import from_dict

        store = cls()
        with store._lock:
            for raw in data.get("nodes", []):
                n = from_dict(Node, raw)
                store._tables["nodes"].data[n.id] = n
            for raw in data.get("jobs", []):
                j = from_dict(Job, raw)
                store._tables["jobs"].data[j.id] = j
            for raw in data.get("job_summary", []):
                s = from_dict(JobSummary, raw)
                store._tables["job_summary"].data[s.job_id] = s
            for raw in data.get("periodic_launch", []):
                p = from_dict(PeriodicLaunch, raw)
                store._tables["periodic_launch"].data[p.id] = p
            for raw in data.get("evals", []):
                e = from_dict(Evaluation, raw)
                store._tables["evals"].data[e.id] = e
                store._indexes["evals_by_job"].add(e.job_id, e.id)
            for raw in data.get("allocs", []):
                a = from_dict(Allocation, raw)
                store._tables["allocs"].data[a.id] = a
                store._indexes["allocs_by_job"].add(a.job_id, a.id)
                store._indexes["allocs_by_node"].add(a.node_id, a.id)
                store._indexes["allocs_by_eval"].add(a.eval_id, a.id)
            from ..structs.alloc import VaultAccessor

            for raw in data.get("vault_accessors", []):
                v = from_dict(VaultAccessor, raw)
                store._tables["vault_accessors"].data[v.accessor] = v
            store._table_indexes = dict(data.get("table_indexes", {}))
            store._latest_index = data.get("latest_index", 0)
            scopes = data.get("scope_indexes")
            if scopes is None:
                # Snapshot predates scope persistence: every scope's
                # history is unknown, so the floor is the whole
                # restored history (conservative global-index wakes for
                # pre-restore scopes, exact tracking from here on).
                store._scope_floor = store._latest_index
            else:
                store._scope_indexes = {
                    (kind, key): idx for kind, key, idx in scopes
                }
                store._scope_floor = data.get("scope_floor", 0)
        return store
