"""Scoped watch notifications powering blocking queries.

Reference: nomad/watch/watch.go:11 (Item — one-scope-per-item keys) and
nomad/notify.go:7 (NotifyGroup). A watcher subscribes to a set of scoped
items; every state-store write transaction notifies the union of the
scopes it touched.
"""

from __future__ import annotations

import threading
from typing import Dict, FrozenSet, Iterable, Set, Tuple

# A watch item is a (kind, key) pair, e.g. ("table", "nodes"),
# ("alloc_job", job_id), ("eval", eval_id), ("node", node_id).
Item = Tuple[str, str]


def table(name: str) -> Item:
    return ("table", name)


def job(job_id: str) -> Item:
    return ("job", job_id)


def job_summary(job_id: str) -> Item:
    return ("job_summary", job_id)


def node(node_id: str) -> Item:
    return ("node", node_id)


def eval_item(eval_id: str) -> Item:
    return ("eval", eval_id)


def alloc(alloc_id: str) -> Item:
    return ("alloc", alloc_id)


def alloc_job(job_id: str) -> Item:
    return ("alloc_job", job_id)


def alloc_node(node_id: str) -> Item:
    return ("alloc_node", node_id)


def alloc_eval(eval_id: str) -> Item:
    return ("alloc_eval", eval_id)


class NotifyGroup:
    """Fan-out notification: wait on any of a set of items.

    Two consumer shapes: per-query Events (``watch``/``stop_watch``,
    the thread-parking blocking query) and process-wide sinks
    (``subscribe``), callables invoked with every commit's item list —
    the read-plane multiplexer's wake feed. Sinks run OUTSIDE the
    group lock, on the committing (FSM) thread, so they must be cheap
    and non-blocking (the mux only appends to a deque and signals its
    own condition)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._watchers: Dict[Item, Set[threading.Event]] = {}
        self._sinks: list = []  # guarded-by: _lock (copy-on-write)

    def watch(self, items: Iterable[Item]) -> threading.Event:
        ev = threading.Event()
        with self._lock:
            for item in items:
                self._watchers.setdefault(item, set()).add(ev)
        return ev

    def stop_watch(self, items: Iterable[Item], ev: threading.Event) -> None:
        with self._lock:
            for item in items:
                group = self._watchers.get(item)
                if group:
                    group.discard(ev)
                    if not group:
                        del self._watchers[item]

    def subscribe(self, sink) -> None:
        """Register a commit sink: called with every notify()'s item
        list (a materialized list, safe to retain)."""
        with self._lock:
            self._sinks = self._sinks + [sink]

    def unsubscribe(self, sink) -> None:
        with self._lock:
            self._sinks = [s for s in self._sinks if s is not sink]

    def notify(self, items: Iterable[Item]) -> None:
        items = list(items)
        fired: Set[threading.Event] = set()
        with self._lock:
            sinks = self._sinks
            for item in items:
                for ev in self._watchers.get(item, ()):
                    fired.add(ev)
        for ev in fired:
            ev.set()
        for sink in sinks:
            sink(items)
