"""Status and type constants of the data model.

Reference: nomad/structs/structs.go (status const blocks around
Job/Node/Alloc/Eval definitions at structs.go:629,1068,2854,3219).
"""

# --- Job types (structs.go JobType*) ---
JOB_TYPE_SERVICE = "service"
JOB_TYPE_BATCH = "batch"
JOB_TYPE_SYSTEM = "system"
JOB_TYPE_CORE = "_core"

# --- Job statuses ---
JOB_STATUS_PENDING = "pending"
JOB_STATUS_RUNNING = "running"
JOB_STATUS_DEAD = "dead"

# --- Priorities ---
JOB_MIN_PRIORITY = 1
JOB_DEFAULT_PRIORITY = 50
JOB_MAX_PRIORITY = 100
CORE_JOB_PRIORITY = JOB_MAX_PRIORITY * 2

# --- Node statuses ---
NODE_STATUS_INIT = "initializing"
NODE_STATUS_READY = "ready"
NODE_STATUS_DOWN = "down"

# --- Allocation desired statuses ---
ALLOC_DESIRED_RUN = "run"
ALLOC_DESIRED_STOP = "stop"
ALLOC_DESIRED_EVICT = "evict"

# --- Allocation client statuses ---
ALLOC_CLIENT_PENDING = "pending"
ALLOC_CLIENT_RUNNING = "running"
ALLOC_CLIENT_COMPLETE = "complete"
ALLOC_CLIENT_FAILED = "failed"
ALLOC_CLIENT_LOST = "lost"

# --- Evaluation statuses ---
EVAL_STATUS_BLOCKED = "blocked"
EVAL_STATUS_PENDING = "pending"
EVAL_STATUS_COMPLETE = "complete"
EVAL_STATUS_FAILED = "failed"
EVAL_STATUS_CANCELLED = "canceled"

# --- Evaluation trigger reasons (structs.go:3183-3190) ---
EVAL_TRIGGER_JOB_REGISTER = "job-register"
EVAL_TRIGGER_JOB_DEREGISTER = "job-deregister"
EVAL_TRIGGER_PERIODIC_JOB = "periodic-job"
EVAL_TRIGGER_NODE_UPDATE = "node-update"
EVAL_TRIGGER_SCHEDULED = "scheduled"
EVAL_TRIGGER_ROLLING_UPDATE = "rolling-update"
EVAL_TRIGGER_MAX_PLANS = "max-plan-attempts"
# Broker delivery-limit exhaustion: the eval was dead-lettered to the
# failed queue with a structured reason (server/broker.py nack()).
EVAL_TRIGGER_DEAD_LETTER = "delivery-limit-exhausted"
# Overload protection (nomad_tpu/admission): the eval was shed from a
# full bounded ready queue (priority-aware, lowest-priority newest-first;
# server/broker.py _shed_locked) ...
EVAL_TRIGGER_SHED = "shed-overload"
# ... or its creation-stamped deadline passed before it could be
# dispatched (broker dequeue skip / dispatch-pipeline launch drop).
EVAL_TRIGGER_EXPIRED = "deadline-expired"
# Churn workflows (nomad_tpu/migrate): a drain storm's displaced allocs
# that exceeded the in-flight migration budget ride a follow-up eval
# with this trigger (the budget analog of rolling-update follow-ups) ...
EVAL_TRIGGER_MIGRATION = "migration-budget"
# ... and a job whose alloc was evicted by a higher-priority eval's
# preemption pass gets a replacement eval with this trigger (it
# typically blocks until capacity returns — the cluster was red).
EVAL_TRIGGER_PREEMPTION = "preemption"
# Continuous defragmentation (nomad_tpu/defrag): the leader-side
# optimizer's bounded migration waves ride evals with this trigger,
# carrying the alloc ids to move (Evaluation.defrag_alloc_ids) and the
# solver's target nodes (Evaluation.defrag_targets) — the scheduler
# treats the marked allocs as budget-exempt migrations (the loop holds
# the governor slots) and prefers the solver's target for each
# replacement placement.
EVAL_TRIGGER_DEFRAG = "defrag-migration"

# --- Task states (structs.go:2317) ---
TASK_STATE_PENDING = "pending"
TASK_STATE_RUNNING = "running"
TASK_STATE_DEAD = "dead"

# --- Task events (structs.go:2434) ---
TASK_EVENT_STARTED = "Started"
TASK_EVENT_TERMINATED = "Terminated"
TASK_EVENT_FAILED_VALIDATION = "Failed Validation"
TASK_EVENT_DRIVER_FAILURE = "Driver Failure"
TASK_EVENT_RECEIVED = "Received"
TASK_EVENT_RESTARTING = "Restarting"
TASK_EVENT_NOT_RESTARTING = "Not Restarting"
TASK_EVENT_KILLING = "Killing"
TASK_EVENT_KILLED = "Killed"
TASK_EVENT_DOWNLOADING_ARTIFACTS = "Downloading Artifacts"
TASK_EVENT_ARTIFACT_DOWNLOAD_FAILED = "Failed Artifact Download"
TASK_EVENT_SIGNALING = "Signaling"
TASK_EVENT_RESTART_SIGNAL = "Restart Signaled"
TASK_EVENT_DISK_EXCEEDED = "Disk Resources Exceeded"

# --- Constraint operands (structs.go:2713-2715, feasible.go:337-371) ---
CONSTRAINT_DISTINCT_HOSTS = "distinct_hosts"
CONSTRAINT_REGEX = "regexp"
CONSTRAINT_VERSION = "version"

# --- Restart policy modes (structs.go RestartPolicy) ---
RESTART_POLICY_MODE_DELAY = "delay"
RESTART_POLICY_MODE_FAIL = "fail"

# --- Dynamic port range (structs/network.go:11-19) ---
MIN_DYNAMIC_PORT = 20000
MAX_DYNAMIC_PORT = 60000
MAX_VALID_PORT = 65536
MAX_RAND_PORT_ATTEMPTS = 20

# --- Core (GC) job ids (core_sched.go) ---
CORE_JOB_EVAL_GC = "eval-gc"
CORE_JOB_NODE_GC = "node-gc"
CORE_JOB_JOB_GC = "job-gc"
CORE_JOB_FORCE_GC = "force-gc"

# Node unique-attribute namespace excluded from computed class
# (structs/node_class.go:13).
NODE_UNIQUE_NAMESPACE = "unique."
