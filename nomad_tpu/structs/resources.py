"""Resource types: Port, NetworkResource, Resources.

Reference: nomad/structs/structs.go:765 (Resources), :917 (NetworkResource),
:924 (Port).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import List, Optional, Tuple


@dataclass
class Port:
    label: str = ""
    value: int = 0


@dataclass
class NetworkResource:
    device: str = ""  # interface name
    cidr: str = ""  # CIDR block of the interface
    ip: str = ""  # host IP
    mbits: int = 0  # throughput
    reserved_ports: List[Port] = field(default_factory=list)
    dynamic_ports: List[Port] = field(default_factory=list)

    def copy(self) -> "NetworkResource":
        # Field-wise: Ports are two-field value objects, and this copy
        # runs once per task per upserted alloc (plan-apply hot path).
        return NetworkResource(
            device=self.device, cidr=self.cidr, ip=self.ip,
            mbits=self.mbits,
            reserved_ports=[Port(p.label, p.value)
                            for p in self.reserved_ports],
            dynamic_ports=[Port(p.label, p.value)
                           for p in self.dynamic_ports],
        )

    def add(self, delta: "NetworkResource") -> None:
        self.mbits += delta.mbits
        self.reserved_ports.extend(copy.deepcopy(delta.reserved_ports))

    def port_labels(self) -> dict:
        labels = {}
        for p in self.reserved_ports + self.dynamic_ports:
            labels[p.label] = p.value
        return labels


@dataclass
class Resources:
    cpu: int = 0  # MHz
    memory_mb: int = 0
    disk_mb: int = 0
    iops: int = 0
    networks: List[NetworkResource] = field(default_factory=list)

    DEFAULT_CPU = 100
    DEFAULT_MEMORY_MB = 10
    DEFAULT_DISK_MB = 300
    DEFAULT_IOPS = 0

    def copy(self) -> "Resources":
        new = Resources.__new__(Resources)
        new.__dict__.update(self.__dict__)
        new.networks = [n.copy() for n in self.networks]
        return new

    def canonicalize(self) -> None:
        if self.cpu == 0:
            self.cpu = self.DEFAULT_CPU
        if self.memory_mb == 0:
            self.memory_mb = self.DEFAULT_MEMORY_MB
        if self.disk_mb == 0:
            self.disk_mb = self.DEFAULT_DISK_MB

    def merge(self, other: "Resources") -> None:
        """Overlay non-zero fields of other (structs.go Resources.Merge)."""
        if other.cpu:
            self.cpu = other.cpu
        if other.memory_mb:
            self.memory_mb = other.memory_mb
        if other.disk_mb:
            self.disk_mb = other.disk_mb
        if other.iops:
            self.iops = other.iops
        if other.networks:
            self.networks = [n.copy() for n in other.networks]

    def add(self, delta: Optional["Resources"]) -> None:
        """Accumulate delta into self; networks are summed by index
        (structs.go Resources.Add)."""
        if delta is None:
            return
        self.cpu += delta.cpu
        self.memory_mb += delta.memory_mb
        self.disk_mb += delta.disk_mb
        self.iops += delta.iops
        for idx, net in enumerate(delta.networks):
            if idx < len(self.networks):
                self.networks[idx].add(net)
            else:
                self.networks.append(net.copy())

    def superset(self, other: "Resources") -> Tuple[bool, str]:
        """Whether self >= other on every scalar dimension; returns the
        first exhausted dimension name (structs.go Resources.Superset —
        network is checked separately via NetworkIndex)."""
        if self.cpu < other.cpu:
            return False, "cpu"
        if self.memory_mb < other.memory_mb:
            return False, "memory"
        if self.disk_mb < other.disk_mb:
            return False, "disk"
        if self.iops < other.iops:
            return False, "iops"
        return True, ""

    def net_index(self, n: NetworkResource) -> int:
        """Index of a network resource matching n's device, else -1."""
        for i, net in enumerate(self.networks):
            if net.device == n.device:
                return i
        return -1
