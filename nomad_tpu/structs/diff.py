"""Structural job diffs for `plan` dry-runs.

Reference: nomad/structs/diff.go:48-954 (Job.Diff / TaskGroup / Task /
ObjectDiff / FieldDiff) and scheduler/annotate.go:37 (merging plan
counts into the diff). The reference hand-writes a differ per struct;
here one recursive differ walks the dataclasses, which yields the same
diff shape (fields / nested objects / named-list matching) for every
type in the job tree.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .job import Job, Task, TaskGroup

DIFF_NONE = "None"
DIFF_ADDED = "Added"
DIFF_DELETED = "Deleted"
DIFF_EDITED = "Edited"

# Fields that never belong in a user-facing spec diff.
_JOB_SKIP = {
    "id", "status", "status_description", "create_index", "modify_index",
    "job_modify_index", "vault_token", "task_groups", "parent_id",
}
_TG_SKIP = {"name", "tasks"}
_TASK_SKIP = {"name"}

# How to identify elements of a named object list when pairing old/new.
_LIST_KEYS = {
    "task_groups": "name",
    "tasks": "name",
    "services": "name",
    "checks": "name",
    "templates": "dest_path",
    "artifacts": "getter_source",
}


@dataclass
class FieldDiff:
    type: str = DIFF_NONE
    name: str = ""
    old: str = ""
    new: str = ""


@dataclass
class ObjectDiff:
    type: str = DIFF_NONE
    name: str = ""
    fields: List[FieldDiff] = field(default_factory=list)
    objects: List["ObjectDiff"] = field(default_factory=list)


@dataclass
class TaskDiff:
    type: str = DIFF_NONE
    name: str = ""
    fields: List[FieldDiff] = field(default_factory=list)
    objects: List[ObjectDiff] = field(default_factory=list)
    annotations: List[str] = field(default_factory=list)


@dataclass
class TaskGroupDiff:
    type: str = DIFF_NONE
    name: str = ""
    fields: List[FieldDiff] = field(default_factory=list)
    objects: List[ObjectDiff] = field(default_factory=list)
    tasks: List[TaskDiff] = field(default_factory=list)
    # Placement counts merged in by annotate() (scheduler/annotate.go:17-24):
    # create / destroy / migrate / in-place update / canary ...
    updates: Dict[str, int] = field(default_factory=dict)


@dataclass
class JobDiff:
    type: str = DIFF_NONE
    id: str = ""
    fields: List[FieldDiff] = field(default_factory=list)
    objects: List[ObjectDiff] = field(default_factory=list)
    task_groups: List[TaskGroupDiff] = field(default_factory=list)


def _is_scalar(v: Any) -> bool:
    return v is None or isinstance(v, (str, int, float, bool))


def _render(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def _field_diff(name: str, old: Any, new: Any, contextual: bool) -> Optional[FieldDiff]:
    old_empty = old is None or old == "" or old == [] or old == {}
    new_empty = new is None or new == "" or new == [] or new == {}
    if old == new or (old_empty and new_empty):
        if contextual:
            return FieldDiff(DIFF_NONE, name, _render(old), _render(new))
        return None
    if old_empty:
        return FieldDiff(DIFF_ADDED, name, "", _render(new))
    if new_empty:
        return FieldDiff(DIFF_DELETED, name, _render(old), "")
    return FieldDiff(DIFF_EDITED, name, _render(old), _render(new))


def _map_diff(name: str, old: Dict, new: Dict, contextual: bool) -> Optional[ObjectDiff]:
    old = old or {}
    new = new or {}
    fields: List[FieldDiff] = []
    for k in sorted(set(old) | set(new)):
        fd = _field_diff(f"{name}[{k}]", old.get(k), new.get(k), contextual)
        if fd is not None:
            fields.append(fd)
    return _wrap_object(name, fields, [], old, new, contextual)


def _wrap_object(name: str, fields: List[FieldDiff], objects: List[ObjectDiff],
                 old: Any, new: Any, contextual: bool = False) -> Optional[ObjectDiff]:
    changed = ([f for f in fields if f.type != DIFF_NONE]
               or [o for o in objects if o.type != DIFF_NONE])
    if not changed:
        if contextual and (fields or objects):
            return ObjectDiff(DIFF_NONE, name, fields, objects)
        return None
    if old in (None, {}, []):
        typ = DIFF_ADDED
    elif new in (None, {}, []):
        typ = DIFF_DELETED
    else:
        typ = DIFF_EDITED
    return ObjectDiff(typ, name, fields, objects)


def _scalar_list_diff(name: str, old: List, new: List, contextual: bool) -> Optional[ObjectDiff]:
    old_set = set(map(str, old or []))
    new_set = set(map(str, new or []))
    fields: List[FieldDiff] = []
    for v in sorted(old_set | new_set):
        in_old, in_new = v in old_set, v in new_set
        if in_old and in_new:
            if contextual:
                fields.append(FieldDiff(DIFF_NONE, name, v, v))
        elif in_new:
            fields.append(FieldDiff(DIFF_ADDED, name, "", v))
        else:
            fields.append(FieldDiff(DIFF_DELETED, name, v, ""))
    return _wrap_object(name, fields, [], old, new, contextual)


def _object_set_diff(name: str, old: List, new: List) -> List[ObjectDiff]:
    """Set-style diff for unnamed object lists (constraints): elements
    are only ever Added or Deleted, never Edited (diff.go setDiff)."""
    out: List[ObjectDiff] = []
    old_strs = {_obj_repr(o): o for o in (old or [])}
    new_strs = {_obj_repr(o): o for o in (new or [])}
    for key in sorted(old_strs.keys() - new_strs.keys()):
        out.append(_obj_to_object_diff(name, old_strs[key], DIFF_DELETED))
    for key in sorted(new_strs.keys() - old_strs.keys()):
        out.append(_obj_to_object_diff(name, new_strs[key], DIFF_ADDED))
    return out


def _obj_repr(o: Any) -> str:
    if dataclasses.is_dataclass(o):
        return repr(dataclasses.astuple(o))
    return repr(o)


def _obj_to_object_diff(name: str, o: Any, typ: str) -> ObjectDiff:
    fields = []
    for f in dataclasses.fields(o):
        v = getattr(o, f.name)
        if _is_scalar(v):
            side = _render(v)
            fields.append(FieldDiff(
                typ, f.name,
                side if typ == DIFF_DELETED else "",
                side if typ == DIFF_ADDED else "",
            ))
    return ObjectDiff(typ, name, fields, [])


def _dataclass_diff(name: str, old: Any, new: Any, contextual: bool,
                    skip=frozenset()) -> tuple[List[FieldDiff], List[ObjectDiff]]:
    """Diff two same-typed dataclasses (either may be None) into flat
    field diffs plus nested object diffs."""
    template = old if old is not None else new
    fields: List[FieldDiff] = []
    objects: List[ObjectDiff] = []
    for f in dataclasses.fields(template):
        if f.name in skip:
            continue
        ov = getattr(old, f.name) if old is not None else None
        nv = getattr(new, f.name) if new is not None else None
        if _is_scalar(ov) and _is_scalar(nv):
            fd = _field_diff(f.name, ov, nv, contextual)
            if fd is not None:
                fields.append(fd)
        elif isinstance(ov or nv, dict):
            od = _map_diff(f.name, ov, nv, contextual)
            if od is not None:
                objects.append(od)
        elif isinstance(ov or nv, list):
            sample = (ov or nv)[0] if (ov or nv) else None
            if sample is None or _is_scalar(sample):
                od = _scalar_list_diff(f.name, ov, nv, contextual)
                if od is not None:
                    objects.append(od)
            elif f.name in _LIST_KEYS:
                objects.extend(_named_list_diff(f.name, ov, nv, contextual))
            else:
                objects.extend(_object_set_diff(f.name, ov, nv))
        elif dataclasses.is_dataclass(ov or nv):
            if ov == nv and not contextual:
                continue
            sub_f, sub_o = _dataclass_diff(f.name, ov, nv, contextual)
            od = _wrap_object(f.name, sub_f, sub_o, ov, nv, contextual)
            if od is not None:
                objects.append(od)
    return fields, objects


def _named_list_diff(name: str, old: List, new: List, contextual: bool) -> List[ObjectDiff]:
    key = _LIST_KEYS[name]
    singular = name[:-1] if name.endswith("s") else name
    old_by = {getattr(o, key): o for o in (old or [])}
    new_by = {getattr(o, key): o for o in (new or [])}
    out: List[ObjectDiff] = []
    for k in sorted(set(old_by) | set(new_by)):
        ov, nv = old_by.get(k), new_by.get(k)
        if ov == nv and not contextual:
            continue
        sub_f, sub_o = _dataclass_diff(singular, ov, nv, contextual)
        od = _wrap_object(f"{singular}[{k}]", sub_f, sub_o, ov, nv, contextual)
        if od is not None:
            out.append(od)
    return out


def _diff_type_of(old: Any, new: Any, fields, objects, children) -> str:
    if old is None and new is not None:
        return DIFF_ADDED
    if new is None and old is not None:
        return DIFF_DELETED
    changed = ([f for f in fields if f.type != DIFF_NONE] or objects
               or [c for c in children if c.type != DIFF_NONE])
    return DIFF_EDITED if changed else DIFF_NONE


def task_diff(old: Optional[Task], new: Optional[Task], contextual: bool = False) -> TaskDiff:
    template = old if old is not None else new
    fields, objects = _dataclass_diff("task", old, new, contextual, skip=_TASK_SKIP)
    d = TaskDiff(name=template.name if template else "", fields=fields, objects=objects)
    d.type = _diff_type_of(old, new, fields, objects, [])
    if d.type == DIFF_ADDED:
        d.annotations.append("forces create")
    elif d.type == DIFF_DELETED:
        d.annotations.append("forces destroy")
    return d


def task_group_diff(old: Optional[TaskGroup], new: Optional[TaskGroup],
                    contextual: bool = False) -> TaskGroupDiff:
    template = old if old is not None else new
    fields, objects = _dataclass_diff("group", old, new, contextual, skip=_TG_SKIP)
    old_tasks = {t.name: t for t in (old.tasks if old else [])}
    new_tasks = {t.name: t for t in (new.tasks if new else [])}
    tasks = []
    for name in sorted(set(old_tasks) | set(new_tasks)):
        td = task_diff(old_tasks.get(name), new_tasks.get(name), contextual)
        if td.type != DIFF_NONE or contextual:
            tasks.append(td)
    d = TaskGroupDiff(name=template.name if template else "",
                      fields=fields, objects=objects, tasks=tasks)
    d.type = _diff_type_of(old, new, fields, objects, tasks)
    return d


def job_diff(old: Optional[Job], new: Optional[Job], contextual: bool = False) -> JobDiff:
    """Job.Diff (diff.go:59): structural diff keyed by task-group and
    task name; index/status fields are excluded."""
    if old is not None and new is not None and old.id != new.id:
        raise ValueError("can not diff jobs with different IDs")
    template = old if old is not None else new
    fields, objects = _dataclass_diff("job", old, new, contextual, skip=_JOB_SKIP)
    old_tgs = {tg.name: tg for tg in (old.task_groups if old else [])}
    new_tgs = {tg.name: tg for tg in (new.task_groups if new else [])}
    tgs = []
    for name in sorted(set(old_tgs) | set(new_tgs)):
        tgd = task_group_diff(old_tgs.get(name), new_tgs.get(name), contextual)
        if tgd.type != DIFF_NONE or contextual:
            tgs.append(tgd)
    d = JobDiff(id=template.id if template else "",
                fields=fields, objects=objects, task_groups=tgs)
    d.type = _diff_type_of(old, new, fields, objects, tgs)
    return d


# --------------------------------------------------------------- annotate

UPDATE_TYPE_IGNORE = "ignore"
UPDATE_TYPE_CREATE = "create"
UPDATE_TYPE_DESTROY = "destroy"
UPDATE_TYPE_MIGRATE = "migrate"
UPDATE_TYPE_IN_PLACE = "in-place update"
UPDATE_TYPE_DESTRUCTIVE = "create/destroy update"


def annotate(diff: JobDiff, annotations) -> None:
    """Merge scheduler plan counts into the diff's per-group `updates`
    maps (scheduler/annotate.go:37). `annotations` is the plan's
    PlanAnnotations (desired_tg_updates: {tg: DesiredUpdates})."""
    if annotations is None:
        return
    desired = getattr(annotations, "desired_tg_updates", None) or {}
    by_name = {tg.name: tg for tg in diff.task_groups}
    for tg_name, du in desired.items():
        tgd = by_name.get(tg_name)
        if tgd is None:
            tgd = TaskGroupDiff(type=DIFF_NONE, name=tg_name)
            diff.task_groups.append(tgd)
            by_name[tg_name] = tgd
        counts = du if isinstance(du, dict) else dataclasses.asdict(du)
        mapping = {
            "ignore": UPDATE_TYPE_IGNORE,
            "place": UPDATE_TYPE_CREATE,
            "stop": UPDATE_TYPE_DESTROY,
            "migrate": UPDATE_TYPE_MIGRATE,
            "in_place_update": UPDATE_TYPE_IN_PLACE,
            "destructive_update": UPDATE_TYPE_DESTRUCTIVE,
        }
        for key, label in mapping.items():
            n = counts.get(key, 0)
            if n:
                tgd.updates[label] = tgd.updates.get(label, 0) + int(n)
