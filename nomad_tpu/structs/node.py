"""Node type and computed node class.

Reference: nomad/structs/structs.go:629 (Node),
nomad/structs/node_class.go:31 (ComputeClass — hash over Datacenter,
Attributes, Meta, NodeClass, excluding `unique.`-prefixed map keys).
"""

from __future__ import annotations

import copy
import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from . import consts
from .job import Constraint
from .resources import Resources


@dataclass
class Node:
    id: str = ""
    secret_id: str = ""
    datacenter: str = ""
    name: str = ""
    http_addr: str = ""
    attributes: Dict[str, str] = field(default_factory=dict)
    resources: Optional[Resources] = None
    reserved: Optional[Resources] = None
    links: Dict[str, str] = field(default_factory=dict)
    meta: Dict[str, str] = field(default_factory=dict)
    node_class: str = ""
    computed_class: str = ""
    drain: bool = False
    status: str = consts.NODE_STATUS_INIT
    status_description: str = ""
    status_updated_at: float = 0.0
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "Node":
        return copy.deepcopy(self)

    def terminal_status(self) -> bool:
        return self.status == consts.NODE_STATUS_DOWN

    def ready(self) -> bool:
        return self.status == consts.NODE_STATUS_READY and not self.drain

    def compute_class(self) -> None:
        """Derive the computed class: a stable digest over the scheduling-
        relevant identity of the node, excluding `unique.` keys so nodes
        with identical capabilities share a class (the scheduler memoizes
        feasibility per class)."""
        h = hashlib.blake2b(digest_size=8)
        h.update(self.datacenter.encode())
        h.update(b"\x00")
        h.update(self.node_class.encode())
        for m in (self.attributes, self.meta):
            h.update(b"\x01")
            for k in sorted(m):
                if is_unique_namespace(k):
                    continue
                v = m[k]
                if not isinstance(v, (str, int, float, bool)):
                    # Escape hatch: a dynamic, non-hashable value (the
                    # reference's HashIncludeMap error path) has no
                    # stable digest — str() of a list/dict would make
                    # the class depend on repr ordering. Classless
                    # nodes evaluate feasibility per node and get a
                    # singleton class in models/classes.py.
                    self.computed_class = ""
                    return
                h.update(k.encode())
                h.update(b"\x02")
                h.update(str(v).encode())
                h.update(b"\x03")
        self.computed_class = "v1:" + h.hexdigest()


def is_unique_namespace(key: str) -> bool:
    return key.startswith(consts.NODE_UNIQUE_NAMESPACE)


def unique_namespace(key: str) -> str:
    return consts.NODE_UNIQUE_NAMESPACE + key


def escaped_constraints(constraints: List[Constraint]) -> List[Constraint]:
    """Constraints referencing unique node properties escape computed-class
    memoization (node_class.go:70-94)."""
    return [
        c
        for c in constraints
        if _target_escapes(c.ltarget) or _target_escapes(c.rtarget)
    ]


def _target_escapes(target: str) -> bool:
    return (
        target.startswith("${node.unique.")
        or target.startswith("${attr.unique.")
        or target.startswith("${meta.unique.")
    )
