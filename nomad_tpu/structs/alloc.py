"""Allocation, AllocMetric, TaskState/TaskEvent.

Reference: nomad/structs/structs.go:2854 (Allocation), :3074 (AllocMetric),
:2317 (TaskState), :2434 (TaskEvent).
"""

from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from . import consts
from .job import Job
from .resources import Resources


@dataclass
class TaskEvent:
    type: str = ""
    time: float = 0.0
    restart_reason: str = ""
    driver_error: str = ""
    exit_code: int = 0
    signal: int = 0
    message: str = ""
    kill_timeout: float = 0.0
    kill_error: str = ""
    start_delay: float = 0.0
    download_error: str = ""
    validation_error: str = ""


@dataclass
class TaskState:
    state: str = consts.TASK_STATE_PENDING
    failed: bool = False
    events: List[TaskEvent] = field(default_factory=list)

    def successful(self) -> bool:
        if self.state != consts.TASK_STATE_DEAD:
            return False
        return not self.failed


@dataclass
class AllocMetric:
    """The scheduler's explainability record attached to each placement
    attempt (structs.go:3074)."""

    nodes_evaluated: int = 0
    nodes_filtered: int = 0
    nodes_available: Dict[str, int] = field(default_factory=dict)  # by DC
    class_filtered: Dict[str, int] = field(default_factory=dict)
    constraint_filtered: Dict[str, int] = field(default_factory=dict)
    nodes_exhausted: int = 0
    class_exhausted: Dict[str, int] = field(default_factory=dict)
    dimension_exhausted: Dict[str, int] = field(default_factory=dict)
    scores: Dict[str, float] = field(default_factory=dict)  # "node.class" -> score
    allocation_time: float = 0.0  # seconds spent selecting
    coalesced_failures: int = 0

    def copy(self) -> "AllocMetric":
        # Field-wise (values are scalars/flat dicts): metrics are copied
        # once per upserted alloc, so the deepcopy machinery showed up
        # in the plan-apply profile. (__new__ + __dict__.update is ~4x
        # cheaper than copy.copy's reduce protocol.)
        new = AllocMetric.__new__(AllocMetric)
        new.__dict__.update(self.__dict__)
        new.nodes_available = dict(self.nodes_available)
        new.class_filtered = dict(self.class_filtered)
        new.constraint_filtered = dict(self.constraint_filtered)
        new.class_exhausted = dict(self.class_exhausted)
        new.dimension_exhausted = dict(self.dimension_exhausted)
        new.scores = dict(self.scores)
        return new

    def evaluate_node(self) -> None:
        self.nodes_evaluated += 1

    def filter_node(self, node, constraint: str) -> None:
        self.nodes_filtered += 1
        if node is not None and node.node_class:
            self.class_filtered[node.node_class] = self.class_filtered.get(node.node_class, 0) + 1
        if constraint:
            self.constraint_filtered[constraint] = self.constraint_filtered.get(constraint, 0) + 1

    def exhausted_node(self, node, dimension: str) -> None:
        self.nodes_exhausted += 1
        if node is not None and node.node_class:
            self.class_exhausted[node.node_class] = self.class_exhausted.get(node.node_class, 0) + 1
        if dimension:
            self.dimension_exhausted[dimension] = self.dimension_exhausted.get(dimension, 0) + 1

    def score_node(self, node, name: str, score: float) -> None:
        key = f"{node.id}.{name}"
        self.scores[key] = self.scores.get(key, 0.0) + score


@dataclass
class Allocation:
    id: str = ""
    eval_id: str = ""
    name: str = ""  # "<job>.<group>[<index>]"
    node_id: str = ""
    job_id: str = ""
    job: Optional[Job] = None
    task_group: str = ""
    resources: Optional[Resources] = None
    shared_resources: Optional[Resources] = None
    task_resources: Dict[str, Resources] = field(default_factory=dict)
    metrics: Optional[AllocMetric] = None
    desired_status: str = ""
    desired_description: str = ""
    client_status: str = ""
    client_description: str = ""
    task_states: Dict[str, TaskState] = field(default_factory=dict)
    previous_allocation: str = ""
    create_index: int = 0
    modify_index: int = 0
    alloc_modify_index: int = 0  # bumped only on scheduler-driven changes
    create_time: float = 0.0

    def copy(self) -> "Allocation":
        # Field-wise copy instead of copy.deepcopy: this is the plan
        # applier's hot path (a system job touching N nodes upserts N
        # allocs) and the deepcopy machinery dominated its profile. The
        # embedded job is immutable-by-convention (the store's MVCC
        # semantics: every job write stores a fresh object, readers
        # never mutate it in place) so the reference is shared.
        new = Allocation.__new__(Allocation)
        new.__dict__.update(self.__dict__)
        new.resources = self.resources.copy() if self.resources else None
        new.shared_resources = (
            self.shared_resources.copy() if self.shared_resources else None)
        new.task_resources = {
            k: r.copy() for k, r in self.task_resources.items()}
        new.metrics = self.metrics.copy() if self.metrics else None
        new.task_states = {
            k: TaskState(state=ts.state, failed=ts.failed,
                         events=[copy.copy(e) for e in ts.events])
            for k, ts in self.task_states.items()
        }
        # The dense matrix's usage memo must not survive into a copy
        # whose resources may be rewritten (in-place updates).
        new.__dict__.pop("_dense_usage", None)
        return new

    def index(self) -> int:
        """The per-group index parsed from the name suffix '[i]'."""
        lb = self.name.rfind("[")
        rb = self.name.rfind("]")
        if lb == -1 or rb == -1 or rb <= lb:
            return -1
        try:
            return int(self.name[lb + 1 : rb])
        except ValueError:
            return -1

    def terminal_status(self) -> bool:
        """Terminal from the scheduler's perspective (structs.go
        Allocation.TerminalStatus): desired stop/evict, or a terminal
        client status."""
        if self.desired_status in (consts.ALLOC_DESIRED_STOP, consts.ALLOC_DESIRED_EVICT):
            return True
        return self.client_status in (
            consts.ALLOC_CLIENT_COMPLETE,
            consts.ALLOC_CLIENT_FAILED,
            consts.ALLOC_CLIENT_LOST,
        )

    def ran_successfully(self) -> bool:
        """All task states dead and non-failed (used by batch filtering)."""
        if not self.task_states:
            return False
        return all(ts.successful() for ts in self.task_states.values())

    def stub(self) -> dict:
        return {
            "id": self.id,
            "eval_id": self.eval_id,
            "name": self.name,
            "node_id": self.node_id,
            "job_id": self.job_id,
            "task_group": self.task_group,
            "desired_status": self.desired_status,
            "desired_description": self.desired_description,
            "client_status": self.client_status,
            "client_description": self.client_description,
            "create_index": self.create_index,
            "modify_index": self.modify_index,
            "create_time": self.create_time,
        }


def remove_allocs(allocs: List[Allocation], remove: List[Allocation]) -> List[Allocation]:
    """allocs minus the ids of remove (structs/funcs.go:11)."""
    remove_ids = {a.id for a in remove}
    return [a for a in allocs if a.id not in remove_ids]


def filter_terminal_allocs(allocs: List[Allocation]):
    """Split allocs into (live, latest-terminal-by-name)
    (structs/funcs.go:33)."""
    live: List[Allocation] = []
    terminal: Dict[str, Allocation] = {}
    for a in allocs:
        if a.terminal_status():
            prev = terminal.get(a.name)
            if prev is None or prev.create_index < a.create_index:
                terminal[a.name] = a
        else:
            live.append(a)
    return live, terminal


def new_task_event(event_type: str) -> TaskEvent:
    return TaskEvent(type=event_type, time=time.time())


@dataclass
class VaultAccessor:
    """Tracking record for one derived vault token (reference
    structs.VaultAccessor, persisted in the vault_accessors table)."""

    accessor: str = ""
    alloc_id: str = ""
    task: str = ""
    node_id: str = ""
    policies: List[str] = field(default_factory=list)
    create_index: float = 0.0
