"""Plan and PlanResult — the scheduler's proposed state mutations.

Reference: nomad/structs/structs.go:3435 (Plan), :3528 (PlanResult),
:3475 (AppendUpdate), :3503 (PopUpdate), :3516 (AppendAlloc).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from . import consts
from .alloc import Allocation, AllocMetric
from .job import Job


@dataclass
class Plan:
    eval_id: str = ""
    eval_token: str = ""  # split-brain guard: must match broker's token
    priority: int = 0
    all_at_once: bool = False  # gang commit: reject unless fully applicable
    job: Optional[Job] = None
    # node id -> allocs to update/evict on that node
    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    # node id -> new allocations for that node
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    # node id -> lower-priority allocs this plan evicts to make room
    # for its placements (the dense preemption pass, ops/preempt.py).
    # A separate leg from node_update because the applier VERIFIES it
    # differently: each victim must still exist, be non-terminal, and
    # be strictly lower-priority than the plan — a victim that died or
    # changed underneath the scheduler rejects the node and forces a
    # replan, exactly like a placement that no longer fits.
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    # Gang atomicity leg (nomad_tpu/gang): gang key ("job/<tg>") ->
    # alloc ids of the gang's members in node_allocation. The plan
    # applier treats each group as ALL-OR-NOTHING across nodes: any
    # member's node failing verification removes every member of that
    # gang from the result (on accepted nodes too) — partial-commit
    # granularity stays per node for ordinary placements and becomes
    # per GANG for these. All members still commit in the one raft
    # apply the accepted plan rides.
    gang_groups: Dict[str, List[str]] = field(default_factory=dict)
    annotations: Optional["PlanAnnotations"] = None
    failed_tg_allocs: Dict[str, AllocMetric] = field(default_factory=dict)
    # Raft watermark of the snapshot the dense node matrix serving this
    # plan was built from (-1 = unknown/host path). On rejection the
    # plan applier reads it to tell an ordinary optimistic-concurrency
    # loss (the node moved PAST this index before verification) from
    # resident-matrix staleness (it didn't — the matrix claimed a fit
    # its own snapshot refutes), which decides whether the device-
    # resident delta chain must be purged (models/resident.py).
    matrix_index: int = -1

    def append_update(
        self, alloc: Allocation, desired_status: str, description: str
    ) -> None:
        """Record an evict/stop of an existing alloc. The copied alloc is
        stripped of its embedded job to keep the plan small (the reference
        nulls Job on updates, structs.go:3475)."""
        new_alloc = alloc.copy()
        new_alloc.job = None
        new_alloc.desired_status = desired_status
        new_alloc.desired_description = description
        self.node_update.setdefault(alloc.node_id, []).append(new_alloc)

    def pop_update(self, alloc: Allocation) -> None:
        """Undo the most recent staged update for alloc (used by the
        in-place-update path when the re-selection fails)."""
        updates = self.node_update.get(alloc.node_id, [])
        if updates and updates[-1].id == alloc.id:
            updates.pop()
            if not updates:
                del self.node_update[alloc.node_id]

    def append_alloc(self, alloc: Allocation) -> None:
        self.node_allocation.setdefault(alloc.node_id, []).append(alloc)

    def append_gang_alloc(self, gang_key: str, alloc: Allocation) -> None:
        """Stage one gang member: an ordinary placement PLUS membership
        in the gang's atomicity group (see gang_groups)."""
        self.append_alloc(alloc)
        self.gang_groups.setdefault(gang_key, []).append(alloc.id)

    def pop_gang(self, gang_key: str) -> int:
        """Unstage every placement of one gang (the scheduler backs a
        gang out when a member's host-side port assignment fails — an
        incomplete gang must never reach the applier). Returns the
        number of members removed."""
        ids = set(self.gang_groups.pop(gang_key, ()))
        if not ids:
            return 0
        removed = 0
        for node_id in list(self.node_allocation):
            kept = [a for a in self.node_allocation[node_id]
                    if a.id not in ids]
            removed += len(self.node_allocation[node_id]) - len(kept)
            if kept:
                self.node_allocation[node_id] = kept
            else:
                del self.node_allocation[node_id]
        return removed

    def append_preemption(
        self, alloc: Allocation, desired_status: str, description: str
    ) -> None:
        """Stage a preemption eviction of a lower-priority alloc. The
        scheduler passes consts.ALLOC_DESIRED_EVICT; the stamp commits
        through the plan applier's raft apply after per-victim
        verification (server/plan_apply.py), never directly."""
        new_alloc = alloc.copy()
        new_alloc.job = None
        new_alloc.desired_status = desired_status
        new_alloc.desired_description = description
        self.node_preemptions.setdefault(alloc.node_id, []).append(new_alloc)

    def pop_preemptions(self, node_id: str, n: int) -> None:
        """Un-stage the last ``n`` preemptions for a node (the dense
        commit loop backs out victims when the placement they were
        freeing room for fails host-side port assignment)."""
        victims = self.node_preemptions.get(node_id, [])
        if n > 0:
            del victims[-n:]
        if not victims:
            self.node_preemptions.pop(node_id, None)

    def is_no_op(self) -> bool:
        return (not self.node_update and not self.node_allocation
                and not self.node_preemptions)

    def copy(self) -> "Plan":
        return copy.deepcopy(self)


@dataclass
class PlanResult:
    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    # Preemption evictions that passed per-victim verification and
    # committed with the plan (the scheduler mints the victims' jobs
    # replacement evals from this).
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    refresh_index: int = 0  # worker must refresh its snapshot to this index
    alloc_index: int = 0  # raft index the accepted allocs committed at

    def is_no_op(self) -> bool:
        return (not self.node_update and not self.node_allocation
                and not self.node_preemptions)

    def full_commit(self, plan: Plan) -> tuple:
        """Compare attempted vs accepted placements: (full, expected, actual)."""
        expected = sum(len(v) for v in plan.node_allocation.values())
        actual = sum(len(v) for v in self.node_allocation.values())
        return expected == actual, expected, actual


@dataclass
class PlanAnnotations:
    desired_tg_updates: Dict[str, "DesiredUpdates"] = field(default_factory=dict)


@dataclass
class DesiredUpdates:
    ignore: int = 0
    place: int = 0
    migrate: int = 0
    stop: int = 0
    in_place_update: int = 0
    destructive_update: int = 0
