"""Port bitmap. Reference: nomad/structs/bitmap.go:6.

Backed by a Python int used as a bitset — set/check are O(1) amortized
and the TPU path summarizes these into dense per-node availability
counts anyway (see models/matrix.py).
"""

from __future__ import annotations

from typing import List


class Bitmap:
    __slots__ = ("size", "_bits")

    def __init__(self, size: int):
        if size <= 0:
            raise ValueError("bitmap size must be positive")
        self.size = size
        self._bits = 0

    def set(self, idx: int) -> None:
        self._bits |= 1 << idx

    def check(self, idx: int) -> bool:
        return bool(self._bits >> idx & 1)

    def clear(self) -> None:
        self._bits = 0

    def copy(self) -> "Bitmap":
        b = Bitmap(self.size)
        b._bits = self._bits
        return b

    def indexes_in_range(self, set_value: bool, lo: int, hi: int) -> List[int]:
        """All indexes in [lo, hi] whose bit equals set_value."""
        out = []
        bits = self._bits
        for i in range(lo, min(hi, self.size - 1) + 1):
            if bool(bits >> i & 1) == set_value:
                out.append(i)
        return out
