"""Evaluation — the unit of scheduler work.

Reference: nomad/structs/structs.go:3219 (Evaluation), :3359
(ShouldEnqueue), :3372 (ShouldBlock), :3385 (MakePlan), :3400
(NextRollingEval), :3417 (CreateBlockedEval).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..utils.ids import generate_uuid
from . import consts
from .alloc import AllocMetric
from .job import Job
from .plan import Plan


@dataclass
class Evaluation:
    id: str = ""
    priority: int = 0
    type: str = ""  # routes to a scheduler factory
    triggered_by: str = ""
    job_id: str = ""
    job_modify_index: int = 0
    node_id: str = ""
    node_modify_index: int = 0
    status: str = ""
    status_description: str = ""
    wait: float = 0.0  # seconds to delay before eligible (rolling updates)
    next_eval: str = ""
    previous_eval: str = ""
    blocked_eval: str = ""
    failed_tg_allocs: Dict[str, AllocMetric] = field(default_factory=dict)
    class_eligibility: Dict[str, bool] = field(default_factory=dict)
    escaped_computed_class: bool = False
    annotate_plan: bool = False
    queued_allocations: Dict[str, int] = field(default_factory=dict)  # tg -> queued count
    snapshot_index: int = 0
    create_index: int = 0
    modify_index: int = 0
    # Eval-lifecycle trace id (nomad_tpu/trace): stamped at creation,
    # carried through broker/dispatch/scheduler/plan so every layer's
    # spans land in one tree. Empty on evals minted by older callers —
    # the recorder falls back to the eval id.
    trace_id: str = ""
    # Overload protection (nomad_tpu/admission): absolute wall-clock
    # instant past which this eval is stale — the broker skips it at
    # dequeue and the dispatch pipeline drops it before matrix build.
    # 0.0 = no deadline. Stamped once at creation (priority-scaled,
    # admission/deadline.py) by the server's eval_update funnel.
    deadline: float = 0.0
    # Continuous defragmentation (nomad_tpu/defrag): on a
    # triggered_by=EVAL_TRIGGER_DEFRAG eval, the alloc ids of this job
    # the optimizer wants moved this wave (the scheduler promotes them
    # from the diff's ignore bucket to migrate — budget-exempt, the
    # loop already holds the governor slots) and the solver's target
    # node per alloc id (a placement PREFERENCE: the replacement still
    # runs the full feasibility stack and falls back to a free select).
    defrag_alloc_ids: List[str] = field(default_factory=list)
    defrag_targets: Dict[str, str] = field(default_factory=dict)
    # Wall-clock instant past which this wave's markers are VOID: the
    # loop abandons a wave (and releases its governor slots) after
    # WAVE_TIMEOUT, so an eval that surfaces later must not stage
    # budget-exempt evictions against slots nobody holds — and its
    # solve is stale anyway. The scheduler ignores expired markers
    # (the eval degrades to a no-op reconciliation); the next round
    # re-derives from fresh state. 0.0 = no deadline (tests).
    defrag_wave_expires: float = 0.0

    def copy(self) -> "Evaluation":
        return copy.deepcopy(self)

    def expired(self, now: Optional[float] = None) -> bool:
        """True when a deadline is set and has passed (wall clock)."""
        if not self.deadline:
            return False
        import time

        return (now if now is not None else time.time()) >= self.deadline

    def terminal_status(self) -> bool:
        return self.status in (
            consts.EVAL_STATUS_COMPLETE,
            consts.EVAL_STATUS_FAILED,
            consts.EVAL_STATUS_CANCELLED,
        )

    def should_enqueue(self) -> bool:
        """Whether the eval belongs in the broker's ready queues."""
        return self.status == consts.EVAL_STATUS_PENDING

    def should_block(self) -> bool:
        """Whether the eval belongs in the blocked-evals tracker."""
        return self.status == consts.EVAL_STATUS_BLOCKED

    def make_plan(self, job: Optional[Job]) -> Plan:
        plan = Plan(
            eval_id=self.id,
            priority=self.priority,
            job=job,
        )
        if job is not None:
            plan.all_at_once = job.all_at_once
        return plan

    def next_rolling_eval(self, wait: float) -> "Evaluation":
        """Follow-up eval for the next rolling-update batch."""
        return Evaluation(
            id=generate_uuid(),
            priority=self.priority,
            type=self.type,
            triggered_by=consts.EVAL_TRIGGER_ROLLING_UPDATE,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=consts.EVAL_STATUS_PENDING,
            wait=wait,
            previous_eval=self.id,
            trace_id=generate_uuid(),
        )

    def next_migration_eval(self, wait: float) -> "Evaluation":
        """Follow-up eval for displaced allocs deferred past the
        in-flight migration budget (nomad_tpu/migrate): the drain
        storm's next wave, the budget analog of next_rolling_eval."""
        return Evaluation(
            id=generate_uuid(),
            priority=self.priority,
            type=self.type,
            triggered_by=consts.EVAL_TRIGGER_MIGRATION,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=consts.EVAL_STATUS_PENDING,
            wait=wait,
            previous_eval=self.id,
            trace_id=generate_uuid(),
        )

    def create_blocked_eval(
        self,
        class_eligibility: Dict[str, bool],
        escaped: bool,
    ) -> "Evaluation":
        """Blocked eval re-enqueued when node capacity changes."""
        return Evaluation(
            id=generate_uuid(),
            priority=self.priority,
            type=self.type,
            triggered_by=self.triggered_by,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=consts.EVAL_STATUS_BLOCKED,
            previous_eval=self.id,
            class_eligibility=dict(class_eligibility),
            escaped_computed_class=escaped,
            trace_id=generate_uuid(),
        )


def new_eval(job: Job, triggered_by: str) -> Evaluation:
    return Evaluation(
        id=generate_uuid(),
        priority=job.priority,
        type=job.type,
        triggered_by=triggered_by,
        job_id=job.id,
        # The spec-change index, not modify_index: derived-status writes
        # bump the latter without changing the job spec.
        job_modify_index=job.job_modify_index,
        status=consts.EVAL_STATUS_PENDING,
        trace_id=generate_uuid(),
    )
