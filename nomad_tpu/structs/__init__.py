"""Data model for nomad_tpu (reference: nomad/structs/)."""

from . import consts
from .alloc import VaultAccessor
from .alloc import (
    AllocMetric,
    Allocation,
    TaskEvent,
    TaskState,
    filter_terminal_allocs,
    new_task_event,
    remove_allocs,
)
from .bitmap import Bitmap
from .eval import Evaluation, new_eval
from .funcs import allocs_fit, score_fit
from .job import (
    Constraint,
    DispatchPayloadConfig,
    EphemeralDisk,
    Gang,
    Job,
    JobSummary,
    LogConfig,
    PeriodicConfig,
    RestartPolicy,
    Service,
    ServiceCheck,
    Task,
    TaskArtifact,
    TaskGroup,
    TaskGroupSummary,
    Template,
    UpdateStrategy,
    Vault,
    default_batch_restart_policy,
    default_service_restart_policy,
)
from .network import NetworkIndex
from .node import (
    Node,
    escaped_constraints,
    is_unique_namespace,
    unique_namespace,
)
from .plan import DesiredUpdates, Plan, PlanAnnotations, PlanResult
from .resources import NetworkResource, Port, Resources

__all__ = [
    "consts",
    "AllocMetric",
    "Allocation",
    "TaskEvent",
    "TaskState",
    "filter_terminal_allocs",
    "new_task_event",
    "remove_allocs",
    "Bitmap",
    "Evaluation",
    "new_eval",
    "allocs_fit",
    "score_fit",
    "Constraint",
    "DispatchPayloadConfig",
    "EphemeralDisk",
    "Gang",
    "Job",
    "JobSummary",
    "LogConfig",
    "PeriodicConfig",
    "RestartPolicy",
    "Service",
    "ServiceCheck",
    "Task",
    "TaskArtifact",
    "TaskGroup",
    "TaskGroupSummary",
    "Template",
    "UpdateStrategy",
    "Vault",
    "default_batch_restart_policy",
    "default_service_restart_policy",
    "NetworkIndex",
    "Node",
    "escaped_constraints",
    "is_unique_namespace",
    "unique_namespace",
    "DesiredUpdates",
    "Plan",
    "PlanAnnotations",
    "PlanResult",
    "NetworkResource",
    "Port",
    "Resources",
]
