"""Placement fit and scoring primitives — the kernel the TPU path
vectorizes.

Reference: nomad/structs/funcs.go:60 (AllocsFit), :123 (ScoreFit,
Google BestFit-v3).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .alloc import Allocation
from .network import NetworkIndex
from .node import Node
from .resources import Resources


def allocs_fit(
    node: Node,
    allocs: List[Allocation],
    net_idx: Optional[NetworkIndex] = None,
) -> Tuple[bool, str, Resources]:
    """Whether the set of allocs (plus the node's reserved resources) fits
    on the node. Returns (fit, exhausted-dimension, utilization)."""
    used = Resources()
    if node.reserved:
        used.add(node.reserved)

    for alloc in allocs:
        if alloc.resources is not None:
            used.add(alloc.resources)
        elif alloc.task_resources:
            # Plan allocs carry the combined resources stripped; sum the
            # shared ask plus each task's resources (funcs.go:77-90).
            used.add(alloc.shared_resources)
            for task_res in alloc.task_resources.values():
                used.add(task_res)
        else:
            raise ValueError(f"allocation {alloc.id!r} has no resources set")

    ok, dimension = node.resources.superset(used)
    if not ok:
        return False, dimension, used

    if net_idx is None:
        net_idx = NetworkIndex()
        if net_idx.set_node(node) or net_idx.add_allocs(allocs):
            return False, "reserved port collision", used

    if net_idx.overcommitted():
        return False, "bandwidth exceeded", used

    return True, "", used


def score_fit(node: Node, util: Resources) -> float:
    """BestFit-v3: 20 - (10^free_cpu_frac + 10^free_mem_frac), clamped to
    [0, 18]. Packed nodes score high; empty nodes score 0.

    Note: util (from allocs_fit) includes node.reserved while the
    denominator subtracts it — reference parity (funcs.go:123-131 does
    the same), so reserved-heavy nodes score as partially packed."""
    node_cpu = float(node.resources.cpu)
    node_mem = float(node.resources.memory_mb)
    if node.reserved:
        node_cpu -= node.reserved.cpu
        node_mem -= node.reserved.memory_mb
    if node_cpu <= 0 or node_mem <= 0:
        # Fully-reserved node: nothing schedulable, worst score.
        return 0.0

    free_pct_cpu = 1.0 - (util.cpu / node_cpu)
    free_pct_mem = 1.0 - (util.memory_mb / node_mem)
    total = 10.0**free_pct_cpu + 10.0**free_pct_mem
    score = 20.0 - total
    return max(0.0, min(18.0, score))
