"""NetworkIndex: port/bandwidth accounting and network offers.

Reference: nomad/structs/network.go:35 (NetworkIndex), :72 (SetNode),
:94 (AddAllocs), :172 (AssignNetwork), :245/:288 (dynamic port pickers).
"""

from __future__ import annotations

import ipaddress
import random
from typing import Dict, List, Optional

from . import consts
from .alloc import Allocation
from .bitmap import Bitmap
from .node import Node
from .resources import NetworkResource, Port


class NetworkIndex:
    def __init__(self):
        self.avail_networks: List[NetworkResource] = []
        self.avail_bandwidth: Dict[str, int] = {}  # by device
        self.used_ports: Dict[str, Bitmap] = {}  # by IP
        self.used_bandwidth: Dict[str, int] = {}  # by device

    def overcommitted(self) -> bool:
        return any(
            used > self.avail_bandwidth.get(device, 0)
            for device, used in self.used_bandwidth.items()
        )

    def set_node(self, node: Node) -> bool:
        """Register the node's available networks and reserved usage.
        Returns True on a port collision."""
        collide = False
        if node.resources:
            for n in node.resources.networks:
                if n.device:
                    self.avail_networks.append(n)
                    self.avail_bandwidth[n.device] = n.mbits
        if node.reserved:
            for n in node.reserved.networks:
                if self.add_reserved(n):
                    collide = True
        return collide

    def add_allocs(self, allocs: List[Allocation]) -> bool:
        collide = False
        for alloc in allocs:
            for task_res in alloc.task_resources.values():
                if not task_res.networks:
                    continue
                if self.add_reserved(task_res.networks[0]):
                    collide = True
        return collide

    def add_reserved(self, n: NetworkResource) -> bool:
        collide = False
        used = self.used_ports.get(n.ip)
        if used is None:
            used = Bitmap(consts.MAX_VALID_PORT)
            self.used_ports[n.ip] = used
        for port in list(n.reserved_ports) + list(n.dynamic_ports):
            if port.value < 0 or port.value >= consts.MAX_VALID_PORT:
                # Early return leaves the index partially applied —
                # reference parity (network.go:129-130 does the same).
                return True
            if used.check(port.value):
                collide = True
            else:
                used.set(port.value)
        self.used_bandwidth[n.device] = self.used_bandwidth.get(n.device, 0) + n.mbits
        return collide

    def _yield_ips(self):
        for n in self.avail_networks:
            try:
                network = ipaddress.ip_network(n.cidr, strict=False)
            except ValueError:
                continue
            for ip in network:
                yield n, str(ip)

    def assign_network(
        self, ask: NetworkResource, rng: Optional[random.Random] = None
    ) -> tuple:
        """Build a network offer for the ask: (offer | None, error string)."""
        rng = rng or random
        err = "no networks available"
        for n, ip_str in self._yield_ips():
            avail = self.avail_bandwidth.get(n.device, 0)
            used_bw = self.used_bandwidth.get(n.device, 0)
            if used_bw + ask.mbits > avail:
                err = "bandwidth exceeded"
                continue

            used = self.used_ports.get(ip_str)
            collision = False
            for port in ask.reserved_ports:
                if port.value < 0 or port.value >= consts.MAX_VALID_PORT:
                    return None, f"invalid port {port.value} (out of range)"
                if used is not None and used.check(port.value):
                    collision = True
                    break
            if collision:
                err = "reserved port collision"
                continue

            dyn_ports, dyn_err = _pick_dynamic_ports_stochastic(used, ask, rng)
            if dyn_err:
                dyn_ports, dyn_err = _pick_dynamic_ports_precise(used, ask, rng)
                if dyn_err:
                    err = dyn_err
                    continue

            offer = NetworkResource(
                device=n.device,
                ip=ip_str,
                mbits=ask.mbits,
                reserved_ports=[Port(p.label, p.value) for p in ask.reserved_ports],
                dynamic_ports=[
                    Port(p.label, v) for p, v in zip(ask.dynamic_ports, dyn_ports)
                ],
            )
            return offer, ""
        return None, err


def _pick_dynamic_ports_stochastic(
    used: Optional[Bitmap], ask: NetworkResource, rng
) -> tuple:
    """Random probing for dynamic ports; fast path, may give up."""
    taken = [p.value for p in ask.reserved_ports]
    picked: List[int] = []
    for _ in ask.dynamic_ports:
        for attempt in range(consts.MAX_RAND_PORT_ATTEMPTS + 1):
            if attempt == consts.MAX_RAND_PORT_ATTEMPTS:
                return [], "stochastic dynamic port selection failed"
            port = rng.randrange(consts.MIN_DYNAMIC_PORT, consts.MAX_DYNAMIC_PORT)
            if used is not None and used.check(port):
                continue
            if port in taken or port in picked:
                continue
            picked.append(port)
            break
    return picked, ""


def _pick_dynamic_ports_precise(
    used: Optional[Bitmap], ask: NetworkResource, rng
) -> tuple:
    """Exhaustive scan of the dynamic range; authoritative failure."""
    used_set = used.copy() if used is not None else Bitmap(consts.MAX_VALID_PORT)
    for port in ask.reserved_ports:
        used_set.set(port.value)
    available = used_set.indexes_in_range(
        False, consts.MIN_DYNAMIC_PORT, consts.MAX_DYNAMIC_PORT
    )
    num = len(ask.dynamic_ports)
    if len(available) < num:
        return [], "dynamic port selection failed"
    rng.shuffle(available)
    return available[:num], ""
