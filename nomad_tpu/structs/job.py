"""Job specification types: Job, TaskGroup, Task and sub-blocks.

Reference: nomad/structs/structs.go:1068 (Job), :1532 (TaskGroup),
:1923 (Task), :2719 (Constraint), :1320 (UpdateStrategy),
:1343 (PeriodicConfig), :1471 (RestartPolicy), :2771 (EphemeralDisk).
"""

from __future__ import annotations

import copy
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import consts


@dataclass
class Constraint:
    ltarget: str = ""  # left-hand target, e.g. "${attr.kernel.name}"
    rtarget: str = ""  # right-hand target / literal
    operand: str = "="  # =, !=, <, <=, >, >=, version, regexp, distinct_hosts

    def copy(self) -> "Constraint":
        return Constraint(self.ltarget, self.rtarget, self.operand)

    def __str__(self) -> str:
        return f"{self.ltarget} {self.operand} {self.rtarget}"

    def validate(self) -> List[str]:
        errs = []
        if not self.operand:
            errs.append("missing constraint operand")
        if self.operand == consts.CONSTRAINT_REGEX:
            try:
                re.compile(self.rtarget)
            except re.error as e:
                errs.append(f"regular expression failed to compile: {e}")
        return errs


@dataclass
class LogConfig:
    max_files: int = 10
    max_file_size_mb: int = 10


@dataclass
class RestartPolicy:
    attempts: int = 0
    interval: float = 0.0  # seconds (reference uses time.Duration)
    delay: float = 0.0  # seconds
    mode: str = consts.RESTART_POLICY_MODE_FAIL

    def validate(self) -> List[str]:
        errs = []
        if self.mode not in (consts.RESTART_POLICY_MODE_DELAY, consts.RESTART_POLICY_MODE_FAIL):
            errs.append(f"unsupported restart mode: {self.mode!r}")
        if self.interval and self.attempts > 0 and self.interval < 5:
            errs.append("interval is too small")
        return errs


def default_service_restart_policy() -> RestartPolicy:
    return RestartPolicy(attempts=2, interval=60.0, delay=15.0, mode=consts.RESTART_POLICY_MODE_DELAY)


def default_batch_restart_policy() -> RestartPolicy:
    return RestartPolicy(attempts=15, interval=7 * 24 * 3600.0, delay=15.0, mode=consts.RESTART_POLICY_MODE_DELAY)


@dataclass
class EphemeralDisk:
    sticky: bool = False
    migrate: bool = False
    size_mb: int = 300


@dataclass
class ServiceCheck:
    name: str = ""
    type: str = ""  # http | tcp | script
    command: str = ""
    args: List[str] = field(default_factory=list)
    path: str = ""
    protocol: str = ""
    port_label: str = ""
    interval: float = 0.0
    timeout: float = 0.0
    initial_status: str = ""


@dataclass
class Service:
    name: str = ""
    port_label: str = ""
    tags: List[str] = field(default_factory=list)
    checks: List[ServiceCheck] = field(default_factory=list)


@dataclass
class Vault:
    policies: List[str] = field(default_factory=list)
    env: bool = True
    change_mode: str = "restart"
    change_signal: str = ""


@dataclass
class Template:
    source_path: str = ""
    dest_path: str = ""
    embedded_tmpl: str = ""
    change_mode: str = "restart"
    change_signal: str = ""
    splay: float = 5.0


@dataclass
class TaskArtifact:
    getter_source: str = ""
    getter_options: Dict[str, str] = field(default_factory=dict)
    relative_dest: str = ""


@dataclass
class DispatchPayloadConfig:
    file: str = ""


from .resources import Resources  # noqa: E402  (avoid circular import at top)


@dataclass
class Task:
    name: str = ""
    driver: str = ""
    user: str = ""
    config: Dict[str, Any] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    services: List[Service] = field(default_factory=list)
    vault: Optional[Vault] = None
    templates: List[Template] = field(default_factory=list)
    constraints: List[Constraint] = field(default_factory=list)
    resources: Optional[Resources] = None
    meta: Dict[str, str] = field(default_factory=dict)
    kill_timeout: float = 5.0
    log_config: Optional[LogConfig] = None
    artifacts: List[TaskArtifact] = field(default_factory=list)

    def copy(self) -> "Task":
        return copy.deepcopy(self)

    def canonicalize(self) -> None:
        if self.resources is None:
            self.resources = Resources()
        self.resources.canonicalize()
        if self.log_config is None:
            self.log_config = LogConfig()

    def validate(self) -> List[str]:
        errs = []
        if not self.name:
            errs.append("missing task name")
        elif re.search(r"[^a-zA-Z0-9\-_]", self.name):
            errs.append(f"task name {self.name!r} has invalid characters")
        if not self.driver:
            errs.append(f"task {self.name!r} missing driver")
        if self.resources is None:
            errs.append(f"task {self.name!r} missing resources")
        elif self.kill_timeout < 0:
            errs.append("kill_timeout must be positive")
        for c in self.constraints:
            if c.operand == consts.CONSTRAINT_DISTINCT_HOSTS:
                errs.append("task-level constraint must not be distinct_hosts")
            errs.extend(c.validate())
        return errs


GANG_TOPOLOGY_LEVELS = ("rack", "ici")


@dataclass
class Gang:
    """Gang-scheduling stanza (nomad_tpu/gang): a task group carrying
    one places its `count` members ATOMICALLY — all K or none, in one
    plan leg the applier verifies per node and commits in one raft
    apply. Topology policy (all levels name a node-meta key,
    models/topology.py):

    - ``slice``: hard contiguity — all K members land inside ONE
      topology group of this level (one rack / one ICI neighborhood),
      or the whole gang is unplaceable. Nodes missing the meta key can
      never prove contiguity and are excluded.
    - ``spread``: balance — members spread across groups of this
      level, at most ceil(K / eligible groups) per group.
    - ``affinity``: soft co-location — members prefer groups already
      holding gang members, without requiring a single slice.

    ``slice`` subsumes ``affinity`` and contradicts ``spread``;
    validation enforces the exclusivity."""

    slice: str = ""  # "" | "rack" | "ici"
    affinity: str = ""  # "" | "rack" | "ici"
    spread: str = ""  # "" | "rack" | "ici"

    def copy(self) -> "Gang":
        return Gang(self.slice, self.affinity, self.spread)

    def validate(self) -> List[str]:
        errs = []
        for label, level in (("slice", self.slice),
                             ("affinity", self.affinity),
                             ("spread", self.spread)):
            if level and level not in GANG_TOPOLOGY_LEVELS:
                errs.append(
                    f"gang {label} must be one of {GANG_TOPOLOGY_LEVELS},"
                    f" got {level!r}")
        if self.slice and self.spread:
            errs.append("gang slice and spread are mutually exclusive")
        if self.slice and self.affinity:
            errs.append(
                "gang affinity is redundant with slice (a slice is "
                "already maximally co-located)")
        if self.spread and self.affinity:
            errs.append(
                "gang spread and affinity are mutually exclusive "
                "(spread caps a group's members, affinity piles them "
                "in — pick one policy)")
        return errs


@dataclass
class TaskGroup:
    name: str = ""
    count: int = 1
    constraints: List[Constraint] = field(default_factory=list)
    restart_policy: Optional[RestartPolicy] = None
    tasks: List[Task] = field(default_factory=list)
    ephemeral_disk: Optional[EphemeralDisk] = None
    meta: Dict[str, str] = field(default_factory=dict)
    # All-or-nothing multi-node placement (nomad_tpu/gang). None =
    # ordinary independent placement.
    gang: Optional[Gang] = None

    def copy(self) -> "TaskGroup":
        return copy.deepcopy(self)

    def canonicalize(self, job: "Job") -> None:
        if self.count == 0:
            self.count = 1
        if self.ephemeral_disk is None:
            self.ephemeral_disk = EphemeralDisk()
        if self.restart_policy is None:
            if job.type == consts.JOB_TYPE_BATCH:
                self.restart_policy = default_batch_restart_policy()
            else:
                self.restart_policy = default_service_restart_policy()
        for t in self.tasks:
            t.canonicalize()

    def lookup_task(self, name: str) -> Optional[Task]:
        for t in self.tasks:
            if t.name == name:
                return t
        return None

    def validate(self) -> List[str]:
        errs = []
        if not self.name:
            errs.append("missing task group name")
        if self.count < 0:
            errs.append(f"group {self.name!r} count must be positive")
        if not self.tasks:
            errs.append(f"group {self.name!r} missing tasks")
        seen = set()
        for t in self.tasks:
            if t.name in seen:
                errs.append(f"group {self.name!r} has duplicate task {t.name!r}")
            seen.add(t.name)
            errs.extend(t.validate())
        for c in self.constraints:
            errs.extend(c.validate())
        if self.gang is not None:
            errs.extend(self.gang.validate())
        return errs


@dataclass
class UpdateStrategy:
    stagger: float = 0.0  # seconds between sets of updates
    max_parallel: int = 0  # number of concurrent destructive updates

    def rolling(self) -> bool:
        return self.stagger > 0 and self.max_parallel > 0


@dataclass
class PeriodicConfig:
    enabled: bool = False
    spec: str = ""  # cron expression
    spec_type: str = "cron"
    prohibit_overlap: bool = False

    def validate(self) -> List[str]:
        if not self.enabled:
            return []
        errs = []
        if self.spec_type != "cron":
            errs.append(f"unknown periodic spec type {self.spec_type!r}")
        elif not self.spec:
            errs.append("must specify a spec")
        else:
            from ..utils.cron import CronSchedule

            try:
                CronSchedule(self.spec)
            except ValueError as e:
                errs.append(f"invalid cron spec: {e}")
        return errs

    def next_launch(self, after: float) -> Optional[float]:
        """Next launch time (unix seconds) strictly after `after`."""
        if not self.enabled:
            return None
        from ..utils.cron import CronSchedule

        return CronSchedule(self.spec).next_after(after)


@dataclass
class JobSummary:
    job_id: str = ""
    summary: Dict[str, "TaskGroupSummary"] = field(default_factory=dict)
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "JobSummary":
        # Flat dataclass of counters — field-wise copy keeps the
        # per-alloc summary update out of the deepcopy machinery.
        new = JobSummary.__new__(JobSummary)
        new.__dict__.update(self.__dict__)
        new.summary = {}
        for k, v in self.summary.items():
            tgs = TaskGroupSummary.__new__(TaskGroupSummary)
            tgs.__dict__.update(v.__dict__)
            new.summary[k] = tgs
        return new


@dataclass
class TaskGroupSummary:
    queued: int = 0
    complete: int = 0
    failed: int = 0
    running: int = 0
    starting: int = 0
    lost: int = 0


@dataclass
class Job:
    region: str = "global"
    id: str = ""
    parent_id: str = ""  # set on periodic children
    name: str = ""
    type: str = consts.JOB_TYPE_SERVICE
    priority: int = consts.JOB_DEFAULT_PRIORITY
    all_at_once: bool = False
    datacenters: List[str] = field(default_factory=list)
    constraints: List[Constraint] = field(default_factory=list)
    task_groups: List[TaskGroup] = field(default_factory=list)
    update: Optional[UpdateStrategy] = None
    periodic: Optional[PeriodicConfig] = None
    meta: Dict[str, str] = field(default_factory=dict)
    vault_token: str = ""
    status: str = ""
    status_description: str = ""
    create_index: int = 0
    modify_index: int = 0
    job_modify_index: int = 0  # bumped only on spec changes (structs.go:1155)

    def copy(self) -> "Job":
        return copy.deepcopy(self)

    def canonicalize(self) -> None:
        if not self.name:
            self.name = self.id
        for tg in self.task_groups:
            tg.canonicalize(self)

    def lookup_task_group(self, name: str) -> Optional[TaskGroup]:
        for tg in self.task_groups:
            if tg.name == name:
                return tg
        return None

    def is_periodic(self) -> bool:
        return self.periodic is not None and self.periodic.enabled

    def stopped(self) -> bool:
        return self.status == consts.JOB_STATUS_DEAD

    def validate(self) -> List[str]:
        errs = []
        if not self.region:
            errs.append("missing job region")
        if not self.id:
            errs.append("missing job ID")
        elif " " in self.id:
            errs.append("job ID contains a space")
        if not self.name:
            errs.append("missing job name")
        if self.type not in (consts.JOB_TYPE_SERVICE, consts.JOB_TYPE_BATCH, consts.JOB_TYPE_SYSTEM):
            errs.append(f"invalid job type: {self.type!r}")
        if not (consts.JOB_MIN_PRIORITY <= self.priority <= consts.JOB_MAX_PRIORITY):
            errs.append(
                f"job priority must be between [{consts.JOB_MIN_PRIORITY}, {consts.JOB_MAX_PRIORITY}]"
            )
        if not self.datacenters:
            errs.append("missing job datacenters")
        if not self.task_groups:
            errs.append("missing job task groups")
        seen = set()
        for tg in self.task_groups:
            if tg.name in seen:
                errs.append(f"job has duplicate task group {tg.name!r}")
            seen.add(tg.name)
            errs.extend(tg.validate())
        for c in self.constraints:
            errs.extend(c.validate())
        if self.type == consts.JOB_TYPE_SYSTEM:
            if self.periodic and self.periodic.enabled:
                errs.append("periodic is not allowed on system jobs")
            if any(tg.gang is not None for tg in self.task_groups):
                errs.append(
                    "gang is not allowed on system jobs (system "
                    "placements are pinned per node, never gangs)")
        if self.periodic:
            errs.extend(self.periodic.validate())
        return errs
