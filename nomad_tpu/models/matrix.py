"""Dense cluster-matrix construction for the TPU placement kernel.

Bridges the object model (structs/state) to the array program
(ops/binpack.py):

- nodes -> [N, 4] capacity/utilization matrices (+ bandwidth, free
  dynamic-port counts);
- constraints -> a [N, G] feasibility mask computed per *computed node
  class* host-side (C << N constraint evaluations, the dense analog of
  the reference's FeasibilityWrapper memo, scheduler/feasible.go:457),
  with `unique.`-escaped constraints evaluated per node;
- shapes bucketed (N and K padded to fixed sizes) so XLA compiles one
  program per bucket instead of per cluster size.
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..scheduler.context import EvalContext
from ..scheduler.feasible import ConstraintChecker, DriverChecker
from ..structs import (
    Allocation,
    Job,
    Node,
    Plan,
    consts,
    escaped_constraints,
    remove_allocs,
)
from ..structs.resources import Resources

# Node-count buckets: VPU-lane-friendly multiples of 128. Denser steps
# above 8k: pure powers of two made a 10k-node cluster pad to 16384
# (+63% on every transfer and scan row).
BUCKETS = [128, 256, 512, 1024, 2048, 4096, 6144, 8192, 10240, 12288,
           16384, 20480, 24576, 32768]
ASK_BUCKETS = [8, 16, 32, 64, 128, 256, 512, 1024]
# Compact-overlay padding buckets (each distinct size is one compile):
# class count, feasibility-patch rows, job alloc positions. Overlays
# larger than the top bucket fall back to the dense [N,G] overlay.
CLASS_BUCKETS = [8, 32, 128]
PATCH_BUCKETS = [16, 64, 256]
JOBPOS_BUCKETS = [16, 64, 256, 1024]

# Job-independent cluster base, cached across evaluations: rebuilding
# the [N,4] utilization matrices is O(N x allocs) host work per eval,
# and the base only changes when the nodes or allocs tables do (the
# incremental-update-keyed-on-raft-index plan from SURVEY.md §7).
# _BASE_FAMILY tracks the newest base per (store, nodes-index, dc-set)
# so a snapshot that only advanced the allocs table DELTA-updates the
# previous base (recompute touched node rows only) instead of paying
# the O(N x allocs) full rebuild — the live pipeline bumps the allocs
# index on every plan apply, so full rebuilds would dominate at 10k+
# nodes / 50k+ allocs.
_BASE_CACHE: Dict[Tuple, "_ClusterBase"] = {}
_BASE_FAMILY: Dict[Tuple, "_ClusterBase"] = {}
# key -> Event while a build is in flight (single-flight guard).
_BASE_PENDING: Dict[Tuple, object] = {}
# Bumped (under _BASE_CACHE_LOCK) by every stale-purge: a builder that
# delta'd from a pre-purge parent sees the epoch moved at store time
# and must discard its chain instead of re-seeding the purged cache.
_BASE_EPOCH = 0
_BASE_CACHE_MAX = 8
_BASE_CACHE_LOCK = __import__("threading").Lock()
_BASE_TOKENS = __import__("itertools").count(1)


def base_epoch() -> int:
    """The stale-purge epoch (bumped by every plan-apply-rejection
    purge in resolve_cluster_base). The defrag loop snapshots it before
    a solve and discards the solved wave if it moved — a wave derived
    from a chain the applier just convicted must commit nothing
    (nomad_tpu/defrag, chaos site `defrag.solve_stale`)."""
    with _BASE_CACHE_LOCK:
        return _BASE_EPOCH


class _ClusterBase:
    __slots__ = ("n_real", "n", "capacity", "sched_capacity",
                 "util", "bw_avail", "bw_used", "ports_free", "node_ok",
                 "alloc_groups", "token", "allocs_index", "table_len",
                 "nodes_index", "delta_parent", "class_ids", "class_reps",
                 "class_index", "topology", "_positions",
                 "_positions_lock")

    def __init__(self, nodes, proposed_fn, allocs_index: int = -1,
                 table_len: int = -1, nodes_index: int = -1):
        # Identity token: evals whose matrices share one base can share
        # a single device upload (scheduler/batcher.py groups by it).
        self.token = next(_BASE_TOKENS)
        self.allocs_index = allocs_index  # -1 = not delta-updatable
        # Allocs-table size at build time: deletions (GC) are invisible
        # to the modify_index scan, so a shrinking table forces a full
        # rebuild (see delta_update).
        self.table_len = table_len
        # Nodes-table watermark: node up/down/drain transitions bump it
        # and delta as node_ok row flips (models/resident.py) — the
        # node stays in the matrix, masked, instead of rebuilding the
        # node axis. -1 = node-axis deltas off for this base.
        self.nodes_index = nodes_index
        # (parent_token, changed_rows) when this base was produced by
        # delta_update: the batcher uses it to scatter-update the
        # parent's device-cached arrays instead of re-uploading
        # (ops/binpack.py apply_base_delta).
        self.delta_parent = None
        self.n_real = len(nodes)
        self.n = bucket_size(self.n_real)
        n = self.n
        self.capacity = np.zeros((n, 4), np.float32)
        self.sched_capacity = np.zeros((n, 4), np.float32)
        self.util = np.zeros((n, 4), np.float32)
        self.bw_avail = np.zeros(n, np.float32)
        self.bw_used = np.zeros(n, np.float32)
        self.ports_free = np.zeros(n, np.float32)
        self.node_ok = np.zeros(n, bool)
        # per node: [(job_id, task_group), ...] of live allocs, for the
        # cheap per-job overlay counts
        self.alloc_groups: List[List[Tuple[str, str]]] = []
        self._init_class_index(nodes)
        # job_id -> {tg: row indices}, built lazily
        from ..profile import ProfiledLock

        self._positions = None  # guarded-by: _positions_lock
        self._positions_lock = ProfiledLock("models.matrix.positions")
        self._fill_all(nodes, proposed_fn)

    def _init_class_index(self, nodes) -> None:
        """Node -> computed-class index, so feasibility evaluates once
        per CLASS on a representative node and numpy-expands to all N
        (the dense analog of FeasibilityWrapper's memo,
        scheduler/feasible.go:457). Node-level, alloc-independent:
        delta clones share it by reference."""
        ids, self.class_reps = compute_class_index(nodes)
        self.class_ids = np.full(self.n, -1, np.int32)
        self.class_ids[: len(nodes)] = ids
        # Signature-class interning (models/classes.py): REFINES the
        # computed class with the static row state, so class-granular
        # dense programs (the defrag solve's x[K, C]) can expand back
        # to bit-identical node rows. Escaped nodes get singleton
        # classes there, so aggregation always covers the whole fleet.
        from .classes import ClassIndex

        self.class_index = ClassIndex(nodes, self.n)
        # Node-topology tensor (models/topology.py): rack/ICI id
        # columns for the gang program. Node-level and alloc-
        # independent like the class index — delta clones share it by
        # reference; register/deregister breaks the family and this
        # rebuild re-derives it.
        from .topology import TopologyIndex

        self.topology = TopologyIndex(nodes, self.n)

    def job_positions(self, job_id: str) -> Dict[str, np.ndarray]:
        """{task_group: node-row indices (with repeats)} for one job's
        live allocs. The index over alloc_groups builds lazily ONCE per
        base (O(total allocs)) and every eval in a drained batch then
        pays O(its own job's allocs) instead of an O(N x allocs) python
        scan — the per-eval overlay cost that dominated the live dense
        path at 10k nodes / 50k allocs."""
        with self._positions_lock:
            if self._positions is None:
                positions: Dict[str, Dict[str, List[int]]] = {}
                for i, groups in enumerate(self.alloc_groups):
                    for jid, tg in groups:
                        positions.setdefault(jid, {}).setdefault(
                            tg, []).append(i)
                self._positions = {
                    jid: {tg: np.asarray(rows, np.int64)
                          for tg, rows in per.items()}
                    for jid, per in positions.items()
                }
            return self._positions.get(job_id, {})

    def _fill_static(self, i, node) -> Tuple[float, float, int]:
        """Node-only (alloc-independent) fields of one row. Returns
        (reserved bw, reserved dynamic-port count) for the caller to
        combine with alloc usage."""
        r = node.resources
        self.capacity[i] = (r.cpu, r.memory_mb, r.disk_mb, r.iops)
        res = node.reserved
        res_cpu = res.cpu if res else 0
        res_mem = res.memory_mb if res else 0
        res_disk = res.disk_mb if res else 0
        res_iops = res.iops if res else 0
        self.sched_capacity[i] = (
            r.cpu - res_cpu, r.memory_mb - res_mem,
            r.disk_mb - res_disk, r.iops - res_iops,
        )
        self.util[i] = (res_cpu, res_mem, res_disk, res_iops)
        self.bw_avail[i] = r.networks[0].mbits if r.networks else 0.0
        res_bw = 0.0
        ports_used = 0
        if res:
            for net in res.networks:
                res_bw += net.mbits
                for p in list(net.reserved_ports) + list(net.dynamic_ports):
                    if consts.MIN_DYNAMIC_PORT <= p.value < consts.MAX_DYNAMIC_PORT:
                        ports_used += 1
        self.bw_used[i] = res_bw
        return res_bw, ports_used

    def _fill_row(self, i, node, allocs) -> None:
        """(Re)compute one node's row from its object + live allocs
        (the delta-update path; full builds go through _fill_all)."""
        _res_bw, ports_used = self._fill_static(i, node)
        # Accumulate in python floats: one numpy scalar op per ALLOC
        # (util[i] += tuple) was the dominant cost of row fills.
        cpu = mem = disk = iops = bw = 0.0
        groups: List[Tuple[str, str]] = []
        for alloc in allocs:
            c, m, d, io, mbits, aports = _alloc_usage(alloc)
            cpu += c
            mem += m
            disk += d
            iops += io
            bw += mbits
            ports_used += aports
            groups.append((alloc.job_id, alloc.task_group))
        if allocs:
            self.util[i] += (cpu, mem, disk, iops)
            self.bw_used[i] += bw
        self.alloc_groups[i] = groups
        self.ports_free[i] = (
            consts.MAX_DYNAMIC_PORT - consts.MIN_DYNAMIC_PORT - ports_used)
        # Readiness is ROW state, not matrix membership: the resident
        # universe keeps down/draining nodes in the matrix with node_ok
        # masked, so their transitions are deltas (models/resident.py).
        self.node_ok[i] = node.ready()

    def _fill_all(self, nodes, proposed_fn) -> None:
        """Full build, vectorized over allocs: statics per node (a
        python loop over N cheap attribute reads), then ONE bulk
        scatter-add of every alloc's memoized usage — the per-row
        python/numpy churn here dominated the per-eval matrix cost in
        system storms (BASELINE config 5/7)."""
        n_real = self.n_real
        rows: List[int] = []
        usages: List[Tuple] = []
        static_ports = np.zeros(n_real, np.float32)
        for i, node in enumerate(nodes):
            _res_bw, ports_used = self._fill_static(i, node)
            static_ports[i] = ports_used
            groups: List[Tuple[str, str]] = []
            for alloc in proposed_fn(node.id):
                rows.append(i)
                usages.append(_alloc_usage(alloc))
                groups.append((alloc.job_id, alloc.task_group))
            self.alloc_groups.append(groups)
        alloc_ports = np.zeros(n_real, np.float32)
        if rows:
            ridx = np.asarray(rows, np.intp)
            ua = np.asarray(usages, np.float32)
            np.add.at(self.util[:n_real], ridx, ua[:, :4])
            np.add.at(self.bw_used[:n_real], ridx, ua[:, 4])
            np.add.at(alloc_ports, ridx, ua[:, 5])
        self.ports_free[:n_real] = (
            consts.MAX_DYNAMIC_PORT - consts.MIN_DYNAMIC_PORT
            - static_ports - alloc_ports)
        self.node_ok[:n_real] = [node.ready() for node in nodes]

    def delta_update(self, nodes, state, new_allocs_index: int,
                     new_nodes_index: int = -1) -> Optional["_ClusterBase"]:
        """A newer base for the same node set: only rows whose allocs
        changed since our allocs_index are recomputed — and, when the
        NODES table advanced too, rows whose node object changed
        (up/down/drain flips) are refilled with node_ok re-derived, so
        a node transition is a delta record like a plan commit instead
        of a node-axis rebuild. Returns None when a full rebuild is the
        better deal (too many touched rows) or required for correctness
        (allocs were DELETED — GC removals leave no modify_index trace,
        so their usage would stay baked in; or a changed node's
        capacity/class moved, which the device-shared immutable arrays
        cannot express), or self unchanged-but-rekeyed when no relevant
        alloc moved (same token -> the device-cached upload is reused
        as-is)."""
        # Snapshot the watermark set ONCE: this base may be shared
        # across worker threads, and a concurrent rekey mid-scan would
        # make us compare a mixed-era (table_len, allocs_index) pair.
        with _BASE_CACHE_LOCK:
            base_allocs_index = self.allocs_index
            base_table_len = self.table_len
            base_nodes_index = self.nodes_index
        if base_allocs_index < 0 or base_table_len < 0:
            return None
        if new_nodes_index != base_nodes_index and base_nodes_index < 0:
            # The nodes table moved but this base can't attribute node
            # changes (no watermark): rebuild.
            return None
        node_rows: List[int] = []
        if 0 <= base_nodes_index < new_nodes_index:
            for i, node in enumerate(nodes):
                if node.modify_index <= base_nodes_index:
                    continue
                # The device keeps capacity/sched_capacity/bw_avail
                # and the class index of a delta child BY REFERENCE to
                # the parent (scheduler/batcher.py): a node whose
                # computed class moved (or that IS its class's
                # representative — the memoized verdicts were computed
                # on its old attributes) can't ride a row delta.
                ci = int(self.class_ids[i]) if i < self.n_real else -1
                if ci >= 0:
                    rep = self.class_reps[ci]
                    if rep == i or (nodes[rep].computed_class
                                    != node.computed_class):
                        return None
                elif node.computed_class:
                    return None
                # Class-split path (models/classes.py): the signature
                # covers capacity/reserved/link state beyond the
                # computed class — a node whose signature moved cannot
                # keep riding the shared interning; rebuild re-interns.
                # Readiness/drain flips are row state, outside the
                # signature, and stay deltas.
                from .classes import node_signature

                if (i < self.n_real
                        and self.class_index.signature_of(i)
                        != node_signature(node)):
                    return None
                node_rows.append(i)
        allocs = state.allocs()
        created = sum(1 for a in allocs if a.create_index > base_allocs_index)
        if len(allocs) != base_table_len + created:
            return None  # deletions happened; they are untraceable
        # Split the changes: an alloc CREATED after our watermark was
        # never in this base, so its usage can be scatter-ADDED to its
        # row directly — no re-scan of the node's other allocs. Only
        # rows with modified pre-existing allocs (in-place updates,
        # terminal transitions whose usage must come OUT) need the full
        # refill. A placement storm is pure creations — without this
        # split every committed plan degraded the next eval's delta to
        # a full O(N x allocs) rebuild (the refill cap below), making
        # the storm quadratic in total allocs (VERDICT r4 ask #8).
        refill_nids = set()
        adds = []
        for a in allocs:
            if a.modify_index <= base_allocs_index:
                continue
            if a.create_index > base_allocs_index:
                if not a.terminal_status():
                    adds.append(a)
                # created-then-terminal since the base: never counted,
                # consumes nothing now — nothing to do.
            else:
                refill_nids.add(a.node_id)
        row_of = {node.id: i for i, node in enumerate(nodes)}
        adds = [a for a in adds
                if a.node_id not in refill_nids and a.node_id in row_of]
        node_row_set = set(node_rows)
        refill_rows = sorted(
            {row_of[nid] for nid in refill_nids if nid in row_of}
            | node_row_set)
        adds = [a for a in adds if row_of[a.node_id] not in node_row_set]
        rows = sorted({row_of[a.node_id] for a in adds}
                      | set(refill_rows))
        if not rows:
            # Nothing in OUR node set changed: rekey in place. table_len
            # must advance too — allocs may have been created on nodes
            # outside this family (other DCs, non-pinned nodes), and a
            # stale length would trip the deletion check on the next
            # delta, degrading every future update to a full rebuild.
            # Compare-and-advance under the lock: a concurrent delta from
            # a NEWER snapshot must never have its watermark regressed.
            with _BASE_CACHE_LOCK:
                if new_allocs_index > self.allocs_index:
                    self.allocs_index = new_allocs_index
                    self.table_len = len(allocs)
                if 0 <= self.nodes_index < new_nodes_index:
                    self.nodes_index = new_nodes_index
            return self
        from .resident import get_tracker

        if len(refill_rows) > get_tracker().max_refill_rows(self.n_real):
            return None  # full rebuild is cheaper (refills only: the
            #              additive rows cost O(1) per new alloc)
        from ..chaos import chaos

        if chaos.enabled and chaos.fire(
                "matrix.stale_delta", rows=len(rows)) == "drop":
            # Injected staleness: one delta record is LOST — the row
            # keeps its previous values on host AND device (the scatter
            # below ships the un-recomputed row, so mirror and resident
            # tensor agree with each other and disagree with the
            # store). The plan applier's exact verification is the
            # safety net that must catch the resulting bad placement
            # and force a rebuild (models/resident.py note_rejection).
            lost = rows[0]
            refill_rows = [r for r in refill_rows if r != lost]
            adds = [a for a in adds if row_of[a.node_id] != lost]
            node_rows = [r for r in node_rows if r != lost]
        new = _ClusterBase.__new__(_ClusterBase)
        new.token = next(_BASE_TOKENS)
        new.allocs_index = new_allocs_index
        new.table_len = len(allocs)
        new.nodes_index = max(base_nodes_index, new_nodes_index)
        new.delta_parent = (self.token, tuple(rows))
        new.n_real, new.n = self.n_real, self.n
        # Node-level class index is alloc-independent: share it. The
        # topology tensor rides the same contract (a meta edit that
        # moved a group also moved the computed class, and the class
        # checks above already refused the row delta for that).
        new.class_ids, new.class_reps = self.class_ids, self.class_reps
        new.class_index = self.class_index
        new.topology = self.topology
        # Same profiled declaration site as __init__: delta clones ARE
        # the live pipeline's dominant base-build path, and an
        # unprofiled lock here would make the observatory's
        # 'models.matrix.positions' row cover only the rare full
        # rebuilds. Dead clones' stats retire on GC (profile
        # _register_lock), so snapshot churn never exhausts the
        # registry.
        from ..profile import ProfiledLock

        new._positions_lock = ProfiledLock("models.matrix.positions")
        new._positions = None  # patched below when the parent built one
        new.capacity = self.capacity.copy()
        new.sched_capacity = self.sched_capacity.copy()
        new.util = self.util.copy()
        new.bw_avail = self.bw_avail.copy()
        new.bw_used = self.bw_used.copy()
        new.ports_free = self.ports_free.copy()
        new.node_ok = self.node_ok.copy()
        new.alloc_groups = list(self.alloc_groups)
        old_groups = {i: self.alloc_groups[i] for i in rows}
        for i in refill_rows:
            new._fill_row(
                i, nodes[i],
                state.allocs_by_node_terminal(nodes[i].id, False))
        if node_rows:
            # The device delta scatters only the MUTABLE arrays
            # (util/bw_used/ports_free/node_ok); a node change that
            # moved capacity, reserved headroom, or link bandwidth
            # cannot be expressed as a row delta against the parent's
            # shared immutable arrays — rebuild instead. Readiness and
            # drain flips (the common transitions) leave these
            # untouched.
            nr = np.asarray(node_rows, np.intp)
            if (not np.array_equal(new.capacity[nr], self.capacity[nr])
                    or not np.array_equal(new.sched_capacity[nr],
                                          self.sched_capacity[nr])
                    or not np.array_equal(new.bw_avail[nr],
                                          self.bw_avail[nr])):
                return None
        get_tracker().count_delta(len(rows) - len(node_rows),
                                  len(node_rows))
        if adds:
            # Additive rows: one bulk scatter-add of the new allocs'
            # memoized usage — O(new allocs), not O(rows x allocs).
            ridx = np.asarray([row_of[a.node_id] for a in adds], np.intp)
            ua = np.asarray([_alloc_usage(a) for a in adds], np.float32)
            np.add.at(new.util, ridx, ua[:, :4])
            np.add.at(new.bw_used, ridx, ua[:, 4])
            np.subtract.at(new.ports_free, ridx, ua[:, 5])
            for a in adds:
                i = row_of[a.node_id]
                # Copy-on-write: the parent's row list stays untouched.
                if new.alloc_groups[i] is self.alloc_groups[i]:
                    new.alloc_groups[i] = list(self.alloc_groups[i])
                new.alloc_groups[i].append((a.job_id, a.task_group))
        new._patch_positions(self, rows, old_groups)
        return new

    def _patch_positions(self, parent: "_ClusterBase", rows,
                         old_groups) -> None:
        """Carry the parent's job-positions index forward, re-deriving
        only the jobs present in the changed rows — rebuilding the full
        index is an O(total allocs) python scan per delta base, dozens
        of times per live storm."""
        with parent._positions_lock:
            base_positions = parent._positions
        if base_positions is None:
            return  # parent never built one; stay lazy
        affected = set()
        for i in rows:
            for jid, _tg in old_groups[i]:
                affected.add(jid)
            for jid, _tg in self.alloc_groups[i]:
                affected.add(jid)
        patched = dict(base_positions)
        rowset = np.asarray(sorted(rows), np.int64)
        for jid in affected:
            per = {tg: arr for tg, arr in
                   (base_positions.get(jid) or {}).items()}
            # Strip the changed rows' old memberships...
            for tg in list(per):
                keep = per[tg][~np.isin(per[tg], rowset)]
                if keep.size:
                    per[tg] = keep
                else:
                    del per[tg]
            # ... and add their current ones.
            adds: Dict[str, List[int]] = {}
            for i in rows:
                for jid2, tg in self.alloc_groups[i]:
                    if jid2 == jid:
                        adds.setdefault(tg, []).append(i)
            for tg, idxs in adds.items():
                prev = per.get(tg)
                arr = np.asarray(idxs, np.int64)
                per[tg] = (np.concatenate([prev, arr])
                           if prev is not None else arr)
            if per:
                patched[jid] = per
            else:
                patched.pop(jid, None)
        # Publish under the lock: `self` is freshly built and unshared
        # in the current delta path, but the guarded-by contract on
        # _positions is unconditional — a future caller patching a
        # LIVE base would otherwise race job_positions' lazy build.
        with self._positions_lock:
            self._positions = patched


def compute_class_index(nodes) -> Tuple[np.ndarray, List[int]]:
    """Node -> computed-class index: ids[i] is the class number of
    nodes[i] (-1 = classless), class_reps[c] a representative row."""
    ids = np.full(len(nodes), -1, np.int32)
    reps: List[int] = []
    index: Dict[str, int] = {}
    for i, node in enumerate(nodes):
        cls = node.computed_class
        if not cls:
            continue
        ci = index.get(cls)
        if ci is None:
            ci = len(reps)
            index[cls] = ci
            reps.append(i)
        ids[i] = ci
    return ids, reps


# Ready-node class index cached per snapshot node set: every system
# eval of a storm sees the same ready nodes, and the O(N) class walk
# per eval would otherwise dominate the vectorized diff.
_CLASS_INDEX_CACHE: Dict[Tuple, Tuple[np.ndarray, List[int]]] = {}
_CLASS_INDEX_MAX = 4

# Ready-node LIST cached per (snapshot nodes-index, dc set): the
# central dispatch pipeline fans a full 64-eval batch out against one
# snapshot, and each eval's ClusterMatrix would otherwise re-walk all
# N node objects (ready_nodes_in_dcs is an O(N) python scan — 64 x 10k
# attribute reads per batch, all under the GIL while the batcher's
# accumulation window is ticking). Readiness depends only on the nodes
# table, so the nodes index keys it exactly. Callers treat the cached
# (nodes, by_dc) pair as immutable.
_READY_NODES_CACHE: Dict[Tuple, Tuple[List[Node], Dict[str, int]]] = {}
_READY_NODES_MAX = 4


def ready_nodes_cached(state, datacenters):
    """ready_nodes_in_dcs with a per-snapshot memo (see note above).
    Falls through to the plain scan for stateless snapshots (tests,
    shadow stores)."""
    key = None
    if hasattr(state, "index") and getattr(state, "store_id", ""):
        key = (state.store_id, state.index("nodes"),
               tuple(sorted(datacenters or [])))
        with _BASE_CACHE_LOCK:
            hit = _READY_NODES_CACHE.get(key)
        if hit is not None:
            return hit
    from ..scheduler.util import ready_nodes_in_dcs

    out = ready_nodes_in_dcs(state, datacenters)
    if key is not None:
        with _BASE_CACHE_LOCK:
            while len(_READY_NODES_CACHE) >= _READY_NODES_MAX:
                _READY_NODES_CACHE.pop(next(iter(_READY_NODES_CACHE)))
            _READY_NODES_CACHE[key] = out
    return out


# Feasibility memo per (base token, job constraint signature): the
# [N, G] mask depends only on the node set (pinned by the base token)
# and the job's constraint/driver STRUCTURE — not its id. A placement
# storm is N structurally identical jobs with distinct ids (one
# service scaled out, the bench's e2e-0..e2e-119 shape), so every eval
# of a drained batch was recomputing an identical mask under the GIL
# while the batcher's cohort window ticked — the mask memo is to
# node_feasibility what the base cache is to the [N, 4] build.
_FEAS_CACHE: Dict[Tuple, Tuple[np.ndarray, Optional[np.ndarray]]] = {}
_FEAS_MAX = 16
# Compact overlay + zero job-count memo for jobs with NO live allocs
# (every job of a placement storm, until its own plan commits): the
# overlay is then a pure function of (base, constraint signature) and
# its padded arrays are identical across the batch — per-eval numpy
# materialization was the residual cohort-window stagger after the
# mask memo. All cached arrays are read-only by contract (the batcher
# stacks them; the kernel carries functional copies).
_OVERLAY_CACHE: Dict[Tuple, Tuple] = {}
_OVERLAY_MAX = 16


def _constraint_sig(cons) -> Tuple:
    return tuple((c.ltarget, c.operand, c.rtarget) for c in cons)


def feasibility_signature(job) -> Tuple:
    """Hashable signature of everything node_feasibility reads from the
    job: job/TG/task constraints (order-sensitive, like the checkers)
    and the TG driver sets. Two jobs with equal signatures get
    identical masks on the same base."""
    tg_sigs = []
    for tg in job.task_groups:
        tg_sigs.append((
            _constraint_sig(tg.constraints),
            tuple(_constraint_sig(t.constraints) for t in tg.tasks),
            tuple(sorted({t.driver for t in tg.tasks})),
        ))
    return (_constraint_sig(job.constraints), tuple(tg_sigs))


# Full node UNIVERSE per (snapshot nodes-index, dc set): every node of
# the dc set regardless of readiness, plus the ready-only per-dc counts
# (metric parity with the host path) and an identity signature over the
# ordered node-id tuple. The resident dense path builds its matrix over
# THIS list — readiness is a node_ok row bit, so up/down/drain flips
# are delta records against the device-resident base instead of a
# rebuild of the node axis (models/resident.py). The signature keys the
# base FAMILY: it changes exactly when the node set (or its row order)
# changes, which is when a delta chain must break.
_UNIVERSE_CACHE: Dict[Tuple, Tuple[List[Node], Dict[str, int], int]] = {}
_UNIVERSE_MAX = 4


def universe_nodes_cached(state, datacenters):
    """(nodes, ready_by_dc, ids_sig) over the full dc node universe;
    memoized per snapshot nodes-index like ready_nodes_cached."""
    key = None
    if hasattr(state, "index") and getattr(state, "store_id", ""):
        key = (state.store_id, state.index("nodes"),
               tuple(sorted(datacenters or [])))
        with _BASE_CACHE_LOCK:
            hit = _UNIVERSE_CACHE.get(key)
        if hit is not None:
            return hit
    dc_map = {dc: 0 for dc in (datacenters or [])}
    nodes: List[Node] = []
    for node in state.nodes():
        if node.datacenter not in dc_map:
            continue
        nodes.append(node)
        if node.ready():
            dc_map[node.datacenter] += 1
    out = (nodes, dc_map, hash(tuple(n.id for n in nodes)))
    if key is not None:
        with _BASE_CACHE_LOCK:
            while len(_UNIVERSE_CACHE) >= _UNIVERSE_MAX:
                _UNIVERSE_CACHE.pop(next(iter(_UNIVERSE_CACHE)))
            _UNIVERSE_CACHE[key] = out
    return out


def ready_class_index(state, nodes, dcs) -> Tuple[np.ndarray, List[int]]:
    key = None
    if hasattr(state, "index") and getattr(state, "store_id", ""):
        key = (state.store_id, state.index("nodes"),
               tuple(sorted(dcs or [])), len(nodes))
        with _BASE_CACHE_LOCK:
            cached = _CLASS_INDEX_CACHE.get(key)
        if cached is not None:
            return cached
    out = compute_class_index(nodes)
    if key is not None:
        with _BASE_CACHE_LOCK:
            while len(_CLASS_INDEX_CACHE) >= _CLASS_INDEX_MAX:
                _CLASS_INDEX_CACHE.pop(next(iter(_CLASS_INDEX_CACHE)))
            _CLASS_INDEX_CACHE[key] = out
    return out


def node_feasibility(state, job, groups, nodes, class_ids, class_reps,
                     return_verdicts: bool = False):
    """[len(nodes), G] constraint mask. Non-escaped job/TG constraints
    are evaluated ONCE PER COMPUTED CLASS on a representative node and
    numpy-expanded; escaped constraints and classless nodes fall back
    to per-node checks (node_class.go:70).

    With return_verdicts, returns (feasible, verdicts [C, G] or None):
    the per-class verdicts are the compact form the device-side overlay
    expansion consumes (ops/binpack.py CompactOverlay)."""
    n_real = len(nodes)
    g = len(groups)
    feasible = np.zeros((n_real, g), bool)
    ctx = EvalContext(state, Plan())

    job_cons = job.constraints
    job_escaped = escaped_constraints(job_cons)
    job_static = [c for c in job_cons if c not in job_escaped]

    per_group = []
    any_esc = bool(job_escaped)
    for tg in groups:
        cons = list(tg.constraints)
        drivers = set()
        for task in tg.tasks:
            cons.extend(task.constraints)
            drivers.add(task.driver)
        esc = escaped_constraints(cons)
        static = [c for c in cons if c not in esc]
        any_esc = any_esc or bool(esc)
        per_group.append((static, esc, drivers))

    job_checker = ConstraintChecker(ctx, job_static)
    cons_checker = ConstraintChecker(ctx)
    driver_checker = DriverChecker(ctx)
    esc_checker = ConstraintChecker(ctx)

    def static_row(node) -> np.ndarray:
        row = np.zeros(g, bool)
        if not job_checker.feasible(node):
            return row
        for gi, (static, _esc, drivers) in enumerate(per_group):
            driver_checker.set_drivers(drivers)
            cons_checker.set_constraints(static)
            row[gi] = (driver_checker.feasible(node)
                       and cons_checker.feasible(node))
        return row

    # One evaluation per class, expanded by numpy take.
    verdicts = None
    if class_reps:
        verdicts = np.stack([static_row(nodes[rep]) for rep in class_reps])
        ids = class_ids[:n_real]
        classed = ids >= 0
        feasible[classed] = verdicts[ids[classed]]
    # Classless nodes: individual evaluation (flatnonzero — a python
    # scan over 10k rows that are all classed would cost more than
    # the class pass saved).
    for i in np.flatnonzero(class_ids[:n_real] < 0):
        feasible[i] = static_row(nodes[i])
    # Escaped constraints reference unique per-node attrs: they can
    # never ride the class verdict (node_class.go:70) — walk only
    # the still-candidate rows.
    if any_esc:
        for i in np.flatnonzero(feasible.any(axis=1)):
            node = nodes[i]
            if job_escaped:
                esc_checker.set_constraints(job_escaped)
                if not esc_checker.feasible(node):
                    feasible[i] = False
                    continue
            for gi, (_static, esc, _drivers) in enumerate(per_group):
                if esc and feasible[i, gi]:
                    esc_checker.set_constraints(esc)
                    feasible[i, gi] = esc_checker.feasible(node)
    if return_verdicts:
        return feasible, verdicts
    return feasible


def bucket_size(n: int, buckets: List[int] = BUCKETS) -> int:
    i = bisect.bisect_left(buckets, max(n, 1))
    if i == len(buckets):
        # Beyond the largest bucket: round up to a multiple of the top.
        top = buckets[-1]
        return ((n + top - 1) // top) * top
    return buckets[i]


def _alloc_usage(alloc: Allocation) -> Tuple[float, float, float, float, float, int]:
    """(cpu, mem, disk, iops, mbits, dyn_ports_in_range) consumed by one
    alloc — same accounting as AllocsFit (structs/funcs.go:72-94).

    Memoized on the alloc object: an alloc's usage never changes after
    creation (store writes replace the object), and every base rebuild
    across a storm re-reads the same allocs — the attribute-walk here
    was the top cost of the per-eval matrix build. Allocation.copy()
    drops the memo (a copy's resources may be rewritten, e.g. in-place
    updates)."""
    cached = alloc.__dict__.get("_dense_usage")
    if cached is not None:
        return cached
    cpu = mem = disk = iops = 0.0
    mbits = 0.0
    ports = 0
    resources: List[Resources] = []
    if alloc.resources is not None:
        resources.append(alloc.resources)
    else:
        if alloc.shared_resources is not None:
            resources.append(alloc.shared_resources)
        resources.extend(alloc.task_resources.values())
    for r in resources:
        cpu += r.cpu
        mem += r.memory_mb
        disk += r.disk_mb
        iops += r.iops
    # Network usage mirrors NetworkIndex.AddAllocs: first network of each
    # task's resources (structs/network.go:94-107).
    for tr in alloc.task_resources.values():
        if tr.networks:
            n0 = tr.networks[0]
            mbits += n0.mbits
            for p in list(n0.reserved_ports) + list(n0.dynamic_ports):
                if consts.MIN_DYNAMIC_PORT <= p.value < consts.MAX_DYNAMIC_PORT:
                    ports += 1
    usage = (cpu, mem, disk, iops, mbits, ports)
    alloc._dense_usage = usage
    return usage


def resolve_cluster_base(state, datacenters, nodes=None, explicit=False,
                         proposed_fn=None, cacheable=True):
    """Resolve the job-independent cluster base for one (snapshot, dc
    set): exact-key cache hit, family delta-update, or full rebuild —
    single-flighted, since a drained batch's evals all build matrices
    CONCURRENTLY against one fresh snapshot (without the pending gate
    every thread misses at once and builds its own base with its own
    token, fragmenting the batcher's token-keyed queues AND paying one
    ~full base upload per thread; observed: 24 uploads of one identical
    10k-node base through the device tunnel).

    Module-level (job-free) on purpose: the dispatch pipeline prefetches
    batch k+1's base under batch k's in-flight compute with no job in
    hand (dispatch/pipeline.py), and ClusterMatrix delegates here for
    its own build. With `nodes=None` the node list derives from the
    resident universe (or the ready set when resident state is off).

    Returns (base, kind) with kind in "hit" | "rekey" | "delta" |
    "full". Family keying is the residency core: with device-resident
    state enabled the family keys on the node-SET identity instead of
    the nodes-table index, so node up/down/drain transitions (which
    bump the index but keep the set) delta against the previous base
    instead of starting a new family — the delta chain only breaks when
    nodes register/deregister (the universe signature moves)."""
    from .resident import get_tracker

    tracker = get_tracker()
    resident = tracker.is_enabled() and not explicit
    if nodes is None:
        if resident:
            nodes, _by_dc, _sig = universe_nodes_cached(state, datacenters)
        else:
            nodes, _by_dc = ready_nodes_cached(state, datacenters)
    if proposed_fn is None:
        from ..scheduler.util import proposed_allocs_for_node

        def proposed_fn(node_id, _state=state):
            return proposed_allocs_for_node(_state, None, node_id)

    key = family = prev = done = None
    allocs_idx = nodes_idx = -1
    if (cacheable and hasattr(state, "index")
            and getattr(state, "store_id", "")):
        dcs = tuple(sorted(datacenters or []))
        # Caller-provided node lists (the system path's pinned
        # subsets) need their identity in the key: two different
        # subsets of equal size on one snapshot must not collide.
        # The derived full-ready-set is determined by (nodes index,
        # dcs), so a constant marker suffices there.
        nodes_sig = (hash(tuple(n.id for n in nodes)) if explicit else 0)
        nodes_idx = state.index("nodes")
        allocs_idx = state.index("allocs")
        key = (state.store_id, nodes_idx, allocs_idx, dcs,
               len(nodes), nodes_sig)
        if resident:
            _unodes, _by_dc, usig = universe_nodes_cached(
                state, datacenters)
            family = (state.store_id, "resident", dcs, usig)
        else:
            family = (state.store_id, nodes_idx, dcs,
                      len(nodes), nodes_sig)
        if tracker.consume_stale():
            # A plan-apply rejection marked the resident chain suspect:
            # whatever matrix the scheduler planned against disagreed
            # with the store. The rejection doesn't say WHOSE state was
            # wrong, so purge every cached base (the exact-key entries
            # included — a rejected plan commits nothing, so the next
            # build may land on the SAME snapshot index and would
            # otherwise be served the poisoned entry) and pay one full
            # rebuild to re-anchor (models/resident.py counts it in
            # stale_rebuilds).
            with _BASE_CACHE_LOCK:
                global _BASE_EPOCH
                _BASE_EPOCH += 1
                _BASE_CACHE.clear()
                _BASE_FAMILY.clear()
        while True:
            with _BASE_CACHE_LOCK:
                cached = _BASE_CACHE.get(key)
                if cached is not None:
                    return cached, "hit"
                pending = _BASE_PENDING.get(key)
                if pending is None:
                    done = __import__("threading").Event()
                    _BASE_PENDING[key] = done
                    prev = _BASE_FAMILY.get(family)
                    epoch = _BASE_EPOCH
                    break
            pending.wait(60.0)
    base = None
    kind = "full"
    try:
        while True:
            if prev is not None and 0 <= prev.allocs_index <= allocs_idx:
                base = prev.delta_update(
                    nodes, state, allocs_idx,
                    new_nodes_index=nodes_idx if resident else -1)
                if base is prev:
                    kind = "rekey"
                elif base is not None:
                    kind = "delta"
            if base is None:
                table_len = (state.alloc_count()
                             if key is not None
                             and hasattr(state, "alloc_count") else -1)
                base = _ClusterBase(
                    nodes, proposed_fn,
                    allocs_index=allocs_idx if key is not None else -1,
                    table_len=table_len,
                    nodes_index=nodes_idx if (key is not None and resident)
                    else -1)
                kind = "full"
                if key is not None:
                    tracker.count_full()
                    if resident and prev is None:
                        # No family base to delta from: first build, or
                        # the node SET itself changed (register/
                        # deregister) — the one transition that must
                        # re-anchor.
                        tracker.count_universe()
            if key is None:
                return base, kind
            with _BASE_CACHE_LOCK:
                # A full build derives from the snapshot alone, so it
                # is clean regardless of purges; a delta/rekey result
                # extends a pre-registration parent and is suspect if
                # a stale-purge landed since — checking the epoch
                # atomically with the store means an in-flight delta
                # can never re-seed a purged cache.
                if kind == "full" or epoch == _BASE_EPOCH:
                    while len(_BASE_CACHE) >= _BASE_CACHE_MAX:
                        _BASE_CACHE.pop(next(iter(_BASE_CACHE)))
                    _BASE_CACHE[key] = base
                    _BASE_FAMILY[family] = base
                    while len(_BASE_FAMILY) > _BASE_CACHE_MAX:
                        _BASE_FAMILY.pop(next(iter(_BASE_FAMILY)))
                    return base, kind
                epoch = _BASE_EPOCH
            prev = None
            base = None
    finally:
        if key is not None:
            with _BASE_CACHE_LOCK:
                _BASE_PENDING.pop(key, None)
            done.set()


class _BaseView:
    """A _ClusterBase under the attribute names the batcher's
    device-residency entry points expect (ClusterMatrix's surface) —
    what prefetch_cluster_base hands to PlacementBatcher.prefetch_base."""

    __slots__ = ("base_token", "base_delta", "capacity", "sched_capacity",
                 "util", "bw_avail", "bw_used", "ports_free", "node_ok",
                 "class_ids")

    def __init__(self, base: "_ClusterBase"):
        self.base_token = base.token
        self.base_delta = base.delta_parent
        self.capacity = base.capacity
        self.sched_capacity = base.sched_capacity
        self.util = base.util
        self.bw_avail = base.bw_avail
        self.bw_used = base.bw_used
        self.ports_free = base.ports_free
        self.node_ok = base.node_ok
        self.class_ids = base.class_ids


def prefetch_cluster_base(state, datacenters):
    """Resolve the cacheable cluster base for (snapshot, dc set) and
    return (view-or-None, kind) — the dispatch pipeline's double-buffer
    prefetch entry. The base is job-independent, so no job is needed;
    un-cacheable snapshots (no store identity) return None."""
    base, kind = resolve_cluster_base(state, datacenters)
    if base.allocs_index < 0:
        return None, kind
    return _BaseView(base), kind


class ClusterMatrix:
    """Dense view of the schedulable cluster for one job's placements."""

    def __init__(self, state, job: Job, plan: Optional[Plan] = None,
                 nodes: Optional[List[Node]] = None):
        self.state = state
        self.job = job
        self.plan = plan
        self._explicit_nodes = nodes is not None
        if nodes is None:
            from .resident import get_tracker

            if get_tracker().is_enabled():
                # Resident universe: ALL dc nodes, readiness as the
                # node_ok row bit — up/down/drain flips become deltas
                # against the device-resident base instead of changing
                # the matrix shape (models/resident.py).
                nodes, by_dc, _sig = universe_nodes_cached(
                    state, job.datacenters)
            else:
                nodes, by_dc = ready_nodes_cached(state, job.datacenters)
            self.nodes_by_dc = by_dc
        else:
            self.nodes_by_dc = {}
        self.nodes: List[Node] = nodes
        self.n_real = len(nodes)
        self.n = bucket_size(self.n_real)
        self.groups = job.task_groups
        self.g = len(self.groups)
        self._build()

    # ------------------------------------------------------------------

    def _proposed_allocs(self, node_id: str) -> List[Allocation]:
        from ..scheduler.util import proposed_allocs_for_node

        return proposed_allocs_for_node(self.state, self.plan, node_id)

    def _cached_base(self) -> "_ClusterBase":
        cacheable = self.plan is None or self.plan.is_no_op()
        base, self.build_kind = resolve_cluster_base(
            self.state, self.job.datacenters, nodes=self.nodes,
            explicit=self._explicit_nodes,
            proposed_fn=self._proposed_allocs, cacheable=cacheable)
        self.delta_rows = (len(base.delta_parent[1])
                           if self.build_kind == "delta"
                           and base.delta_parent else 0)
        return base

    def _build(self) -> None:
        n, g = self.n, self.g
        base = self._cached_base()
        if self.plan is not None and hasattr(self.state, "index"):
            # Any nodes/allocs change the matrix could have seen has
            # modify_index <= this watermark; anything later is an
            # optimistic race the applier must not blame on the
            # resident chain. max() keeps the strictest watermark when
            # several builds feed one plan — over-purging is safe,
            # under-purging is not.
            wm = max(self.state.index("allocs"), self.state.index("nodes"))
            if wm > self.plan.matrix_index:
                self.plan.matrix_index = wm
        # Share the immutable base arrays; the kernel never mutates its
        # inputs (functional scan carries copies).
        self.base_token = base.token
        self.base_delta = base.delta_parent
        self.capacity = base.capacity
        self.sched_capacity = base.sched_capacity
        self.util = base.util
        self.bw_avail = base.bw_avail
        self.bw_used = base.bw_used
        self.ports_free = base.ports_free
        self.node_ok = base.node_ok
        # Padded [N] class index: rides the device base upload so the
        # compact overlay's verdict expansion happens on device.
        self.class_ids = base.class_ids
        # Signature-class interning (models/classes.py): the defrag
        # solver's class-compressed solve reads this off the resolved
        # matrix.
        self.class_index = base.class_index
        # Node-topology tensor (models/topology.py) for the gang
        # program's slice/spread/affinity group ops.
        self.topology = base.topology

        # Job-specific overlay: this job's per-node alloc counts, from
        # the base's lazy positions index (O(this job's allocs)).
        positions = base.job_positions(self.job.id)
        if not positions and base.allocs_index >= 0:
            # No live allocs (the storm shape): the whole overlay —
            # zero counts, feasibility, compact form — is a function
            # of (base, constraint signature); share one memo across
            # the batch instead of re-materializing ~N-sized arrays
            # per eval under the GIL.
            okey = (base.token, feasibility_signature(self.job))
            with _BASE_CACHE_LOCK:
                hit = _OVERLAY_CACHE.get(okey)
            if hit is not None:
                (self.job_count, self.tg_count, self.feasible,
                 self.compact_overlay) = hit
                return
            self.job_count = np.zeros(n, np.int32)
            self.tg_count = np.zeros((n, g), np.int32)
            self.feasible, verdicts = self._build_feasibility(base)
            self._build_compact_overlay(base, verdicts)
            with _BASE_CACHE_LOCK:
                while len(_OVERLAY_CACHE) >= _OVERLAY_MAX:
                    _OVERLAY_CACHE.pop(next(iter(_OVERLAY_CACHE)))
                _OVERLAY_CACHE[okey] = (
                    self.job_count, self.tg_count, self.feasible,
                    self.compact_overlay)
            return
        job_count = np.zeros(n, np.int32)
        tg_count = np.zeros((n, g), np.int32)
        gi_by_name = {tg.name: gi for gi, tg in enumerate(self.groups)}
        for task_group, rows in positions.items():
            np.add.at(job_count, rows, 1)
            gi = gi_by_name.get(task_group)
            if gi is not None:
                np.add.at(tg_count[:, gi], rows, 1)
        self.job_count = job_count
        self.tg_count = tg_count
        self.feasible, verdicts = self._build_feasibility(base)
        self._build_compact_overlay(base, verdicts)

    def _build_compact_overlay(self, base, verdicts) -> None:
        """The pre-expansion overlay (ops/binpack.py CompactOverlay):
        per-class verdicts + a sparse patch for rows the class verdict
        can't represent, and this job's alloc row positions — a few KB
        per eval instead of the ~100KB x G dense overlay at 10k nodes.
        None (dense fallback) when the base isn't device-cacheable or
        any component overflows its top padding bucket."""
        self.compact_overlay = None
        if self.base_token is None or verdicts is None:
            return
        n_real, g = self.n_real, self.g
        ids = base.class_ids[:n_real]
        if len(base.class_reps) > CLASS_BUCKETS[-1]:
            return
        # Patch rows: wherever the real mask differs from the class
        # expansion (classless nodes, escaped constraints).
        expected = np.zeros((n_real, g), bool)
        classed = ids >= 0
        expected[classed] = verdicts[ids[classed]]
        feas_real = self.feasible[:n_real]
        patch_rows = np.flatnonzero((feas_real != expected).any(axis=1))
        if len(patch_rows) > PATCH_BUCKETS[-1]:
            return
        # This job's alloc positions, flattened with their TG indices.
        gi_by_name = {tg.name: gi for gi, tg in enumerate(self.groups)}
        rows_parts: List[np.ndarray] = []
        tg_parts: List[np.ndarray] = []
        n_pos = 0
        for task_group, rows in base.job_positions(self.job.id).items():
            gi = gi_by_name.get(task_group)
            if gi is None:
                continue
            rows_parts.append(rows)
            tg_parts.append(np.full(len(rows), gi, np.int64))
            n_pos += len(rows)
        if n_pos > JOBPOS_BUCKETS[-1]:
            return
        c_pad = bucket_size(max(len(base.class_reps), 1), CLASS_BUCKETS)
        p_pad = bucket_size(len(patch_rows), PATCH_BUCKETS) \
            if len(patch_rows) else PATCH_BUCKETS[0]
        j_pad = bucket_size(n_pos, JOBPOS_BUCKETS) \
            if n_pos else JOBPOS_BUCKETS[0]
        verd = np.zeros((c_pad, g), bool)
        verd[: len(verdicts)] = verdicts
        # Pad with self.n: out of range, dropped by the device scatter.
        p_rows = np.full(p_pad, self.n, np.int32)
        p_rows[: len(patch_rows)] = patch_rows
        p_vals = np.zeros((p_pad, g), bool)
        p_vals[: len(patch_rows)] = feas_real[patch_rows]
        j_rows = np.full(j_pad, self.n, np.int32)
        j_tgs = np.zeros(j_pad, np.int32)
        if n_pos:
            j_rows[:n_pos] = np.concatenate(rows_parts)
            j_tgs[:n_pos] = np.concatenate(tg_parts)
        from ..ops.binpack import CompactOverlay

        self.compact_overlay = CompactOverlay(
            verdicts=verd, patch_rows=p_rows, patch_vals=p_vals,
            job_rows=j_rows, job_tgs=j_tgs)

    def _build_feasibility(self, base):
        """([N, G] padded mask, per-class verdicts or None); see
        node_feasibility. Memoized per (base token, job constraint
        signature): a storm's structurally identical jobs share one
        mask computation per base instead of one per eval (the memo'd
        arrays are treated as immutable by every consumer)."""
        key = None
        if base.allocs_index >= 0:  # cacheable bases only
            key = (base.token, feasibility_signature(self.job))
            with _BASE_CACHE_LOCK:
                hit = _FEAS_CACHE.get(key)
            if hit is not None:
                return hit
        feasible = np.zeros((self.n, self.g), bool)
        real, verdicts = node_feasibility(
            self.state, self.job, self.groups, self.nodes,
            base.class_ids[: self.n_real], base.class_reps,
            return_verdicts=True)
        feasible[: self.n_real] = real
        if key is not None:
            with _BASE_CACHE_LOCK:
                while len(_FEAS_CACHE) >= _FEAS_MAX:
                    _FEAS_CACHE.pop(next(iter(_FEAS_CACHE)))
                _FEAS_CACHE[key] = (feasible, verdicts)
        return feasible, verdicts

    # ------------------------------------------------------------------

    def build_asks(self, placements) -> Tuple[np.ndarray, ...]:
        """Convert an ordered list of (tg_index) placements into padded
        ask arrays. placements: list of task-group indices."""
        k_real = len(placements)
        k = bucket_size(k_real, ASK_BUCKETS)
        resources = np.zeros((k, 4), np.float32)
        bw = np.zeros(k, np.float32)
        ports = np.zeros(k, np.float32)
        tg_index = np.zeros(k, np.int32)
        active = np.zeros(k, bool)

        group_sizes = []
        for tg in self.groups:
            cpu = mem = iops = 0.0
            disk = tg.ephemeral_disk.size_mb if tg.ephemeral_disk else 0
            mbits = 0.0
            nports = 0
            for task in tg.tasks:
                r = task.resources
                cpu += r.cpu
                mem += r.memory_mb
                disk += r.disk_mb
                iops += r.iops
                if r.networks:
                    mbits += r.networks[0].mbits
                    nports += len(r.networks[0].dynamic_ports) + len(
                        r.networks[0].reserved_ports
                    )
            group_sizes.append((cpu, mem, disk, iops, mbits, nports))

        for j, gi in enumerate(placements):
            cpu, mem, disk, iops, mbits, nports = group_sizes[gi]
            resources[j] = (cpu, mem, disk, iops)
            bw[j] = mbits
            ports[j] = nports
            tg_index[j] = gi
            active[j] = True

        job_dh = any(
            c.operand == consts.CONSTRAINT_DISTINCT_HOSTS for c in self.job.constraints
        )
        tg_dh = np.array(
            [
                any(c.operand == consts.CONSTRAINT_DISTINCT_HOSTS for c in tg.constraints)
                for tg in self.groups
            ],
            bool,
        )
        return resources, bw, ports, tg_index, active, job_dh, tg_dh

    def build_victims(self, max_priority: int):
        """Per-node preemption candidates for ops/preempt.py: the V
        lowest-priority live allocations on each real node, sorted
        priority-ascending (nomad_tpu/migrate victim_sort_key — the
        host list and the device tensor MUST agree on order, because
        the kernel returns only a victim COUNT per placement and the
        commit loop maps it back to the first n unconsumed entries).

        Only allocs strictly below ``max_priority`` (the preempting
        eval's) are candidates, and never this job's own. Returns
        (victim_arrays, victim_lists) where victim_arrays feed
        make_victim_state and victim_lists[row] is the ordered
        Allocation list; rows beyond n_real are padding."""
        from ..migrate import victim_priority, victim_sort_key
        from ..ops.preempt import PREEMPT_MAX_VICTIMS as V

        n = self.n
        res = np.zeros((n, V, 4), np.float32)
        bw = np.zeros((n, V), np.float32)
        ports = np.zeros((n, V), np.float32)
        prio = np.full((n, V), np.inf, np.float32)
        ok = np.zeros((n, V), bool)
        victim_lists: Dict[int, List[Allocation]] = {}
        total = 0
        for i, node in enumerate(self.nodes):
            cands = [
                a for a in self._proposed_allocs(node.id)
                if not a.terminal_status()
                and a.job_id != self.job.id
                and victim_priority(a) < max_priority
            ]
            if not cands:
                continue
            cands.sort(key=victim_sort_key)
            cands = cands[:V]
            victim_lists[i] = cands
            total += len(cands)
            for v, alloc in enumerate(cands):
                cpu, mem, disk, iops, mbits, nports = _alloc_usage(alloc)
                res[i, v] = (cpu, mem, disk, iops)
                bw[i, v] = mbits
                ports[i, v] = nports
                prio[i, v] = victim_priority(alloc)
                ok[i, v] = True
        return (res, bw, ports, prio, ok), victim_lists, total
