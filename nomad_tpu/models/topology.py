"""Node-topology tensor: rack / ICI-neighborhood ids as integer
columns on the cluster base.

Gang scheduling (nomad_tpu/gang) needs topology as ARRAYS: the dense
all-K feasibility pass groups per-node member capacity by topology
group (a scatter-add over group ids) and selects a contiguous slice on
device — per-node python dict reads per eval would put the whole gang
pass back on the GIL. This module interns each topology level's node
meta values (``meta.rack``, ``meta.ici``) into dense int32 id columns
padded to the base's node bucket.

Residency contract: topology is NODE-level and alloc-independent,
exactly like the computed-class index — a ``_ClusterBase`` builds its
``TopologyIndex`` once and every delta clone shares it BY REFERENCE
(models/matrix.py delta_update), so plan commits and node up/down/
drain flips ride the existing delta scatter without touching it. The
one transition that can change topology membership — node register/
deregister, or a meta edit (which moves the computed class and already
refuses the row delta) — breaks the delta family and re-anchors with a
full rebuild, which re-derives the tensor. That is how register/
deregister keeps the tensor current without a dedicated update path.

Padding/missing conventions (shared with ops/gang.py):

- rows past ``n_real`` (bucket padding) carry ``-1``;
- real nodes MISSING the meta key carry ``-1`` too: they can never
  prove slice contiguity, so slice-constrained gangs exclude them;
  spread/affinity treat each as its own singleton group.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

# Node meta keys per topology level. "rack" reuses the key the
# differential rig and docs already use; "ici" names the accelerator
# interconnect neighborhood (the Tesserae slice axis).
TOPOLOGY_META_KEYS = {"rack": "rack", "ici": "ici"}
TOPOLOGY_LEVELS = tuple(TOPOLOGY_META_KEYS)

# Topology-group-count padding ladder: the gang program's group-
# capacity array is [G_pad] and each distinct size is one compiled
# program (models/matrix.py CLASS_BUCKETS precedent — coarse beats
# tight through a compile-per-shape regime).
TOPO_GROUP_BUCKETS = [16, 64, 256, 1024]

# Registered sizer for ntalint's `unbucketed-shape` rule. The
# returns-a-bucketizer closure already sanctions topo_group_pad
# (its return IS a bucket_size call); the manifest states the intent
# explicitly so the sanction survives any reshaping of the body.
NTA_BUCKET_FNS = ("topo_group_pad",)


def topo_group_pad(n_groups: int) -> int:
    from .matrix import bucket_size

    return bucket_size(max(n_groups, 1), TOPO_GROUP_BUCKETS)


class TopologyIndex:
    """Interned topology columns for one node set. ``ids[level]`` is a
    padded [n_pad] int32 column (-1 = missing/padding), ``names[level]``
    the interned group-name list (id -> name)."""

    __slots__ = ("n_real", "n_pad", "ids", "names", "counts")

    def __init__(self, nodes, n_pad: int):
        self.n_real = len(nodes)
        self.n_pad = n_pad
        self.ids: Dict[str, np.ndarray] = {}
        self.names: Dict[str, List[str]] = {}
        self.counts: Dict[str, int] = {}
        for level, key in TOPOLOGY_META_KEYS.items():
            col = np.full(n_pad, -1, np.int32)
            interned: Dict[str, int] = {}
            names: List[str] = []
            for i, node in enumerate(nodes):
                value = node.meta.get(key)
                if not value:
                    continue
                gid = interned.get(value)
                if gid is None:
                    gid = len(names)
                    interned[value] = gid
                    names.append(value)
                col[i] = gid
            self.ids[level] = col
            self.names[level] = names
            self.counts[level] = len(names)

    def column(self, level: str) -> np.ndarray:
        """The padded id column for one level (read-only by contract:
        delta clones share it by reference)."""
        return self.ids[level]

    def group_name(self, level: str, gid: int) -> str:
        names = self.names[level]
        return names[gid] if 0 <= gid < len(names) else ""

    def singleton_column(self, level: str) -> Tuple[np.ndarray, int]:
        """The level's column with MISSING rows remapped to unique
        singleton group ids (spread/affinity semantics: a node without
        the meta key is its own group). Returns (column, group_count
        including singletons); padding rows stay -1."""
        col = self.ids[level].copy()
        base = self.counts[level]
        missing = np.flatnonzero(col[: self.n_real] < 0)
        col[missing] = base + np.arange(len(missing), dtype=np.int32)
        return col, base + len(missing)


def node_topology_summary(nodes) -> Dict[str, Dict[str, int]]:
    """{level: {group name: node count}} over a node list — the
    stats/debug surface (server.stats()["gang"]["topology"])."""
    out: Dict[str, Dict[str, int]] = {}
    for level, key in TOPOLOGY_META_KEYS.items():
        per: Dict[str, int] = {}
        for node in nodes:
            value = node.meta.get(key)
            if value:
                per[value] = per.get(value, 0) + 1
        out[level] = per
    return out
