from .matrix import ClusterMatrix, BUCKETS, bucket_size

__all__ = ["ClusterMatrix", "BUCKETS", "bucket_size"]
