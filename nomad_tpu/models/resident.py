"""Device-resident cluster-state tracking.

The dense path's authoritative ``[N, R]`` node matrix lives ON DEVICE
(scheduler/batcher.py's base cache); ``models/matrix.py``'s cached
``_ClusterBase`` is its host-side mirror. This module is the control
plane for that residency:

- **generation accounting** — every base is keyed by the raft
  watermarks it was built from (``nodes`` index, ``allocs`` index); a
  newer snapshot derives the next generation by a DELTA (recompute the
  touched rows, scatter them on device) instead of a full rebuild +
  re-upload. Plan commits advance the allocs axis
  (``_ClusterBase.delta_update``); node up/down/drain transitions
  advance the nodes axis and ride the SAME row scatter — the node
  stays in the matrix with ``node_ok`` masked instead of forcing a
  rebuild of the node axis (the matrix is built over the full
  datacenter *universe*, not the ready subset, exactly so readiness
  is row state rather than matrix shape).
- **rebuild policy** — thresholds for when a delta stops being worth
  it (too many touched rows) or stops being *possible* (alloc
  deletions, node registrations, capacity edits), with counters that
  tell the two cases apart.
- **staleness safety net** — the plan applier re-verifies every node
  exactly (server/plan_apply.py); a rejected plan means *some* state
  the scheduler planned against was wrong, so ``note_rejection()``
  marks the resident state suspect and the next build pays one full
  rebuild (``stale_rebuilds``) instead of trusting a possibly-bad
  delta chain. A wrong placement therefore costs one retry, never a
  committed double-book — the carve-over of the reference's
  plan_apply.go:318 exactness.

Chaos site ``matrix.stale_delta`` (kind='drop') deterministically
corrupts one delta application — a changed row is left un-recomputed —
so tests can prove the verification-rejection-rebuild loop end to end
without waiting for a real race.

Everything here is process-global (like the batcher's device cache it
fronts) and lock-guarded; counters are exposed via
``server.stats()["device_state"]`` and ``/v1/metrics``.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

# Default max refilled rows before a full rebuild is the better deal;
# mirrors the historical inline policy in _ClusterBase.delta_update.
AUTO_REBUILD_ROWS = 0  # 0 = max(64, n_real // 4)


class ResidentStateTracker:
    """Counters + policy for the device-resident node matrix."""

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = True  # guarded-by: _lock (universe + node deltas)
        self.rebuild_rows = AUTO_REBUILD_ROWS  # guarded-by: _lock
        # Build-mode counters. full_rebuilds counts every from-scratch
        # _ClusterBase on the cacheable path; the *_reason counters
        # attribute why the delta path was skipped.
        self.full_rebuilds = 0  # guarded-by: _lock
        self.delta_updates = 0  # guarded-by: _lock (alloc-axis rows)
        self.node_delta_updates = 0  # guarded-by: _lock (node-axis rows)
        # Cumulative recomputed-row counts per axis: delta SIZE, not
        # count — a climbing rows/update ratio says deltas are drifting
        # toward the rebuild threshold.
        self.alloc_delta_rows = 0  # guarded-by: _lock
        self.node_delta_rows = 0  # guarded-by: _lock
        self.stale_rebuilds = 0  # guarded-by: _lock (post-rejection)
        self.universe_rebuilds = 0  # guarded-by: _lock (node set changed)
        # Plan-apply rejection marked the resident chain suspect; the
        # next cacheable build consumes this and rebuilds from scratch.
        self._stale = False  # guarded-by: _lock

    # ------------------------------------------------------------ policy

    def configure(self, enabled: Optional[bool] = None,
                  rebuild_rows: Optional[int] = None) -> None:
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if rebuild_rows is not None:
                self.rebuild_rows = int(rebuild_rows)

    def is_enabled(self) -> bool:
        with self._lock:
            return self.enabled

    def max_refill_rows(self, n_real: int) -> int:
        with self._lock:
            limit = self.rebuild_rows
        return limit if limit > 0 else max(64, n_real // 4)

    # --------------------------------------------------------- staleness

    def note_rejection(self) -> None:
        """The plan applier rejected a plan: whatever matrix the
        scheduler planned against disagreed with the store. Mark the
        resident chain suspect — one full rebuild re-anchors it. Cheap
        and idempotent; called from the applier's rejection path."""
        with self._lock:
            self._stale = True

    def consume_stale(self) -> bool:
        """True exactly once per note_rejection burst: the caller must
        full-rebuild (and gets counted in stale_rebuilds)."""
        with self._lock:
            if not self._stale:
                return False
            self._stale = False
            self.stale_rebuilds += 1
            return True

    # ---------------------------------------------------------- counters

    def count_full(self) -> None:
        with self._lock:
            self.full_rebuilds += 1

    def count_universe(self) -> None:
        with self._lock:
            self.universe_rebuilds += 1

    def count_delta(self, alloc_rows: int, node_rows: int) -> None:
        with self._lock:
            self.delta_updates += 1
            self.alloc_delta_rows += alloc_rows
            if node_rows:
                self.node_delta_updates += 1
                self.node_delta_rows += node_rows

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "full_rebuilds": self.full_rebuilds,
                "delta_updates": self.delta_updates,
                "node_delta_updates": self.node_delta_updates,
                "alloc_delta_rows": self.alloc_delta_rows,
                "node_delta_rows": self.node_delta_rows,
                "stale_rebuilds": self.stale_rebuilds,
                "universe_rebuilds": self.universe_rebuilds,
            }


_tracker = ResidentStateTracker()


def get_tracker() -> ResidentStateTracker:
    return _tracker


def configure(enabled: Optional[bool] = None,
              rebuild_rows: Optional[int] = None) -> None:
    _tracker.configure(enabled=enabled, rebuild_rows=rebuild_rows)


def note_rejection() -> None:
    _tracker.note_rejection()


def device_state_stats() -> Dict[str, object]:
    """The ``server.stats()["device_state"]`` payload: resident-chain
    counters plus the batcher's upload/delta tallies and the jit
    compile-cache size (a CLIMBING cache under steady load is a
    recompile storm — bench.py's jit_recompiles column gates on it)."""
    from ..scheduler.batcher import get_batcher

    out = _tracker.stats()
    b = get_batcher().stats()
    out["jit_cache_size"] = b["jit_cache_size"]
    out["base_uploads"] = b["base_uploads"]
    out["base_delta_updates"] = b["base_delta_updates"]
    out["upload_bytes"] = b["upload_bytes"]
    return out
