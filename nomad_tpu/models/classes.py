"""The compression plane: computed-node-class dedup for the dense path.

Real fleets collapse into C << N equivalence classes — the reference
memoizes *feasibility* per computed class (structs/node_class.go:31,
scheduler/feasible.go:457) and models/matrix.py already rides that for
the [N, G] constraint mask. This module interns the rest of a node's
*placement-relevant* identity so whole dense programs can run at class
granularity and expand back to concrete nodes only at the
assignment/rounding step (defrag/solver.py's global solve is the first
consumer: its x[K, N] tensor is the biggest in the system and shrinks
to x[K, C]).

The signature REFINES the computed class: it is the computed-class
digest (datacenter / node_class / non-unique attrs+meta — everything
the feasibility checkers read, scheduler/feasible.py
resolve_constraint_target) plus the static row state matrix.py
_fill_static derives (raw + reserved capacity, link bandwidth, reserved
ports) and the topology group ids (models/topology.py). Two nodes with
equal signatures therefore produce bit-identical static matrix rows and
identical feasibility verdicts for every non-escaped constraint — they
are placement-indistinguishable up to their *live* allocations, which
stay per-node in the dense arrays (tests/test_classes.py holds this
against the oracle differential rig).

Escape hatch: a node without a computed class (dynamic, non-hashable
attr values — structs/node.py compute_class refuses to digest those)
gets a SINGLETON class, so every node is in exactly one class and
class-granular aggregation covers the whole fleet; it just compresses
nothing for the escaped rows.

Like the class index and topology tensor, a ClassIndex is node-level
and alloc-independent: delta clones of a cluster base share it by
reference, and a node whose signature moves (meta edit, capacity
change) refuses the row delta and forces a rebuild that re-interns
(models/matrix.py delta_update — the class-split path).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..structs import consts
from ..structs.node import Node
from .topology import TOPOLOGY_META_KEYS


def node_signature(node: Node) -> Optional[Tuple]:
    """Hashable placement signature of one node, or None for the
    escape-hatch (singleton-class) path. Covers the computed-class
    digest plus every static field matrix.py _fill_static reads, so
    signature equality implies bit-identical static rows."""
    if not node.computed_class:
        return None
    r = node.resources
    if r is None:
        return None
    res = node.reserved
    res_bw = 0.0
    res_ports = 0
    if res is not None:
        for net in res.networks:
            res_bw += net.mbits
            for p in list(net.reserved_ports) + list(net.dynamic_ports):
                if consts.MIN_DYNAMIC_PORT <= p.value < consts.MAX_DYNAMIC_PORT:
                    res_ports += 1
    return (
        node.computed_class,
        (r.cpu, r.memory_mb, r.disk_mb, r.iops),
        (res.cpu, res.memory_mb, res.disk_mb, res.iops)
        if res is not None else (0, 0, 0, 0),
        r.networks[0].mbits if r.networks else 0.0,
        res_bw,
        res_ports,
        # Topology group membership (models/topology.py): non-unique
        # meta is already inside the computed-class digest, but the
        # gang program's group ids must never ride a merged class even
        # if the digest scheme drifts — state them explicitly.
        tuple(node.meta.get(k) for k in sorted(TOPOLOGY_META_KEYS.values())),
    )


class ClassIndex:
    """Node -> signature-class interning over one matrix's node list.

    ``ids[i]`` is the class of row i (-1 only on padding rows — escaped
    nodes get singleton classes, so every real row is classed),
    ``reps[c]`` a representative row, ``counts[c]`` the member count,
    and ``members(c)`` the member rows. Construction is deterministic
    in row order, so two builds over the same node list are equal
    array-for-array (the parity property tests/test_resident_state.py
    asserts at every raft index)."""

    __slots__ = ("ids", "reps", "counts", "signatures", "n_real",
                 "n_classes", "n_escaped", "_members")

    def __init__(self, nodes: List[Node], n_pad: Optional[int] = None):
        n_real = len(nodes)
        self.n_real = n_real
        if n_pad is None:
            # Default-sized builds land on the node bucket ladder: a
            # raw len(nodes) shape here becomes a per-N compile key
            # the moment ids rides a device program (ntalint
            # unbucketed-shape). Lazy import: matrix.py imports us.
            from .matrix import BUCKETS, bucket_size
            n_pad = bucket_size(max(n_real, 1), BUCKETS)
        self.ids = np.full(n_pad, -1, np.int32)
        self.reps: List[int] = []
        self.signatures: List[Optional[Tuple]] = []
        counts: List[int] = []
        index: Dict[Tuple, int] = {}
        escaped = 0
        for i, node in enumerate(nodes):
            sig = node_signature(node)
            if sig is None:
                # Escape hatch: a class of one, never merged.
                ci = len(self.reps)
                self.reps.append(i)
                self.signatures.append(None)
                counts.append(1)
                escaped += 1
            else:
                ci = index.get(sig)
                if ci is None:
                    ci = len(self.reps)
                    index[sig] = ci
                    self.reps.append(i)
                    self.signatures.append(sig)
                    counts.append(0)
                counts[ci] += 1
            self.ids[i] = ci
        self.counts = np.asarray(counts, np.int32)
        self.n_classes = len(self.reps)
        self.n_escaped = escaped
        self._members: Optional[List[np.ndarray]] = None

    def signature_of(self, row: int) -> Optional[Tuple]:
        """The interned signature of one real row (None for escaped
        rows) — what delta_update compares against the refreshed node
        object to detect a class split."""
        ci = int(self.ids[row])
        if ci < 0:
            return None
        return self.signatures[ci]

    def members(self, ci: int) -> np.ndarray:
        """Member rows of one class (ascending). The per-class lists
        build lazily in one vectorized pass — expansion-side consumers
        (defrag rounding, bench audits) want them, the hot build path
        does not."""
        if self._members is None:
            order = np.argsort(self.ids[: self.n_real], kind="stable")
            ordered_ids = self.ids[order]
            bounds = np.searchsorted(
                ordered_ids, np.arange(self.n_classes + 1))
            self._members = [
                order[bounds[c]: bounds[c + 1]]
                for c in range(self.n_classes)
            ]
        return self._members[ci]

    def compression_ratio(self) -> float:
        """N / C — the bench's ``class_compression_ratio`` column; 1.0
        means the plane compresses nothing (all-singleton fleet)."""
        return self.n_real / max(1, self.n_classes)

    def stats(self) -> dict:
        """The ``matrix.compress`` trace-span annotation shape."""
        return {
            "classes": int(self.n_classes),
            "nodes": int(self.n_real),
            "escaped": int(self.n_escaped),
            "ratio": round(self.compression_ratio(), 2),
        }


def best_member_rows(idx: ClassIndex, util: np.ndarray,
                     capacity: np.ndarray,
                     node_ok: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-class choice of the concrete node a class-granular placement
    expands to: the least-filled schedulable member (fill = max of the
    cpu/mem utilization fractions). Returns (rows [n_classes] int64,
    class_ok [n_classes] bool); rows of classes with no schedulable
    member point at the representative and class_ok goes False.

    Host numpy, once per placement round on the expansion path — the
    dense program scores C class rows, this picks which member each
    winning class lands on (the same expand-at-rounding step the defrag
    solve takes through expand_to_nodes)."""
    n = idx.n_real
    denom = np.maximum(capacity[:n, :2], 1.0)
    fill = np.max(util[:n, :2] / denom, axis=1)
    fill = np.where(node_ok[:n], fill, np.inf)
    rows = np.empty(idx.n_classes, np.int64)
    ok = np.empty(idx.n_classes, bool)
    for c in range(idx.n_classes):
        members = idx.members(c)
        best = members[np.argmin(fill[members])]
        rows[c] = best
        ok[c] = np.isfinite(fill[best])
    return rows, ok


def class_sum(values: np.ndarray, ids: np.ndarray, n_classes: int,
              where: Optional[np.ndarray] = None) -> np.ndarray:
    """Aggregate per-node values [N(, R)] to per-class sums
    [n_classes(, R)] (n_classes may be padded past the index's count).
    ``where`` masks rows out of the aggregate — the defrag solve drops
    not-ok members so a class's capacity is its LIVE capacity."""
    n = len(ids)
    vals = values[:n]
    if where is not None:
        w = where[:n].astype(vals.dtype)
        vals = vals * (w[:, None] if vals.ndim == 2 else w)
    out_shape = (n_classes,) + vals.shape[1:]
    out = np.zeros(out_shape, vals.dtype)
    np.add.at(out, ids, vals)
    return out


def class_any(flags: np.ndarray, ids: np.ndarray,
              n_classes: int) -> np.ndarray:
    """Per-class OR of a boolean row property (e.g. node_ok: a class is
    schedulable while any member is)."""
    out = np.zeros(n_classes, bool)
    np.logical_or.at(out, ids, flags[: len(ids)])
    return out


def expand_to_nodes(per_class: np.ndarray, ids: np.ndarray,
                    counts: np.ndarray) -> np.ndarray:
    """Expand a class-granular solution [.., C] back to node granularity
    [.., N], splitting each class's mass evenly over its members — the
    expansion step before per-node rounding (defrag/solver.py walks the
    expanded preferences against actual per-node headroom, so the even
    split is a tie-break, not a feasibility claim).

    Host numpy on purpose: expansion happens once per solve on the
    host rounding path, never inside a jitted program (the ntalint
    residency gate keeps device transfers out of here)."""
    share = per_class[..., ids] / np.maximum(counts[ids], 1)
    return share.astype(per_class.dtype, copy=False)
