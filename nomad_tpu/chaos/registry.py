"""Deterministic, seed-driven fault-injection registry.

The dispatch pipeline (PR 1) concentrated the dense placement path
onto a single leader-side dispatcher — a leader flap, a slow follower,
a worker crash, or a device-lane failure now has one high-blast-radius
place to hurt us. This registry makes those failures *injectable,
deterministic, and replayable*: named injection sites are wired into
the layers that matter (transport, raft, broker, dispatch pipeline,
device dispatch, heartbeats), and an armed seed + fault schedule
injects drops, delays and exceptions whose firing sequence is a pure
function of (seed, site, call-ordinal) — replaying the same seed
against the same per-site call sequence produces an identical firing
log.

Production cost: sites guard with ``chaos.enabled`` (a plain attribute
read) before calling :meth:`ChaosRegistry.fire`, and ``fire`` itself
is a constant-false check when disarmed — zero allocation, zero lock.

Site semantics (what a fired action means is defined BY the site):

=====================  =======================================================
site                   wired into
=====================  =======================================================
``transport.send``     TCP raft RPC about to go out (drop = peer unreachable)
``transport.recv``     TCP raft RPC response received (drop = response lost)
``raft.apply``         RaftNode.apply entry (delay = apply latency)
``raft.commit``        commit-index advance (drop = skip a round)
``raft.heartbeat``     leader heartbeat broadcast (drop = missed round ->
                       election timeout -> leader flap)
``broker.deliver``     eval handed to a dequeuer (drop = delivery lost; the
                       lease is burned and the eval redelivers)
``broker.nack_timer``  nack-timeout firing (drop = timer re-armed; delay =
                       late redelivery)
``dispatch.launch``    pipeline batch launch prologue (error = launch fails,
                       whole batch nacks)
``dispatch.submit``    pipeline plan submit (error = submit fails, eval nacks)
``dispatch.finish``    pipeline ack/nack (drop = worker crash holding an
                       unacked eval; the broker nack timer reclaims it)
``batcher.dispatch``   placement batcher device dispatch (delay = slow device)
``binpack.device``     device execution gate (error = device fault; the dense
                       scheduler falls back to the host path)
``heartbeat.expire``   leader-side TTL expiry (drop = invalidation lost, the
                       timer re-arms; delay = late node-down)
``client.heartbeat``   client heartbeat tick (drop = heartbeat lost -> TTL
                       expiry -> node down)
``admission.slow_consumer``  pipeline stage consumer about to process an
                       eval (delay = a wedged scheduler thread: e2e p99
                       inflates and the pressure monitor must react;
                       error = the consumer dies, the eval nacks)
``device.breaker_trip``  device dispatch at the circuit breaker's gate
                       (error = device fault the breaker counts — K of
                       them trip the dense path to the host iterators;
                       delay = a slow batch for the slow-trip rule)
``matrix.stale_delta``  incremental cluster-base delta application
                       (drop = one delta record is lost: a changed node
                       row keeps its stale values on host AND device,
                       so the scheduler plans against wrong state — the
                       plan applier's exact verification must catch the
                       bad placement and force a full rebuild,
                       models/resident.py)
``drain.mid_migration``  top of a scheduler's migrate leg, before any
                       budget claim or staged eviction (error = the
                       eval dies mid-migration and must redeliver with
                       nothing committed — the drain soak's exactly-
                       once contract; delay = a slow migration wave)
``preempt.victim_lost``  per-victim at preemption commit (drop = the
                       victim is NOT staged in the plan though the
                       kernel already counted its freed capacity —
                       the plan applier's exact verification must
                       reject the under-freed node and force a replan)
``defrag.solve_stale``  defrag-loop round, after the solve completes
                       (drop = the solve raced a resident-base
                       rejection purge: the wave is discarded and the
                       warm carry dropped — NOTHING commits from a
                       chain the applier convicted, nomad_tpu/defrag)
``defrag.wave_lost``   defrag-loop wave watch (drop = the in-flight
                       wave is declared dead: every remaining
                       MigrationGovernor slot the loop claimed is
                       released; the wave's evals keep their own
                       exactly-once terminal path)
``gang.partial_commit``  plan-applier gang verification
                       (drop = one gang member's node is treated as
                       under-fitting at verification time — the WHOLE
                       gang must reject, every member filtered off
                       accepted nodes too, nothing partial commits;
                       server/plan_apply.py)
``gang.member_lost``   gang reconciliation in the scheduler (drop =
                       one live gang member is treated as lost — its
                       node died mid-flight — which must trigger the
                       whole-gang replacement: survivors stopped and
                       all K re-placed atomically;
                       scheduler/generic.py)
=====================  =======================================================
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional, Tuple

# Every wire-able site. arm() validates the schedule against this set so
# a typo'd site name fails loudly instead of silently never firing.
KNOWN_SITES = frozenset({
    "transport.send",
    "transport.recv",
    "raft.apply",
    "raft.commit",
    "raft.heartbeat",
    "broker.deliver",
    "broker.nack_timer",
    "dispatch.launch",
    "dispatch.submit",
    "dispatch.finish",
    "batcher.dispatch",
    "binpack.device",
    "heartbeat.expire",
    "client.heartbeat",
    "admission.slow_consumer",
    "device.breaker_trip",
    "matrix.stale_delta",
    "drain.mid_migration",
    "preempt.victim_lost",
    "defrag.solve_stale",
    "defrag.wave_lost",
    "gang.partial_commit",
    "gang.member_lost",
})

DROP = "drop"
DELAY = "delay"
ERROR = "error"
_KINDS = (DROP, DELAY, ERROR)


class ChaosInjectedError(Exception):
    """Raised out of an armed injection site configured kind='error'.

    Carries the site and per-site call ordinal so a failure seen in a
    test log maps straight back to the schedule entry that fired."""

    def __init__(self, site: str, seq: int):
        super().__init__(f"chaos-injected fault at {site!r} (call #{seq})")
        self.site = site
        self.seq = seq


class FaultSpec:
    """One scheduled fault at one site.

    - ``site``: a :data:`KNOWN_SITES` name.
    - ``kind``: ``drop`` | ``delay`` | ``error`` (the site defines what
      each means — see the module docstring table).
    - ``start``: first eligible call ordinal at the site (0-based): the
      fault arms only from the ``start``-th fire() call on.
    - ``count``: max times this spec fires (None = unlimited).
    - ``prob``: per-call firing probability, decided by the seeded RNG.
    - ``delay``: seconds to sleep for kind='delay'.
    - ``match``: optional {key: value} filter against the fire() call's
      context kwargs — e.g. ``match={"node": node_id}`` drops one
      node's heartbeats only.
    """

    __slots__ = ("site", "kind", "start", "count", "prob", "delay",
                 "match", "fired")

    def __init__(self, site: str, kind: str, start: int = 0,
                 count: Optional[int] = None, prob: float = 1.0,
                 delay: float = 0.0, match: Optional[dict] = None):
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        self.site = site
        self.kind = kind
        self.start = start
        self.count = count
        self.prob = prob
        self.delay = delay
        self.match = dict(match) if match else None
        self.fired = 0  # guarded by the registry lock once armed

    def to_dict(self) -> dict:
        return {
            "site": self.site, "kind": self.kind, "start": self.start,
            "count": self.count, "prob": self.prob, "delay": self.delay,
            "match": self.match, "fired": self.fired,
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<FaultSpec {self.to_dict()}>"


class _Armed:
    """Context manager returned by ChaosRegistry.armed()."""

    def __init__(self, registry: "ChaosRegistry"):
        self._registry = registry

    def __enter__(self) -> "ChaosRegistry":
        return self._registry

    def __exit__(self, *exc) -> None:
        self._registry.disarm()


class ChaosRegistry:
    def __init__(self):
        # Plain attribute, read un-locked on every site: the production
        # fast path is one attribute load + branch. Arming happens-before
        # any fire that must see the schedule because arm() publishes
        # under the lock and fire() re-checks under it.
        self.enabled = False
        self._lock = threading.Lock()
        self._seed = 0
        self._specs: Dict[str, List[FaultSpec]] = {}
        self._calls: Dict[str, int] = {}  # site -> fire() calls seen
        # (site, call ordinal, kind, delay) in per-site order; read back
        # sorted so the log is deterministic given deterministic
        # per-site call sequences, regardless of thread interleaving.
        self._log: List[Tuple[str, int, str, float]] = []

    # ------------------------------------------------------ arm/disarm

    def arm(self, seed: int, schedule: List[FaultSpec]) -> None:
        """Arm the registry: from now on fire() decides faults from the
        seed + schedule. Unknown site names raise (typo guard)."""
        bad = sorted({s.site for s in schedule} - KNOWN_SITES)
        if bad:
            raise ValueError(
                f"unknown chaos site(s) {bad}; known sites: "
                f"{sorted(KNOWN_SITES)}")
        with self._lock:
            self._seed = seed
            self._specs = {}
            for spec in schedule:
                spec.fired = 0
                self._specs.setdefault(spec.site, []).append(spec)
            self._calls = {}
            self._log = []
            self.enabled = True

    def armed(self, seed: int, schedule: List[FaultSpec]) -> _Armed:
        """arm() as a context manager: always disarms on exit (the
        registry is process-global — a leaked schedule would inject
        faults into whatever test runs next)."""
        self.arm(seed, schedule)
        return _Armed(self)

    def disarm(self) -> None:
        with self._lock:
            self.enabled = False
            self._specs = {}

    # ------------------------------------------------------------ fire

    def fire(self, site: str, **ctx) -> Optional[str]:
        """Injection-site hook. Disabled: returns None (constant-false
        check). Armed: deterministically decides whether a scheduled
        fault fires for this site's next call ordinal; performs 'delay'
        in-line, raises ChaosInjectedError for 'error', and returns
        'drop'/'delay'/None for the site to act on."""
        if not self.enabled:
            return None
        with self._lock:
            if not self.enabled:  # disarmed between check and lock
                return None
            n = self._calls.get(site, 0)
            self._calls[site] = n + 1
            spec = self._decide_locked(site, n, ctx)
            if spec is None:
                return None
            spec.fired += 1
            action = spec.kind
            delay = spec.delay
            self._log.append((site, n, action, delay))
        # Trace correlation: when the firing call carries an eval
        # context, stamp (site, ordinal, kind) onto that eval's trace —
        # at completion it lands on the span covering the firing time,
        # so a seeded replay pinpoints which stage a fault inflated.
        eval_id = ctx.get("eval_id")
        if eval_id:
            from ..trace import annotate_fault

            annotate_fault(eval_id, site, n, action)
        # Side effects OUTSIDE the lock: a delay must never hold up
        # unrelated sites' decisions, and the raise must not poison the
        # registry state.
        if action == DELAY:
            time.sleep(delay)
            return DELAY
        if action == ERROR:
            raise ChaosInjectedError(site, n)
        return DROP

    def _decide_locked(self, site: str, n: int,
                       ctx: dict) -> Optional[FaultSpec]:
        specs = self._specs.get(site)
        if not specs:
            return None
        # The per-call RNG seeds from a STRING (CPython hashes str/bytes
        # seeds via sha512 — stable across processes, unlike hash()
        # under PYTHONHASHSEED randomization), so the n-th call at a
        # site decides identically on every replay of the same seed.
        rng = random.Random(f"{self._seed}:{site}:{n}")
        for spec in specs:
            if n < spec.start:
                continue
            if spec.count is not None and spec.fired >= spec.count:
                continue
            if spec.match is not None and any(
                    ctx.get(k) != v for k, v in spec.match.items()):
                continue
            if spec.prob < 1.0 and rng.random() >= spec.prob:
                continue
            return spec
        return None

    # ----------------------------------------------------- observation

    def firing_log(self) -> List[Tuple[str, int, str, float]]:
        """Fired faults as (site, call ordinal, kind, delay), sorted by
        (site, ordinal) — the deterministic replay artifact."""
        with self._lock:
            return sorted(self._log)

    def unfired(self) -> List[FaultSpec]:
        """Scheduled specs that never fired — the bench --chaos typo
        guard refuses to report numbers while this is non-empty (a
        schedule that never exercised its path measured nothing)."""
        with self._lock:
            return [s for specs in self._specs.values()
                    for s in specs if s.fired == 0]

    def calls(self, site: str) -> int:
        with self._lock:
            return self._calls.get(site, 0)

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "seed": self._seed,
                "fired": len(self._log),
                "calls": dict(self._calls),
                "specs": [s.to_dict()
                          for specs in self._specs.values()
                          for s in specs],
            }


# The process-wide registry every injection site imports. Module-level
# so the disabled check compiles down to two attribute loads + a branch.
chaos = ChaosRegistry()
