"""Deterministic fault injection for the dispatch/broker/raft path.

See registry.py for the site table and semantics; tests/test_chaos_soak.py
for the soak harness; README.md "Failure model" for the operator view.
"""

from .registry import (  # noqa: F401
    DELAY,
    DROP,
    ERROR,
    KNOWN_SITES,
    ChaosInjectedError,
    ChaosRegistry,
    FaultSpec,
    chaos,
)
