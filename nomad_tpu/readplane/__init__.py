"""The read plane: scoped-index blocking queries served by a
parked-watcher multiplexer, with stale/consistent read modes layered
on in api/http.py. See readplane/README.md."""

from .mux import ParkedQuery, ReadMux

__all__ = ["ParkedQuery", "ReadMux"]
