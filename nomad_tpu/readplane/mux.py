"""Parked-watcher long-poll multiplexer: blocking queries without
parked HTTP threads.

The thread-parking blocking query (api/http.py `_blocking`) holds one
HTTP handler thread per watcher for up to MAX_BLOCKING_WAIT — N
watchers cost N OS threads, and before scoped indexes every commit
woke all of them. The mux applies the executive's event-loop
discipline to the read side:

- A blocking query whose scope has not yet passed ``?index=N``
  registers a **continuation** — scope set, min index, deadline, and a
  serialized-response thunk that re-runs the query and writes the raw
  HTTP response straight to the (detached) client socket — in
  lock-striped parked rings keyed by watch scope. The handler thread
  then exits; the socket stays open, owned by the continuation.
- One **wake-owner thread** (`_wake_loop`, registered in
  ``NTA_DISPATCHER_ENTRYPOINTS`` — it is a never-blocking clock like
  the executive drain) drains scope notifications fed by the store's
  NotifyGroup sink, re-checks each candidate's scope index, and hands
  satisfied or expired continuations to a small bounded WorkPool that
  re-runs the query and streams the response.
- Parked continuations live in the MUX, not in the store's
  NotifyGroup, so an FSM snapshot-restore store swap never strands a
  watcher: the wake loop re-subscribes to the new store's notify feed
  on its next tick (detected via ``store_id``) and scope checks always
  read the current store.

Counters (parked/wakes/spurious/served/timeouts/write_errors) surface
as ``readplane.*`` gauges in /v1/metrics, and park→wake / serve
durations land in the flight recorder's stage table as ``read.park`` /
``read.serve``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..profile import ProfiledCondition, ProfiledLock
from ..trace import get_recorder
from ..utils import metrics
from ..utils.pool import WorkPool

logger = logging.getLogger("nomad_tpu.readplane")

Item = Tuple[str, str]

N_STRIPES = 8
# Wake-loop tick ceiling: the loop re-checks deadlines and store swaps
# at least this often even with no notifications in flight.
WAKE_SLICE = 0.25

# ntalint lock-discipline manifest (analysis/locks.py): the wake owner
# is the read plane's clock — everything reachable from it must never
# block (bounded cond-waits on the mux's own lock are the sanctioned
# scheduling primitive). Query RE-RUNS deliberately happen off-loop on
# the serve pool; the pool handoff is submit-only and never parks.
NTA_DISPATCHER_ENTRYPOINTS = ("ReadMux._wake_loop",)


class ParkedQuery:
    """One parked blocking query's continuation."""

    __slots__ = ("scopes", "min_index", "deadline", "serve", "parked_at",
                 "claimed", "seq")

    def __init__(self, scopes: List[Item], min_index: int, deadline: float,
                 serve: Callable[[str], None], seq: int = 0):
        self.scopes = list(scopes)
        self.min_index = min_index
        self.deadline = deadline
        self.serve = serve
        self.parked_at = time.monotonic()
        self.claimed = False  # guarded-by: primary stripe lock
        # Notify-batch sequence at registration: batches numbered below
        # this predate the park and are never weighed against it (the
        # park-time recheck covers that window), so a backlog of
        # pre-park notifications can't masquerade as spurious wakes.
        self.seq = seq


class _Stripe:
    __slots__ = ("lock", "by_scope")

    def __init__(self):
        self.lock = ProfiledLock("readplane.mux.stripe")
        # scope item -> set of parked continuations watching it
        self.by_scope: Dict[Item, Set[ParkedQuery]] = {}


class ReadMux:
    """Owns the parked rings, the wake-owner thread, and the bounded
    serve pool. ``store`` is a zero-arg callable returning the current
    StateStore (the FSM swaps stores on snapshot restore)."""

    def __init__(self, store: Callable[[], object], workers: int = 4,
                 max_parked: int = 4096):
        self._store = store
        self.max_parked = max_parked
        self._stripes = [_Stripe() for _ in range(N_STRIPES)]
        self._pool = WorkPool(max(1, workers), name="read-serve")
        self._lock = ProfiledLock("readplane.mux")
        self._cond = ProfiledCondition(self._lock)
        # (seq, items) notify batches awaiting the wake owner, plus the
        # next batch number; guarded-by: _lock
        self._pending: List[Tuple[int, List[Item]]] = []
        self._seq = 0
        self._next_deadline: Optional[float] = None  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._subscribed_id = ""
        # counters, guarded-by: _lock
        self._parked = 0
        self._parked_total = 0
        self._wakes = 0
        self._spurious = 0
        self._served = 0
        self._timeouts = 0
        self._write_errors = 0

    # ------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._wake_loop, name="read-mux", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None
        # Flush every still-parked continuation so no client socket is
        # left dangling across a shutdown: serve current data inline.
        for rec in self._drain_all():
            self._run_serve(rec, "shutdown")

    # ------------------------------------------------------- park side

    def park(self, scopes: List[Item], min_index: int, deadline: float,
             serve: Callable[[str], None]) -> bool:
        """Register a continuation. Returns False (caller must fall
        back to thread-parking) when the mux is stopped or full."""
        if self._thread is None:
            return False
        with self._cond:
            if self._parked >= self.max_parked:
                return False
            self._parked += 1
            self._parked_total += 1
            seq = self._seq
        rec = ParkedQuery(scopes, min_index, deadline, serve, seq)
        for scope in set(rec.scopes):
            stripe = self._stripe(scope)
            with stripe.lock:
                stripe.by_scope.setdefault(scope, set()).add(rec)
        with self._cond:
            if (self._next_deadline is None
                    or deadline < self._next_deadline):
                self._next_deadline = deadline
            self._cond.notify()
        # Close the check-then-park race: a commit that landed between
        # the caller's index check and the registration above fired its
        # notify before this continuation was findable.
        store = self._store()
        if store is not None and store.scope_index(rec.scopes) > min_index:
            if self._claim(rec):
                self._retire(rec)
                self._submit_serve(rec, "wake")
        return True

    def _stripe(self, scope: Item) -> _Stripe:
        return self._stripes[hash(scope) % N_STRIPES]

    def _claim(self, rec: ParkedQuery) -> bool:
        stripe = self._stripe(rec.scopes[0])
        with stripe.lock:
            if rec.claimed:
                return False
            rec.claimed = True
            return True

    def _retire(self, rec: ParkedQuery) -> None:
        """Remove a CLAIMED continuation from every scope ring and drop
        the parked count."""
        for scope in set(rec.scopes):
            stripe = self._stripe(scope)
            with stripe.lock:
                group = stripe.by_scope.get(scope)
                if group is not None:
                    group.discard(rec)
                    if not group:
                        del stripe.by_scope[scope]
        with self._cond:
            self._parked -= 1

    def _drain_all(self) -> List[ParkedQuery]:
        out = []
        for stripe in self._stripes:
            with stripe.lock:
                recs = set()
                for group in stripe.by_scope.values():
                    recs |= group
            for rec in recs:
                if self._claim(rec):
                    self._retire(rec)
                    out.append(rec)
        return out

    # ------------------------------------------------------- wake side

    def on_notify(self, items: List[Item]) -> None:
        """NotifyGroup sink: runs on the committing (FSM) thread, so it
        only queues and signals — the scope checks happen on the wake
        owner."""
        with self._cond:
            self._pending.append((self._seq, items))
            self._seq += 1
            self._cond.notify()

    def _wake_loop(self) -> None:
        while not self._stop.is_set():
            store = self._resubscribe_if_swapped()
            now = time.monotonic()
            with self._cond:
                timeout = WAKE_SLICE
                if self._next_deadline is not None:
                    timeout = min(timeout,
                                  max(self._next_deadline - now, 0.0))
                if not self._pending and timeout > 0:
                    self._cond.wait(timeout)
                batch = self._pending
                self._pending = []
                parked = self._parked
            metrics.set_gauge(("readplane", "parked"), parked)
            if store is None:
                continue
            woken: Dict[Item, int] = {}
            for seq, items in batch:
                for it in items:
                    if seq > woken.get(it, -1):
                        woken[it] = seq
            for scope, seq in woken.items():
                stripe = self._stripe(scope)
                with stripe.lock:
                    candidates = list(stripe.by_scope.get(scope, ()))
                for rec in candidates:
                    if seq < rec.seq:
                        # Every batch here predates this park: old news,
                        # not a wake signal for it (any index movement
                        # in that window was caught by park()'s
                        # post-registration recheck).
                        continue
                    self._note_wake()
                    if store.scope_index(rec.scopes) > rec.min_index:
                        if self._claim(rec):
                            self._retire(rec)
                            self._submit_serve(rec, "wake")
                    else:
                        with self._cond:
                            self._spurious += 1
                        metrics.incr_counter(("readplane", "spurious"))
            self._expire(time.monotonic())

    def _note_wake(self) -> None:
        with self._cond:
            self._wakes += 1

    def _resubscribe_if_swapped(self):
        store = self._store()
        if store is None:
            return None
        sid = getattr(store, "store_id", "")
        if sid and sid != self._subscribed_id:
            store.notify.subscribe(self.on_notify)
            self._subscribed_id = sid
        return store

    def _expire(self, now: float) -> None:
        with self._cond:
            nxt = self._next_deadline
        if nxt is None or now < nxt:
            return
        expired: List[ParkedQuery] = []
        soonest: Optional[float] = None
        for stripe in self._stripes:
            with stripe.lock:
                recs = set()
                for group in stripe.by_scope.values():
                    recs |= group
            for rec in recs:
                if rec.deadline <= now:
                    if self._claim(rec):
                        self._retire(rec)
                        expired.append(rec)
                elif soonest is None or rec.deadline < soonest:
                    soonest = rec.deadline
        with self._cond:
            self._next_deadline = soonest
        for rec in expired:
            with self._cond:
                self._timeouts += 1
            metrics.incr_counter(("readplane", "timeouts"))
            self._submit_serve(rec, "timeout")

    # ------------------------------------------------------ serve side

    def _submit_serve(self, rec: ParkedQuery, reason: str) -> None:
        get_recorder().observe_stage(
            "read.park", (time.monotonic() - rec.parked_at) * 1000.0)
        self._pool.submit(self._run_serve, rec, reason)

    def _run_serve(self, rec: ParkedQuery, reason: str) -> None:
        t0 = time.monotonic()
        try:
            rec.serve(reason)
            with self._cond:
                self._served += 1
            metrics.incr_counter(("readplane", "served"))
        except Exception:  # noqa: BLE001
            # The thunk writes to a client socket the client may have
            # abandoned mid-park — a write failure is the client's
            # hangup, not a server fault. Count it and move on.
            with self._cond:
                self._write_errors += 1
            metrics.incr_counter(("readplane", "write_errors"))
            logger.debug("parked-query serve failed", exc_info=True)
        finally:
            get_recorder().observe_stage(
                "read.serve", (time.monotonic() - t0) * 1000.0)

    # ---------------------------------------------------- observation

    def stats(self) -> dict:
        with self._cond:
            out = {
                "parked": self._parked,
                "parked_total": self._parked_total,
                "wakes": self._wakes,
                "spurious": self._spurious,
                "served": self._served,
                "timeouts": self._timeouts,
                "write_errors": self._write_errors,
            }
        out["serve_workers"] = self._pool.worker_count()
        out["serve_queued"] = self._pool.queued()
        return out
