"""Eval-lifecycle tracing: flight-recorder spans with p99 stage
attribution (see trace/README.md).

Every evaluation yields a span tree — broker wait, dispatch-pipeline
accumulate/launch, scheduler invoke, matrix build, device dispatch,
plan submit/evaluate/commit, FSM alloc upsert — recorded into a
bounded lock-striped ring buffer (recorder.py). Exposed via
``/v1/agent/trace`` (recent + tail-kept traces), ``/v1/metrics``
(Prometheus exposition of the shared telemetry registry), and the
per-stage latency table in ``server.stats()["trace"]``.

Call sites use the module-level helpers below against the process-wide
recorder; all of them are no-ops when the recorder is disabled and
never raise into the instrumented path.
"""

from .recorder import FlightRecorder  # noqa: F401
from .span import (  # noqa: F401
    ALL_STAGES,
    LIFECYCLE_CORE_STAGES,
    STAGE_ALLOC_UPSERT,
    STAGE_BROKER_WAIT,
    STAGE_DEFRAG_SOLVE,
    STAGE_DEVICE_DISPATCH,
    STAGE_DEVICE_SOLVE,
    STAGE_DEVICE_TRANSFER,
    STAGE_DISPATCH_ACCUMULATE,
    STAGE_DISPATCH_LAUNCH,
    STAGE_GANG_SELECT,
    STAGE_MATRIX_BUILD,
    STAGE_MATRIX_COMPRESS,
    STAGE_MATRIX_UPDATE,
    STAGE_MIGRATE_PLACE,
    STAGE_PLAN_COMMIT,
    STAGE_PLAN_EVALUATE,
    STAGE_PLAN_SUBMIT,
    STAGE_PREEMPT_SELECT,
    STAGE_SCHED_PROCESS,
)

# The process-wide recorder every instrumentation site uses. Module
# level so the disabled check is two attribute loads + a branch (the
# same shape as chaos.enabled).
_recorder = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _recorder


def mark(eval_id: str, trace_id: str = "") -> None:
    _recorder.mark(eval_id, trace_id)


def record_since_mark(eval_id: str, stage: str, ann=None) -> None:
    _recorder.record_since_mark(eval_id, stage, ann)


def record_span(eval_id: str, stage: str, t0: float, t1=None, ann=None,
                trace_id: str = "", create: bool = True) -> None:
    _recorder.record_span(eval_id, stage, t0, t1, ann, trace_id, create)


def annotate_fault(eval_id: str, site: str, seq: int, kind: str) -> None:
    _recorder.annotate_fault(eval_id, site, seq, kind)


def complete(eval_id: str, status: str = "complete") -> None:
    _recorder.complete(eval_id, status)
