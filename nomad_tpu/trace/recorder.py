"""Flight recorder: bounded, lock-striped storage for eval span trees.

Always-on. The record path is called from the broker (under its lock),
from the dispatch pipeline's stage threads, and — via dequeue_many —
from the dispatcher thread itself, so it must NEVER block and NEVER
grow without bound:

- storage is striped: ``hash(eval_id) % N_STRIPES`` picks a stripe;
  each stripe has its own lock, so concurrent writers on different
  evals don't convoy, and every critical section is a handful of dict
  and slot operations (no I/O, no waits, no allocation proportional to
  anything unbounded).
- completed traces go into per-stripe RINGS of preallocated slots —
  drop-oldest by construction (slot index wraps), fixed memory.
- active (incomplete) traces live in a per-stripe dict capped at
  ``ACTIVE_PER_STRIPE``; admission past the cap evicts the oldest
  entry (insertion order) rather than blocking or growing.
- per-trace span storage is a PREALLOCATED slot list (``SPAN_CAP``);
  spans past the cap are counted, not stored.
- per-stage latency histograms are fixed log-bucket arrays
  (utils/metrics.py bucket math) so p50/p95/p99 are computable at any
  time from O(buckets) memory.

The discipline is machine-enforced: ``NTA_RECORD_PATH`` names the
record-path entrypoints, and ntalint's ``record-path-blocking`` rule
(analysis/robustness.py) walks everything reachable from them for
blocking calls and unbounded-growth container mutations.

Tail-keep: completed traces slower than the rolling p99 of end-to-end
duration (once ``TAIL_MIN_SAMPLES`` have been seen) are ALSO copied
into a dedicated tail ring, so the outliers that define the north-star
p99 survive long after the recent-ring has wrapped past them.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..profile import ProfiledLock
from ..utils.metrics import (
    LatencyHist,
    hist_percentile,
)
from .span import make_span, span_to_dict

N_STRIPES = 8
RING_PER_STRIPE = 64     # completed traces kept per stripe (recent)
TAIL_KEEP = 32           # slow traces kept in the tail ring
SPAN_CAP = 32            # spans stored per trace (excess counted)
FAULT_CAP = 8            # chaos fault annotations stored per trace
ACTIVE_PER_STRIPE = 256  # in-flight traces per stripe before eviction
TAIL_MIN_SAMPLES = 64    # e2e samples before tail-keep engages
MAX_STAGES = 64          # distinct stage histograms (instrumentation-bounded)

# ntalint record-path manifest (analysis/robustness.py
# record-path-blocking): every function reachable from these — the
# paths the broker lock and the dispatcher thread run — must contain
# no blocking call and no unbounded container growth.
NTA_RECORD_PATH = (
    "FlightRecorder.mark",
    "FlightRecorder.record_span",
    "FlightRecorder.record_since_mark",
    "FlightRecorder.annotate_fault",
    "FlightRecorder.complete",
)


# The shared fixed-size log-bucket histogram (utils/metrics.py
# LatencyHist; one implementation for the recorder AND the profiler).
_Hist = LatencyHist


class _Trace:
    """One in-flight eval's trace. Span and fault storage are
    preallocated slot lists (fixed memory; see module docstring)."""

    __slots__ = ("eval_id", "trace_id", "origin", "wall_start", "spans",
                 "n_spans", "dropped_spans", "faults", "n_faults",
                 "enqueued_at")

    def __init__(self, eval_id: str, trace_id: str):
        self.eval_id = eval_id
        self.trace_id = trace_id or eval_id
        self.origin = time.monotonic()
        self.wall_start = time.time()
        self.spans = [None] * SPAN_CAP
        self.n_spans = 0
        self.dropped_spans = 0
        self.faults = [None] * FAULT_CAP
        self.n_faults = 0
        self.enqueued_at: Optional[float] = None


class _Stripe:
    __slots__ = ("lock", "active", "ring", "ring_idx", "evicted",
                 "dropped_spans")

    def __init__(self):
        # Profiled (nomad_tpu/profile): the stripes are taken under the
        # broker lock and on every stage thread — their wait histogram
        # is the recorder's own contention self-check.
        self.lock = ProfiledLock("trace.recorder.stripe")
        self.active: Dict[str, _Trace] = {}  # guarded-by: lock
        self.ring: List[Optional[dict]] = [None] * RING_PER_STRIPE
        self.ring_idx = 0  # guarded-by: lock (monotonic; slot = idx % K)
        self.evicted = 0  # guarded-by: lock (active-cap evictions)
        self.dropped_spans = 0  # guarded-by: lock


class FlightRecorder:
    def __init__(self):
        # Plain attribute read on every record call (the bench --no-trace
        # arm and tests flip it); no lock — a racing record lands or
        # not, either is fine.
        self.enabled = True
        self._stripes = [_Stripe() for _ in range(N_STRIPES)]
        self._hist_lock = ProfiledLock("trace.recorder.hist")
        self._hists: Dict[str, _Hist] = {}  # guarded-by: _hist_lock
        self._e2e = _Hist()  # guarded-by: _hist_lock
        self._tail_lock = ProfiledLock("trace.recorder.tail")
        self._tail: List[Optional[dict]] = [None] * TAIL_KEEP
        self._tail_idx = 0  # guarded-by: _tail_lock
        self._completed = 0  # guarded-by: _tail_lock (lifetime count)

    # ----------------------------------------------------- record path

    def _stripe_for(self, eval_id: str) -> _Stripe:
        return self._stripes[hash(eval_id) % N_STRIPES]

    def _entry_locked(self, stripe: _Stripe, eval_id: str,
                      trace_id: str = "") -> _Trace:
        entry = stripe.active.get(eval_id)
        if entry is None:
            if len(stripe.active) >= ACTIVE_PER_STRIPE:
                # Drop-oldest admission: dict preserves insertion
                # order, so the first key is the longest-inactive
                # trace. Never blocks, never grows.
                oldest = next(iter(stripe.active))
                del stripe.active[oldest]
                stripe.evicted += 1
            entry = _Trace(eval_id, trace_id)
            stripe.active[eval_id] = entry
        elif trace_id and entry.trace_id == entry.eval_id:
            entry.trace_id = trace_id
        return entry

    def mark(self, eval_id: str, trace_id: str = "") -> None:
        """Stamp the broker-enqueue instant (consumed by
        record_since_mark at dequeue). Creates the trace on first
        touch."""
        if not self.enabled or not eval_id:
            return
        stripe = self._stripe_for(eval_id)
        with stripe.lock:
            entry = self._entry_locked(stripe, eval_id, trace_id)
            entry.enqueued_at = time.monotonic()

    def record_since_mark(self, eval_id: str, stage: str,
                          ann: Optional[dict] = None) -> None:
        """Record `stage` spanning the last mark() to now. No-op when
        no mark is outstanding (e.g. an eval enqueued before arming)."""
        if not self.enabled or not eval_id:
            return
        now = time.monotonic()
        stripe = self._stripe_for(eval_id)
        dur_ms = None
        with stripe.lock:
            entry = stripe.active.get(eval_id)
            if entry is None or entry.enqueued_at is None:
                return
            t0 = entry.enqueued_at
            entry.enqueued_at = None
            self._store_span_locked(stripe, entry, stage, t0, now, ann)
            dur_ms = (now - t0) * 1000.0
        self._hist_add(stage, dur_ms)

    def record_span(self, eval_id: str, stage: str, t0: float,
                    t1: Optional[float] = None,
                    ann: Optional[dict] = None,
                    trace_id: str = "", create: bool = True) -> None:
        """Record one completed stage: `t0` (and `t1`, default now) are
        time.monotonic() values captured at the call site.

        ``create=False`` records only onto an ALREADY-ACTIVE trace —
        for call sites that also run outside a traced lifecycle (FSM
        applies replay on restart and replicate on followers, where no
        broker ever opened the trace and nothing would ever complete
        it; minting entries there churns the active cap forever and
        pollutes the stage histograms with historical work)."""
        if not self.enabled or not eval_id:
            return
        if t1 is None:
            t1 = time.monotonic()
        stripe = self._stripe_for(eval_id)
        with stripe.lock:
            if create:
                entry = self._entry_locked(stripe, eval_id, trace_id)
            else:
                entry = stripe.active.get(eval_id)
                if entry is None:
                    return
            self._store_span_locked(stripe, entry, stage, t0, t1, ann)
        self._hist_add(stage, (t1 - t0) * 1000.0)

    def _store_span_locked(self, stripe: _Stripe, entry: _Trace,
                           stage: str, t0: float, t1: float,
                           ann: Optional[dict]) -> None:
        if t0 < entry.origin:
            # A span captured before the trace's first touch (e.g. the
            # call site clocked t0, then created the trace): the trace
            # starts at its earliest evidence, so e2e covers stage one
            # and exported offsets stay non-negative.
            entry.wall_start -= entry.origin - t0
            entry.origin = t0
        n = entry.n_spans
        if n < SPAN_CAP:
            entry.spans[n] = make_span(stage, t0, t1, ann)
            entry.n_spans = n + 1
        else:
            entry.dropped_spans += 1
            stripe.dropped_spans += 1

    def annotate_fault(self, eval_id: str, site: str, seq: int,
                       kind: str) -> None:
        """Attach a chaos firing (site, per-site call ordinal, kind) to
        the eval's trace; at completion it lands on the span whose
        interval covers the firing time."""
        if not self.enabled or not eval_id:
            return
        now = time.monotonic()
        stripe = self._stripe_for(eval_id)
        with stripe.lock:
            entry = stripe.active.get(eval_id)
            if entry is None:
                return
            n = entry.n_faults
            if n < FAULT_CAP:
                entry.faults[n] = (now, site, seq, kind)
                entry.n_faults = n + 1

    def observe_stage(self, stage: str, ms: float) -> None:
        """Public per-stage histogram feed for non-eval pipelines (the
        read plane's `read.park`/`read.serve` stages): lands in
        stage_stats() without opening a trace and without touching the
        e2e histogram — e2e_p99() feeds the admission pressure monitor
        and must keep measuring the eval lifecycle only."""
        if not self.enabled:
            return
        self._hist_add(stage, ms)

    def _hist_add(self, stage: str, ms: Optional[float]) -> None:
        if ms is None:
            return
        with self._hist_lock:
            h = self._hists.get(stage)
            if h is None:
                if len(self._hists) >= MAX_STAGES:
                    return
                h = _Hist()
                self._hists[stage] = h
            h.observe(ms)

    def complete(self, eval_id: str, status: str = "complete") -> None:
        """Close the eval's trace: finalize the span tree, fold its e2e
        duration into the rolling histogram, then publish into the
        stripe's recent ring (and the tail ring when it lands past the
        p99). The dict is fully built — tail_kept flag included —
        BEFORE it becomes reachable by readers, so a published trace is
        immutable (a reader serializing it can never race a late
        mutation)."""
        if not self.enabled or not eval_id:
            return
        now = time.monotonic()
        stripe = self._stripe_for(eval_id)
        with stripe.lock:
            entry = stripe.active.pop(eval_id, None)
            if entry is None:
                return
            done = self._finalize_locked(entry, now, status)
        dur_ms = done["duration_ms"]
        keep_tail = False
        with self._hist_lock:
            # p99 against the distribution SO FAR (excluding this
            # sample): an outlier compared against a p99 that already
            # contains it would sit inside its own bucket's bound and
            # never qualify.
            if self._e2e.count >= TAIL_MIN_SAMPLES:
                p99 = hist_percentile(
                    self._e2e.buckets, self._e2e.count, 0.99)
                keep_tail = dur_ms >= p99
            self._e2e.observe(dur_ms)
        if keep_tail:
            done["tail_kept"] = True
        with stripe.lock:
            stripe.ring[stripe.ring_idx % RING_PER_STRIPE] = done
            stripe.ring_idx += 1
        with self._tail_lock:
            self._completed += 1
            if keep_tail:
                self._tail[self._tail_idx % TAIL_KEEP] = done
                self._tail_idx += 1

    def _finalize_locked(self, entry: _Trace, now: float,
                         status: str) -> dict:
        """Materialize one immutable dict for the completed trace. Runs
        under the stripe lock but does bounded work only (SPAN_CAP x
        FAULT_CAP)."""
        spans = [entry.spans[i] for i in range(entry.n_spans)]
        spans.sort(key=lambda s: (s[1], -s[2]))
        faults = [entry.faults[i] for i in range(entry.n_faults)]
        origin = entry.origin
        end = now
        for s in spans:
            if s[2] > end:  # completion raced a span's tail
                end = s[2]
        # Each fault attaches to the SMALLEST covering span — the most
        # specific stage the fault fired inside (outer spans cover it
        # trivially and would smear the attribution).
        span_faults: List[list] = [[] for _ in spans]
        covered_flags = [False] * len(faults)
        for fi, f in enumerate(faults):
            best = None
            best_len = None
            for si, s in enumerate(spans):
                if s[1] <= f[0] <= s[2]:
                    slen = s[2] - s[1]
                    if best is None or slen < best_len:
                        best, best_len = si, slen
            if best is not None:
                span_faults[best].append(f)
                covered_flags[fi] = True
        dicts = [
            span_to_dict(s, origin, faults=span_faults[i])
            for i, s in enumerate(spans)
        ]
        # Parent = the smallest strictly-enclosing span: the flat list
        # reads back as a tree (scheduler.process contains
        # matrix.build / device.dispatch / plan.submit, which contains
        # plan.evaluate / plan.commit / fsm.alloc_upsert).
        for i, s in enumerate(spans):
            parent = None
            parent_len = None
            for j, p in enumerate(spans):
                if j == i:
                    continue
                if p[1] <= s[1] and s[2] <= p[2]:
                    plen = p[2] - p[1]
                    if (parent is None or plen < parent_len
                            or (plen == parent_len and j < i)):
                        parent, parent_len = j, plen
            dicts[i]["parent"] = (spans[parent][0]
                                  if parent is not None else None)
        uncovered = [f for fi, f in enumerate(faults)
                     if not covered_flags[fi]]
        out = {
            "eval_id": entry.eval_id,
            "trace_id": entry.trace_id,
            "status": status,
            "start_unix": round(entry.wall_start, 6),
            "duration_ms": round((end - origin) * 1000.0, 3),
            "spans": dicts,
            "dropped_spans": entry.dropped_spans,
        }
        if uncovered:
            out["unattributed_faults"] = [
                {"site": site, "ordinal": seq, "kind": kind}
                for (_t, site, seq, kind) in uncovered
            ]
        return out

    # ------------------------------------------------------ read side

    def traces(self, limit: int = 50) -> List[dict]:
        """Most recent completed traces, newest first."""
        out: List[dict] = []
        for stripe in self._stripes:
            with stripe.lock:
                n = min(stripe.ring_idx, RING_PER_STRIPE)
                for k in range(n):
                    slot = stripe.ring[(stripe.ring_idx - 1 - k)
                                       % RING_PER_STRIPE]
                    if slot is not None:
                        out.append(slot)
        out.sort(key=lambda t: t["start_unix"] + t["duration_ms"] / 1000.0,
                 reverse=True)
        return out[:max(0, limit)]

    def trace_for(self, eval_id: str) -> Optional[dict]:
        """The completed trace for one eval, if still in a ring."""
        stripe = self._stripe_for(eval_id)
        with stripe.lock:
            for slot in stripe.ring:
                if slot is not None and slot["eval_id"] == eval_id:
                    return slot
        return None

    def tail_traces(self) -> List[dict]:
        """Traces kept for landing past the rolling e2e p99, newest
        first."""
        with self._tail_lock:
            n = min(self._tail_idx, TAIL_KEEP)
            return [self._tail[(self._tail_idx - 1 - k) % TAIL_KEEP]
                    for k in range(n)]

    def e2e_p99(self) -> float:
        """Rolling end-to-end p99 in ms (0.0 before any completions).
        Cheap single-histogram read for the pressure monitor
        (nomad_tpu/admission) — stage_stats() walks every stage."""
        with self._hist_lock:
            if not self._e2e.count:
                return 0.0
            return hist_percentile(
                self._e2e.buckets, self._e2e.count, 0.99)

    def stage_buckets(self, stage: str):
        """(count, bucket-list copy) of one stage's lifetime histogram,
        or None before any sample. The rolling-window consumers
        (kernels/quality.py's per-interval queueing gauge) snapshot
        this at window reset and percentile over the bucket DELTA —
        lifetime exposition stays monotonic for Prometheus while the
        window reads only what landed since the reset."""
        with self._hist_lock:
            h = self._hists.get(stage)
            if h is None or not h.count:
                return None
            return h.count, list(h.buckets)

    def stage_stats(self) -> Dict[str, dict]:
        """Per-stage latency table: count/mean/max and log-bucket
        p50/p95/p99, all in milliseconds."""
        with self._hist_lock:
            items = [(name, h.count, h.total, h.max, list(h.buckets))
                     for name, h in self._hists.items()]
            items.append(("e2e", self._e2e.count, self._e2e.total,
                          self._e2e.max, list(self._e2e.buckets)))
        out: Dict[str, dict] = {}
        for name, count, total, mx, buckets in items:
            if not count:
                continue
            out[name] = {
                "count": count,
                "mean_ms": round(total / count, 3),
                "max_ms": round(mx, 3),
                "p50_ms": round(hist_percentile(buckets, count, 0.50), 3),
                "p95_ms": round(hist_percentile(buckets, count, 0.95), 3),
                "p99_ms": round(hist_percentile(buckets, count, 0.99), 3),
            }
        return out

    def stats(self) -> dict:
        active = evicted = dropped = 0
        for stripe in self._stripes:
            with stripe.lock:
                active += len(stripe.active)
                evicted += stripe.evicted
                dropped += stripe.dropped_spans
        with self._tail_lock:
            completed = self._completed
            tail_kept = min(self._tail_idx, TAIL_KEEP)
        return {
            "enabled": self.enabled,
            "active": active,
            "completed": completed,
            "evicted_active": evicted,
            "dropped_spans": dropped,
            "tail_kept": tail_kept,
            "ring_capacity": N_STRIPES * RING_PER_STRIPE,
        }

    # -------------------------------------------------------- control

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)

    def reset(self) -> None:
        """Drop all stored traces and histograms (bench A/B arms and
        test isolation; not part of the record path)."""
        for stripe in self._stripes:
            with stripe.lock:
                stripe.active.clear()
                stripe.ring = [None] * RING_PER_STRIPE
                stripe.ring_idx = 0
                stripe.evicted = 0
                stripe.dropped_spans = 0
        with self._hist_lock:
            self._hists = {}
            self._e2e = _Hist()
        with self._tail_lock:
            self._tail = [None] * TAIL_KEEP
            self._tail_idx = 0
            self._completed = 0
