"""Span model for eval-lifecycle tracing.

A *span* is one named stage of one evaluation's life, with monotonic
start/end timestamps and optional annotations. The full set of stage
names an eval can produce is enumerated here so the e2e completeness
test (and the README table) have one source of truth.

Spans are stored as plain immutable tuples — ``(name, t0, t1, ann)`` —
so a reader racing the flight recorder can never observe a torn span:
the tuple is fully constructed before it is published into a ring slot.
"""

from __future__ import annotations

from typing import Optional, Tuple

# ------------------------------------------------------- stage names
#
# Ordered roughly by lifecycle position. Not every eval produces every
# stage: host-path evals skip the dense stages, placement-less evals
# (job stop) skip fsm.alloc_upsert, and dispatch.* only appear when the
# central pipeline handles the eval.

STAGE_BROKER_WAIT = "broker.wait"          # enqueue -> dequeue
STAGE_DISPATCH_ACCUMULATE = "dispatch.accumulate"  # pipeline admit -> batch cut
STAGE_DISPATCH_LAUNCH = "dispatch.launch"  # launch prologue (catch-up + snapshot)
STAGE_SCHED_PROCESS = "scheduler.process"  # scheduler invoke, end to end
STAGE_MATRIX_BUILD = "matrix.build"        # ClusterMatrix + ask construction
STAGE_MATRIX_UPDATE = "matrix.update"      # incremental delta vs full rebuild
STAGE_MATRIX_COMPRESS = "matrix.compress"  # signature-class interning
#   (models/classes.py; ann: classes C, nodes N, escaped, ratio N/C)
STAGE_DEVICE_TRANSFER = "device.transfer"  # base prefetch host->device
STAGE_DEVICE_DISPATCH = "device.dispatch"  # batcher.place round-trip
STAGE_DEVICE_SOLVE = "device.solve"        # the jitted placement-kernel
#   solve inside the dispatch (issue + device sync, kernel-annotated) —
#   device.dispatch minus batch-wait and host stacking
STAGE_MIGRATE_PLACE = "migrate.place"      # drain-displaced allocs staged
#   for re-placement under the migration budget (ann: migrations
#   claimed this wave, deferred to the follow-up eval)
STAGE_PREEMPT_SELECT = "preempt.select"    # dense victim-selection +
#   placement pass (ops/preempt.py; ann: asks, candidate victims)
STAGE_GANG_SELECT = "gang.select"          # all-K gang slice selection
#   + member assignment (ops/gang.py; ann: members, mode,
#   slice group, host_fallback) — one span per gang dispatch
#   (nomad_tpu/gang)
STAGE_DEFRAG_SOLVE = "defrag.solve"        # one defrag-loop round's
#   warm-started global relaxation solve + move extraction
#   (nomad_tpu/defrag; ann: movable, moves, gain, warm, solve_ms) —
#   recorded on its own per-round trace, not an eval's
STAGE_PLAN_SUBMIT = "plan.submit"          # plan queue wait + commit (worker view)
STAGE_PLAN_EVALUATE = "plan.evaluate"      # applier per-node verification
STAGE_PLAN_COMMIT = "plan.commit"          # raft apply of the accepted plan
STAGE_ALLOC_UPSERT = "fsm.alloc_upsert"    # state-store alloc write

ALL_STAGES = (
    STAGE_BROKER_WAIT,
    STAGE_DISPATCH_ACCUMULATE,
    STAGE_DISPATCH_LAUNCH,
    STAGE_SCHED_PROCESS,
    STAGE_MATRIX_BUILD,
    STAGE_MATRIX_UPDATE,
    STAGE_MATRIX_COMPRESS,
    STAGE_DEVICE_TRANSFER,
    STAGE_DEVICE_DISPATCH,
    STAGE_DEVICE_SOLVE,
    STAGE_MIGRATE_PLACE,
    STAGE_PREEMPT_SELECT,
    STAGE_GANG_SELECT,
    STAGE_DEFRAG_SOLVE,
    STAGE_PLAN_SUBMIT,
    STAGE_PLAN_EVALUATE,
    STAGE_PLAN_COMMIT,
    STAGE_ALLOC_UPSERT,
)

# The stages every PLACING eval must produce regardless of path (the
# e2e completeness contract; dense/dispatch stages are path-dependent).
LIFECYCLE_CORE_STAGES = (
    STAGE_BROKER_WAIT,
    STAGE_SCHED_PROCESS,
    STAGE_PLAN_SUBMIT,
    STAGE_PLAN_EVALUATE,
    STAGE_PLAN_COMMIT,
    STAGE_ALLOC_UPSERT,
)

# Span tuple layout: (stage_name, t0_monotonic, t1_monotonic, ann)
# where ann is None or a small read-only dict built by the caller.
Span = Tuple[str, float, float, Optional[dict]]


def make_span(name: str, t0: float, t1: float,
              ann: Optional[dict] = None) -> Span:
    if t1 < t0:  # clock users pass (start, now); never invert
        t1 = t0
    return (name, t0, t1, ann)


def span_to_dict(span: Span, origin: float, faults=()) -> dict:
    """JSON shape for one span. `origin` is the trace's monotonic start
    so exported offsets are relative (monotonic absolutes are
    process-meaningless). `faults` are the chaos (site, ordinal, kind)
    triples whose firing time fell inside this span."""
    name, t0, t1, ann = span
    out = {
        "name": name,
        "start_ms": round((t0 - origin) * 1000.0, 3),
        "end_ms": round((t1 - origin) * 1000.0, 3),
        "duration_ms": round((t1 - t0) * 1000.0, 3),
    }
    if ann:
        out["annotations"] = dict(ann)
    if faults:
        out["faults"] = [
            {"site": site, "ordinal": seq, "kind": kind}
            for (_t, site, seq, kind) in faults
        ]
    return out
