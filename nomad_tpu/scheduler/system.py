"""SystemScheduler: run the job on every ready, feasible node.

Reference: scheduler/system_sched.go:23 (SystemScheduler), :55 (Process),
:87 (process), :179 (computeJobAllocs), :255 (computePlacements).
"""

from __future__ import annotations

import logging
import random
from typing import Dict, List, Optional

from ..structs import (
    AllocMetric,
    Allocation,
    Evaluation,
    Job,
    Plan,
    PlanResult,
    Resources,
    consts,
    filter_terminal_allocs,
)
from ..utils.ids import generate_uuid
from .context import EvalContext
from .stack import SystemStack
from .util import (
    ALLOC_LOST,
    ALLOC_NOT_NEEDED,
    ALLOC_UPDATING,
    AllocTuple,
    SetStatusError,
    _append_update_with_client,
    adjust_queued_allocations,
    desired_updates,
    diff_system_allocs,
    evict_and_place,
    inplace_update,
    progress_made,
    ready_nodes_in_dcs,
    retry_max,
    set_status,
    tainted_nodes,
    update_non_terminal_allocs_to_lost,
)

MAX_SYSTEM_SCHEDULE_ATTEMPTS = 5


class SystemScheduler:
    def __init__(self, logger, state, planner, rng: Optional[random.Random] = None):
        self.logger = logger or logging.getLogger("nomad_tpu.scheduler")
        self.state = state
        self.planner = planner
        self.rng = rng or random.Random()

        self.eval: Optional[Evaluation] = None
        self.job: Optional[Job] = None
        self.plan: Optional[Plan] = None
        self.plan_result: Optional[PlanResult] = None
        self.ctx: Optional[EvalContext] = None
        self.stack: Optional[SystemStack] = None
        self.nodes: List = []
        self.nodes_by_dc: Dict[str, int] = {}

        self.limit_reached = False
        self.next_eval: Optional[Evaluation] = None
        self.failed_tg_allocs: Optional[Dict[str, AllocMetric]] = None
        self.queued_allocs: Optional[Dict[str, int]] = None

    def process_eval(self, eval: Evaluation) -> None:
        self.eval = eval

        if eval.triggered_by not in (
            consts.EVAL_TRIGGER_JOB_REGISTER,
            consts.EVAL_TRIGGER_NODE_UPDATE,
            consts.EVAL_TRIGGER_JOB_DEREGISTER,
            consts.EVAL_TRIGGER_ROLLING_UPDATE,
        ):
            desc = f"scheduler cannot handle '{eval.triggered_by}' evaluation reason"
            set_status(
                self.logger, self.planner, self.eval, self.next_eval, None,
                self.failed_tg_allocs, consts.EVAL_STATUS_FAILED, desc,
                self.queued_allocs,
            )
            return

        try:
            retry_max(
                MAX_SYSTEM_SCHEDULE_ATTEMPTS,
                self._process,
                lambda: progress_made(self.plan_result),
            )
        except SetStatusError as err:
            set_status(
                self.logger, self.planner, self.eval, self.next_eval, None,
                self.failed_tg_allocs, err.eval_status, str(err),
                self.queued_allocs,
            )
            return

        set_status(
            self.logger, self.planner, self.eval, self.next_eval, None,
            self.failed_tg_allocs, consts.EVAL_STATUS_COMPLETE, "",
            self.queued_allocs,
        )

    def _process(self) -> bool:
        self.job = self.state.job_by_id(self.eval.job_id)
        self.queued_allocs = {}

        if self.job is not None:
            self.nodes, self.nodes_by_dc = ready_nodes_in_dcs(
                self.state, self.job.datacenters
            )

        self.plan = self.eval.make_plan(self.job)
        self.failed_tg_allocs = None
        self.ctx = EvalContext(self.state, self.plan, self.logger, rng=self.rng)
        self.stack = SystemStack(self.ctx)
        if self.job is not None:
            self.stack.set_job(self.job)

        self._compute_job_allocs()

        if self.plan.is_no_op() and not self.eval.annotate_plan:
            return True

        if self.limit_reached and self.next_eval is None:
            self.next_eval = self.eval.next_rolling_eval(self.job.update.stagger)
            self.planner.create_eval(self.next_eval)

        result, new_state = self.planner.submit_plan(self.plan)
        self.plan_result = result

        adjust_queued_allocations(self.logger, result, self.queued_allocs)

        if new_state is not None:
            self.state = new_state
            return False

        full_commit, expected, actual = result.full_commit(self.plan)
        if not full_commit:
            self.logger.debug(
                "eval %s: attempted %d placements, %d placed",
                self.eval.id, expected, actual,
            )
            return False

        return True

    def _diff_system(self, tainted, allocs, terminal_allocs):
        """Diff hook. Returns (DiffResult, prefiltered) where
        prefiltered maps tg name -> [count, first_node] of place
        candidates a subclass already ruled out by constraint (the
        dense scheduler gates the place set up front; here nothing is
        pre-filtered — the placement loop filters one at a time)."""
        return diff_system_allocs(
            self.job, self.nodes, tainted, allocs, terminal_allocs), {}

    def _compute_job_allocs(self) -> None:
        allocs = self.state.allocs_by_job(self.eval.job_id)
        tainted = tainted_nodes(self.state, allocs)

        update_non_terminal_allocs_to_lost(self.plan, tainted, allocs)

        allocs, terminal_allocs = filter_terminal_allocs(allocs)

        diff, prefiltered = self._diff_system(
            tainted, allocs, terminal_allocs)
        self.logger.debug("eval %s job %s: %s", self.eval.id, self.eval.job_id, diff)

        for e in diff.stop:
            self.plan.append_update(e.alloc, consts.ALLOC_DESIRED_STOP, ALLOC_NOT_NEEDED)

        for e in diff.lost:
            _append_update_with_client(
                self.plan, e.alloc, consts.ALLOC_DESIRED_STOP, ALLOC_LOST,
                consts.ALLOC_CLIENT_LOST,
            )

        destructive, inplace = inplace_update(
            self.ctx, self.eval, self.job, self.stack, diff.update
        )
        diff.update = destructive

        if self.eval.annotate_plan:
            from ..structs import PlanAnnotations

            self.plan.annotations = PlanAnnotations(
                desired_tg_updates=desired_updates(diff, inplace, destructive)
            )

        limit = [len(diff.update)]
        if self.job is not None and self.job.update is not None and self.job.update.rolling():
            limit = [self.job.update.max_parallel]

        self.limit_reached = evict_and_place(
            self.ctx, diff, diff.update, ALLOC_UPDATING, limit
        )

        # Zero every TG's queue only when there were NO candidates at
        # all: a fully-prefiltered eval must instead look like "every
        # placement was filtered" (same records the host loop leaves).
        if not diff.place and not prefiltered:
            if self.job is not None:
                for tg in self.job.task_groups:
                    self.queued_allocs[tg.name] = 0
            return

        for tup in diff.place:
            self.queued_allocs[tup.task_group.name] = (
                self.queued_allocs.get(tup.task_group.name, 0) + 1
            )

        if diff.place:
            self._compute_placements(diff.place)
        if prefiltered:
            self._merge_prefiltered(prefiltered)

    def _merge_prefiltered(self, prefiltered) -> None:
        """Fold diff-gated (constraint-infeasible) candidates into the
        same records the placement loop produces by filtering them one
        at a time: the queued key exists with its feasible-only net
        value, and failed_tg_allocs carries the filtered tally."""
        for name, (count, first_node) in prefiltered.items():
            if count <= 0:
                continue
            self.queued_allocs.setdefault(name, 0)
            if self.failed_tg_allocs is None:
                self.failed_tg_allocs = {}
            existing = self.failed_tg_allocs.get(name)
            if existing is not None:
                existing.coalesced_failures += count
                continue
            metrics = AllocMetric()
            metrics.nodes_available = self.nodes_by_dc
            metrics.evaluate_node()
            metrics.filter_node(first_node, "constraint")
            metrics.coalesced_failures = count - 1
            self.failed_tg_allocs[name] = metrics

    def _compute_placements(self, place: List[AllocTuple]) -> None:
        node_by_id = {n.id: n for n in self.nodes}

        for missing in place:
            node = node_by_id.get(missing.alloc.node_id)
            if node is None:
                raise RuntimeError(f"could not find node {missing.alloc.node_id!r}")

            self.stack.set_nodes([node])
            option, _ = self.stack.select(missing.task_group)

            if option is None:
                # A constraint mismatch on this node means the alloc was
                # never really "queued" there; undo the optimistic count.
                if self.ctx.metrics.nodes_filtered > 0:
                    self.queued_allocs[missing.task_group.name] -= 1
                    if (
                        self.eval.annotate_plan
                        and self.plan.annotations is not None
                        and missing.task_group.name
                        in self.plan.annotations.desired_tg_updates
                    ):
                        self.plan.annotations.desired_tg_updates[
                            missing.task_group.name
                        ].place -= 1

                if self.failed_tg_allocs and missing.task_group.name in self.failed_tg_allocs:
                    self.failed_tg_allocs[
                        missing.task_group.name
                    ].coalesced_failures += 1
                    continue

            self.ctx.metrics.nodes_available = self.nodes_by_dc

            if option is not None:
                alloc = Allocation(
                    id=generate_uuid(),
                    eval_id=self.eval.id,
                    name=missing.name,
                    job_id=self.job.id,
                    task_group=missing.task_group.name,
                    metrics=self.ctx.metrics,
                    node_id=option.node.id,
                    task_resources=option.task_resources,
                    desired_status=consts.ALLOC_DESIRED_RUN,
                    client_status=consts.ALLOC_CLIENT_PENDING,
                    shared_resources=Resources(
                        disk_mb=missing.task_group.ephemeral_disk.size_mb
                    ),
                )
                if missing.alloc is not None and missing.alloc.id:
                    alloc.previous_allocation = missing.alloc.id
                self.plan.append_alloc(alloc)
            else:
                if self.failed_tg_allocs is None:
                    self.failed_tg_allocs = {}
                self.failed_tg_allocs[missing.task_group.name] = self.ctx.metrics
