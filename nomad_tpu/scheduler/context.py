"""Evaluation context: state access, plan, metrics, caches, and the
computed-class eligibility memo.

Reference: scheduler/context.go:12 (Context), :64 (EvalContext),
:108 (ProposedAllocs), :172 (EvalEligibility).
"""

from __future__ import annotations

import logging
import random
from typing import Dict, List, Optional

from ..structs import (
    AllocMetric,
    Allocation,
    Job,
    Plan,
    escaped_constraints,
    remove_allocs,
)

# Computed-class feasibility states (context.go:149-168)
CLASS_UNKNOWN = 0
CLASS_INELIGIBLE = 1
CLASS_ELIGIBLE = 2
CLASS_ESCAPED = 3


class EvalEligibility:
    """Per-evaluation memo of job/task-group feasibility per computed node
    class. Lets the feasibility wrapper skip constraint checks for every
    node in an already-decided class."""

    def __init__(self):
        self.job: Dict[str, int] = {}
        self.job_escaped = False
        self.task_groups: Dict[str, Dict[str, int]] = {}
        self.tg_escaped: Dict[str, bool] = {}

    def set_job(self, job: Job) -> None:
        self.job_escaped = len(escaped_constraints(job.constraints)) != 0
        for tg in job.task_groups:
            constraints = list(tg.constraints)
            for task in tg.tasks:
                constraints.extend(task.constraints)
            self.tg_escaped[tg.name] = len(escaped_constraints(constraints)) != 0

    def has_escaped(self) -> bool:
        return self.job_escaped or any(self.tg_escaped.values())

    def get_classes(self) -> Dict[str, bool]:
        elig: Dict[str, bool] = {}
        for cls, feas in self.job.items():
            if feas == CLASS_ELIGIBLE:
                elig[cls] = True
            elif feas == CLASS_INELIGIBLE:
                elig[cls] = False
        for classes in self.task_groups.values():
            for cls, feas in classes.items():
                if feas == CLASS_ELIGIBLE:
                    elig[cls] = True
                elif feas == CLASS_INELIGIBLE:
                    # Don't let one TG mark a class ineligible when another
                    # TG found it eligible.
                    elig.setdefault(cls, False)
        return elig

    def job_status(self, cls: str) -> int:
        if self.job_escaped or not cls:
            return CLASS_ESCAPED
        return self.job.get(cls, CLASS_UNKNOWN)

    def set_job_eligibility(self, eligible: bool, cls: str) -> None:
        self.job[cls] = CLASS_ELIGIBLE if eligible else CLASS_INELIGIBLE

    def task_group_status(self, tg: str, cls: str) -> int:
        if not cls:
            return CLASS_ESCAPED
        if self.tg_escaped.get(tg):
            return CLASS_ESCAPED
        return self.task_groups.get(tg, {}).get(cls, CLASS_UNKNOWN)

    def set_task_group_eligibility(self, eligible: bool, tg: str, cls: str) -> None:
        self.task_groups.setdefault(tg, {})[cls] = (
            CLASS_ELIGIBLE if eligible else CLASS_INELIGIBLE
        )


class EvalContext:
    """Context carried through one evaluation's placement pipeline."""

    def __init__(self, state, plan: Plan, logger: Optional[logging.Logger] = None,
                 rng: Optional[random.Random] = None):
        self.state = state
        self.plan = plan
        self.logger = logger or logging.getLogger("nomad_tpu.scheduler")
        self.metrics = AllocMetric()
        self.eligibility = EvalEligibility()
        self.regexp_cache: Dict[str, object] = {}
        self.constraint_cache: Dict[str, object] = {}
        self.rng = rng or random.Random()

    def reset(self) -> None:
        """Called after each placement: metrics are per-selection."""
        self.metrics = AllocMetric()

    def proposed_allocs(self, node_id: str) -> List[Allocation]:
        """Allocations that would exist on the node if the current plan
        commits (shared semantics in util.proposed_allocs_for_node)."""
        from .util import proposed_allocs_for_node

        return proposed_allocs_for_node(self.state, self.plan, node_id)
