"""TPU/dense scheduler factories ("service-tpu", "batch-tpu",
"system-tpu").

The north-star design (BASELINE.json): identical control flow to the
host schedulers — same reconciliation, same blocked-eval/rolling
semantics, same plan shape — but computePlacements runs as one dense
program instead of per-node iterators. In-place updates and
sticky-disk preferences stay host-side (SURVEY.md section 7 hard
parts); exact port numbers are assigned host-side on the chosen nodes;
the plan applier re-verifies every node so kernel approximations cost
retries, not correctness.

The generic path searches (masked argmax on the TPU); the system path
(system_sched.go) pins every placement to its node, so its dense
reformulation is pure vectorized feasibility+fit over the pinned rows
— no search, one ClusterMatrix build instead of a per-node iterator
stack per placement.
"""

from __future__ import annotations

import logging
import random
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import trace
from ..structs import (
    Allocation,
    AllocMetric,
    NetworkIndex,
    NetworkResource,
    Resources,
    consts,
)
from ..utils.ids import generate_uuid
from .generic import GenericScheduler
from .system import SystemScheduler
from .util import AllocTuple


def _offer_networks(rng, missing: AllocTuple, node, net_indexes, matrix):
    """Exact per-task network offers on a dense-path-chosen node.
    Returns {task: Resources} or None if a port can't be assigned."""
    idx = net_indexes.get(node.id)
    if idx is None:
        idx = NetworkIndex()
        idx.set_node(node)
        idx.add_allocs(matrix._proposed_allocs(node.id))
        net_indexes[node.id] = idx

    task_resources: Dict[str, Resources] = {}
    for task in missing.task_group.tasks:
        resources = task.resources.copy()
        if resources.networks:
            ask = resources.networks[0]
            offer, err = idx.assign_network(ask, rng)
            if offer is None:
                # Drop the partially-updated index; it is rebuilt
                # from the plan on next use.
                net_indexes.pop(node.id, None)
                return None
            idx.add_reserved(offer)
            resources.networks = [offer]
        task_resources[task.name] = resources
    return task_resources


def build_placement_config(batch: bool, pre_resolve: bool, kernel,
                           placements, ask_arrays):
    """The PlacementConfig both dense drivers — BatchedTPUScheduler's
    per-eval place() and the scheduler executive's cohort dispatch
    (server/executive.py) — hand the batcher. Factored so the STATIC
    fields that key compiled device programs (penalty, pre_resolve,
    uniform_dh, kernel) can never drift between the two paths: a drift
    would mint a second program per shape bucket (a recompile storm)
    and break executive-vs-worker placement parity."""
    from ..kernels import active_kernel
    from ..ops.binpack import PlacementConfig, uniform_dh_flag
    from .stack import (
        BATCH_JOB_ANTI_AFFINITY_PENALTY,
        SERVICE_JOB_ANTI_AFFINITY_PENALTY,
    )

    kernel = kernel or active_kernel()
    return PlacementConfig(
        anti_affinity_penalty=(
            BATCH_JOB_ANTI_AFFINITY_PENALTY if batch
            else SERVICE_JOB_ANTI_AFFINITY_PENALTY),
        pre_resolve=pre_resolve,
        # Uniform distinct-hosts fast path: one TG scaled to count=K
        # under distinct-hosts (the storm shape) collapses the K-step
        # scan to one scoring pass + top_k (ops/binpack.py). Static, so
        # mixed batches never share a program with uniform ones.
        # Greedy-only: non-default kernels run their own joint solve.
        uniform_dh=(kernel == "greedy" and uniform_dh_flag(
            placements, ask_arrays[5], ask_arrays[6])),
        kernel=kernel,
    )


def _build_allocation(sched, missing: AllocTuple, node, task_resources,
                      metrics) -> Allocation:
    """The Allocation literal both dense schedulers append to the plan
    (shared so the field set can't drift between them)."""
    alloc = Allocation(
        id=generate_uuid(),
        eval_id=sched.eval.id,
        name=missing.name,
        job_id=sched.job.id,
        task_group=missing.task_group.name,
        metrics=metrics,
        node_id=node.id,
        task_resources=task_resources,
        desired_status=consts.ALLOC_DESIRED_RUN,
        client_status=consts.ALLOC_CLIENT_PENDING,
        shared_resources=Resources(
            disk_mb=missing.task_group.ephemeral_disk.size_mb
        ),
    )
    if missing.alloc is not None and missing.alloc.id:
        alloc.previous_allocation = missing.alloc.id
    return alloc


class BatchedTPUScheduler(GenericScheduler):
    """GenericScheduler whose bulk placement loop runs on the TPU.

    `kernel` pins the placement kernel (nomad_tpu/kernels) for this
    scheduler instance — the `service-<kernel>-tpu` factory variants
    set it; None defers to the process-global active kernel
    (kernels.configure, fed by ServerConfig.placement_kernel)."""

    def __init__(self, logger, state, planner, batch=False, rng=None,
                 kernel: Optional[str] = None):
        super().__init__(logger, state, planner, batch=batch, rng=rng)
        self.kernel = kernel

    def _inplace_update(self, updates):
        """Batched host-side in-place routing (scheduler/util.py
        inplace_update_batched): compatible tweaks rewrite allocs with
        zero evictions and zero device dispatches; only destructive
        updates flow on to the dense placement path."""
        from .util import inplace_update_batched

        return inplace_update_batched(
            self.ctx, self.eval, self.job, self.stack, updates)

    def _compute_placements(self, place: List[AllocTuple]) -> None:
        from ..models.matrix import ClusterMatrix
        from ..ops.binpack import host_prng_key, make_asks
        from .batcher import get_batcher

        # Gang task groups (nomad_tpu/gang) take the dense all-K pass:
        # one gang = one dispatch of ops/gang.py's program, atomically
        # staged on the plan's gang leg.
        gang_sets, place = self._split_gang_placements(place)
        for tg, tuples in gang_sets:
            self._place_gang_dense(tg, tuples)
        if not place:
            if gang_sets:
                self._repay_cohort()
            return
        # Sticky-disk placements keep the host path (they pin to one node).
        sticky: List[AllocTuple] = []
        bulk: List[AllocTuple] = []
        for missing in place:
            if self._find_preferred_node(missing) is not None:
                sticky.append(missing)
            else:
                bulk.append(missing)
        if sticky:
            super()._compute_placements(sticky)
        # A TG that already failed (e.g. in the sticky host path) only
        # coalesces from here on — same invariant as the host loop
        # (generic_sched.go:444-447).
        remaining: List[AllocTuple] = []
        for missing in bulk:
            if self.failed_tg_allocs and missing.task_group.name in self.failed_tg_allocs:
                self.failed_tg_allocs[missing.task_group.name].coalesced_failures += 1
            else:
                remaining.append(missing)
        bulk = remaining
        if not bulk:
            self._repay_cohort()
            return
        from ..migrate import preemption_eligible

        may_preempt = preemption_eligible(self.eval.priority)
        if len(bulk) <= 3 and not may_preempt:
            # Too few placements to amortize a dispatch — typical for
            # the retry after a partially-rejected plan (1-3 conflicted
            # allocs replanned on a FRESH snapshot, so the dense path
            # would also pay a new matrix + base token). The host
            # iterators place a handful in low-ms with identical
            # semantics. A preemption-eligible eval stays dense at ANY
            # size: the host iterators cannot evict, and the retry
            # after a partially-committed preemption plan is exactly a
            # 1-3 ask replan that still needs the eviction leg.
            self._repay_cohort()
            super()._compute_placements(bulk)
            return

        # Device-path circuit breaker (nomad_tpu/admission): the
        # consuming gate, checked BEFORE the matrix build — an open
        # breaker means the device path is sick, so paying the
        # ClusterMatrix + build_asks cost only to discard them would
        # burn leader CPU per eval for nothing. An open breaker (or a
        # busy half-open probe slot) routes this eval to the host
        # iterators WITHOUT paying the failure latency the per-eval
        # fallback below would — the whole point of the breaker is
        # that N consecutive failures become one routing decision,
        # not N timeouts.
        from ..admission import get_breaker
        from ..chaos import chaos
        from ..utils import metrics

        breaker = get_breaker()
        if not breaker.acquire():
            self._repay_cohort()
            metrics.incr_counter(
                ("scheduler", "breaker_rejected"), len(bulk))
            trace.record_span(
                self.eval.id, trace.STAGE_DEVICE_DISPATCH,
                time.monotonic(),
                ann={"breaker": breaker.state()},
                trace_id=self.eval.trace_id)
            super()._compute_placements(bulk)
            return

        _t0 = time.monotonic()
        matrix = ClusterMatrix(self.state, self.job, self.plan)
        _t_base = time.monotonic()
        tg_indices = {tg.name: i for i, tg in enumerate(self.job.task_groups)}
        placements = [tg_indices[m.task_group.name] for m in bulk]

        ask_arrays = matrix.build_asks(placements)
        asks = make_asks(*ask_arrays)
        trace.record_span(self.eval.id, trace.STAGE_MATRIX_BUILD, _t0,
                          ann={"placements": len(bulk)},
                          trace_id=self.eval.trace_id)
        # Attribution for the device-resident path: how this eval's
        # base came to be (cache hit / incremental delta / full
        # rebuild) and how many node rows the delta touched — the
        # resident design's win IS this span staying "hit"/"delta"
        # with small row counts under steady load (models/resident.py).
        kind = getattr(matrix, "build_kind", None)
        if kind is not None:
            trace.record_span(
                self.eval.id, trace.STAGE_MATRIX_UPDATE, _t0, _t_base,
                ann={"kind": kind, "rows": matrix.delta_rows},
                trace_id=self.eval.trace_id)
        # Compression-plane attribution (models/classes.py): how far
        # the fleet interned — C classes over N nodes. Zero-duration
        # marker span (the interning rides the base build above); its
        # value is the annotation in the flight recorder.
        cidx = getattr(matrix, "class_index", None)
        if cidx is not None:
            trace.record_span(
                self.eval.id, trace.STAGE_MATRIX_COMPRESS, _t_base,
                _t_base, ann=cidx.stats(),
                trace_id=self.eval.trace_id)
        # In-batch conflict pre-resolution rides the Planner (worker /
        # dispatch-pipeline sessions set it from server config): batch
        # members of one shared-snapshot dispatch then see each other's
        # capacity claims on device instead of colliding at the plan
        # applier. Harness/test planners without the attr stay on the
        # independent (vmapped) path.
        # Placement kernel (nomad_tpu/kernels): instance pin from the
        # factory variant, else the process-global active kernel inside
        # build_placement_config. The name is a static PlacementConfig
        # field — it joins the batcher's shape key, so kernels never
        # share a dispatch. The config literal is shared with the
        # scheduler executive (build_placement_config) so the two dense
        # drivers can never compile divergent programs.
        config = build_placement_config(
            self.batch,
            bool(getattr(self.planner, "pre_resolve", False)),
            self.kernel, placements, ask_arrays)
        kernel = config.kernel
        # Host-side key: a device PRNGKey here would cost a tunnel
        # round-trip per eval and force the batcher to pull keys back
        # for stacking.
        key = host_prng_key(self.rng.getrandbits(31))

        # The announced place() call is about to arrive: mark the
        # cohort unit consumed so the pipeline doesn't also repay it
        # (place() itself decrements the batcher's counter).
        if getattr(self.planner, "announced_cohort", False):
            self.planner.announced_cohort = False
        # The drain-to-batch shim (BASELINE north star): concurrent
        # workers' same-shaped placement programs coalesce into one
        # vmapped device dispatch instead of N serial calls, and evals
        # sharing a cluster base ride one cached device upload.
        _t0 = time.monotonic()
        try:
            if chaos.enabled:
                # 'error' = an injected device fault AT the breaker's
                # gate: lands in the except below, so a seeded schedule
                # can trip the breaker deterministically (the overload
                # soak drives trip -> half-open -> reclose through
                # this site).
                chaos.fire("device.breaker_trip", eval_id=self.eval.id)
            choices, scores = get_batcher().place(
                matrix, asks, key, config,
                span=(self.eval.id, self.eval.trace_id))
        except Exception:
            # Device dispatch failed (runtime fault, OOM on device,
            # chaos binpack.device / device.breaker_trip): the host
            # iterators have IDENTICAL placement semantics
            # (parity-tested), so falling back costs milliseconds of
            # CPU instead of failing the eval into a nack/redelivery
            # round — the eval still completes this delivery. The whole
            # bulk set takes the host path; the plan applier
            # re-verifies either way. The breaker counts the failure:
            # K consecutive ones trip the dense path out of the way.
            breaker.record_failure()
            self.logger.warning(
                "device placement dispatch failed; falling back to the "
                "host path for %d placements", len(bulk), exc_info=True)
            metrics.incr_counter(("scheduler", "host_fallback"), len(bulk))
            trace.record_span(
                self.eval.id, trace.STAGE_DEVICE_DISPATCH, _t0,
                ann={"host_fallback": True}, trace_id=self.eval.trace_id)
            super()._compute_placements(bulk)
            return
        breaker.record_success((time.monotonic() - _t0) * 1000.0)
        choices = np.asarray(choices)
        scores = np.asarray(scores)
        trace.record_span(self.eval.id, trace.STAGE_DEVICE_DISPATCH, _t0,
                          trace_id=self.eval.trace_id)

        # Host-side exact port assignment per chosen node, incremental.
        net_indexes: Dict[str, NetworkIndex] = {}
        # Placements actually APPENDED to the plan, as (ask row j,
        # node row) — the quality board must score committed claims
        # only (coalesced failures and port-collision host re-places
        # never commit through this loop).
        committed: List[Tuple[int, int]] = []
        # Asks the kernel could not place: candidates for the dense
        # preemption pass (red pressure + outranking eval only) before
        # they become recorded failures.
        unplaced: List[AllocTuple] = []

        for j, missing in enumerate(bulk):
            # Coalesce once the TG has failed, even if the kernel found a
            # node for a later ask of that TG (host-loop invariant).
            if self.failed_tg_allocs and missing.task_group.name in self.failed_tg_allocs:
                self.failed_tg_allocs[missing.task_group.name].coalesced_failures += 1
                continue

            choice = int(choices[j])
            node = matrix.nodes[choice] if 0 <= choice < matrix.n_real else None

            metrics = AllocMetric()
            metrics.nodes_evaluated = matrix.n_real
            metrics.nodes_available = matrix.nodes_by_dc

            if node is None:
                if may_preempt:
                    unplaced.append(missing)
                else:
                    self._record_placement_failure(
                        missing, matrix, metrics, tg_indices
                    )
                continue

            metrics.score_node(node, "binpack", float(scores[j]))
            task_resources = _offer_networks(
                self.rng, missing, node, net_indexes, matrix
            )
            if task_resources is None:
                # Dense port-count approximation missed a real collision:
                # fall back to the exact host path for this placement.
                super()._compute_placements([missing])
                continue

            self.plan.append_alloc(_build_allocation(
                self, missing, node, task_resources, metrics))
            committed.append((j, int(choices[j])))

        # Quality scoreboard (kernels/quality.py): score the cluster
        # state this plan commits to — base utilization plus the
        # claims this loop actually appended — on the fragmentation/
        # bin-pack axes, labeled by kernel so --kernel-ab and stats()
        # can compare. Cheap ([N,4] copy + vector ops) next to the
        # dispatch it follows.
        self._note_quality(kernel, matrix, ask_arrays[0], committed)

        if unplaced:
            self._preempt_placements(unplaced, tg_indices)

    def _place_gang_dense(self, tg, tuples: List[AllocTuple]) -> None:
        """One gang's all-K dispatch (ops/gang.py): per-node fit mask
        -> topology-group cumulative capacity -> contiguous-slice
        selection -> K-step member assignment, one compiled program
        over the device-resident base arrays. Members stage through
        the plan's gang leg (Plan.append_gang_alloc) — the applier
        verifies per node and rejects the WHOLE gang on any member's
        under-fit. Device faults and an open breaker fall back to the
        host gang stack with identical atomicity semantics."""
        from ..admission import get_breaker
        from ..chaos import chaos
        from ..gang import build_gang_state, gang_key, note_gang_result
        from ..models.matrix import ClusterMatrix
        from ..ops.binpack import check_device_chaos, host_prng_key
        from ..ops.gang import gang_placement_program_jit
        from ..utils import metrics as _metrics

        name = tg.name
        if self.failed_tg_allocs and name in self.failed_tg_allocs:
            self.failed_tg_allocs[name].coalesced_failures += len(tuples)
            return

        breaker = get_breaker()
        if not breaker.acquire():
            _metrics.incr_counter(
                ("scheduler", "gang_breaker_rejected"), len(tuples))
            self._place_gang_host(tg, tuples)
            return

        _t0 = time.monotonic()
        # The matrix includes this plan's earlier staged legs (gang
        # replacement stops free their capacity through the proposed-
        # alloc overlay) — the all-K pass must see the room the
        # survivors' stops open up.
        matrix = ClusterMatrix(self.state, self.job, self.plan)
        state, active, (ask_res, ask_bw, ask_ports), config = \
            build_gang_state(matrix, self.job, tg)
        key = host_prng_key(self.rng.getrandbits(31))
        _t_solve = time.monotonic()
        try:
            if chaos.enabled:
                chaos.fire("device.breaker_trip", eval_id=self.eval.id)
            check_device_chaos()
            choices, scores, slice_group = gang_placement_program_jit(
                state, ask_res, ask_bw, ask_ports, active, key, config)
        except Exception:
            breaker.record_failure()
            self.logger.warning(
                "gang device dispatch failed; falling back to the host "
                "gang stack for %d members", len(tuples), exc_info=True)
            _metrics.incr_counter(
                ("scheduler", "gang_host_fallback"), len(tuples))
            trace.record_span(
                self.eval.id, trace.STAGE_GANG_SELECT, _t0,
                ann={"members": len(tuples), "mode": config.mode,
                     "host_fallback": True},
                trace_id=self.eval.trace_id)
            self._place_gang_host(tg, tuples)
            return
        breaker.record_success((time.monotonic() - _t_solve) * 1000.0)
        choices = np.asarray(choices)
        scores = np.asarray(scores)
        slice_gid = int(np.asarray(slice_group))
        trace.record_span(
            self.eval.id, trace.STAGE_GANG_SELECT, _t0,
            ann={"members": len(tuples), "mode": config.mode,
                 "slice_group": slice_gid},
            trace_id=self.eval.trace_id)

        if int(choices[0]) < 0:
            # Whole-gang reject on device (no slice fits all K, or a
            # member found no node): ONE failure for the TG, with
            # class eligibility from the feasibility mask so the
            # blocked eval re-runs when capacity returns.
            note_gang_result(False, len(tuples), "device")
            m = AllocMetric()
            m.nodes_evaluated = matrix.n_real
            m.nodes_available = matrix.nodes_by_dc
            tg_indices = {g.name: i
                          for i, g in enumerate(self.job.task_groups)}
            self._record_placement_failure(tuples[0], matrix, m,
                                           tg_indices)
            if len(tuples) > 1:
                self.failed_tg_allocs[name].coalesced_failures += (
                    len(tuples) - 1)
            return

        # Materialize: exact host-side port offers per member, staged
        # on the gang leg. ANY member failing port assignment unwinds
        # the whole gang to the host stack (exact ports there) — a
        # partial gang never survives this loop.
        gkey = gang_key(self.job.id, name)
        net_indexes: Dict[str, NetworkIndex] = {}
        committed: List[Tuple[int, int]] = []
        for j, missing in enumerate(tuples):
            choice = int(choices[j])
            node = (matrix.nodes[choice]
                    if 0 <= choice < matrix.n_real else None)
            m = AllocMetric()
            m.nodes_evaluated = matrix.n_real
            m.nodes_available = matrix.nodes_by_dc
            task_resources = None
            if node is not None:
                m.score_node(node, "gang", float(scores[j]))
                task_resources = _offer_networks(
                    self.rng, missing, node, net_indexes, matrix)
            if task_resources is None:
                self.plan.pop_gang(gkey)
                _metrics.incr_counter(
                    ("scheduler", "gang_port_fallback"), len(tuples))
                self._place_gang_host(tg, tuples)
                return
            self.plan.append_gang_alloc(gkey, _build_allocation(
                self, missing, node, task_resources, m))
            committed.append((j, choice))
        note_gang_result(True, len(tuples), "device")
        from ..kernels import active_kernel

        self._note_quality(
            self.kernel or active_kernel(), matrix,
            np.tile(np.asarray(ask_res)[None, :], (len(tuples), 1)),
            committed)

    def _preempt_placements(self, pending: List[AllocTuple],
                            tg_indices: Dict[str, int]) -> None:
        """The dense preemption pass (ops/preempt.py): place the asks
        the normal kernel could not, by selecting lowest-priority
        victims and the placement in the same masked program. Runs
        only when migrate.preemption_eligible said yes (preemption on,
        cluster red, eval outranks the threshold). Victim evictions
        are staged on the plan's node_preemptions leg and re-verified
        per victim by the plan applier before committing with the
        placements in one raft apply — chaos site preempt.victim_lost
        drops a staged victim here to prove that verification."""
        from ..chaos import chaos
        from ..migrate import note_preemption
        from ..models.matrix import ClusterMatrix
        from ..ops.binpack import (
            PlacementConfig,
            host_prng_key,
            make_asks,
            make_node_state,
        )
        from ..ops.preempt import (
            make_victim_state,
            preempt_placement_program_jit,
        )
        from .stack import (
            BATCH_JOB_ANTI_AFFINITY_PENALTY,
            SERVICE_JOB_ANTI_AFFINITY_PENALTY,
        )
        from .util import ALLOC_PREEMPTED

        def fail_all(rows: List[AllocTuple], pm) -> None:
            for missing in rows:
                name = missing.task_group.name
                if self.failed_tg_allocs and name in self.failed_tg_allocs:
                    self.failed_tg_allocs[name].coalesced_failures += 1
                    continue
                metrics = AllocMetric()
                metrics.nodes_evaluated = pm.n_real
                metrics.nodes_available = pm.nodes_by_dc
                self._record_placement_failure(missing, pm, metrics,
                                               tg_indices)

        _t0 = time.monotonic()
        # A FRESH matrix including this very plan's placements and
        # staged stops (the plan is non-no-op by now, so this build is
        # uncacheable by design): the preemption pass must not claim
        # headroom an earlier ask of this same eval just took, and its
        # victim lists must exclude allocs the plan already stops.
        pm = ClusterMatrix(self.state, self.job, self.plan)
        varrays, victim_lists, n_candidates = pm.build_victims(
            self.eval.priority)
        if n_candidates == 0:
            fail_all(pending, pm)
            return
        placements = [tg_indices[m.task_group.name] for m in pending]
        ask_arrays = pm.build_asks(placements)
        asks = make_asks(*ask_arrays)
        state = make_node_state(
            pm.capacity, pm.sched_capacity, pm.util, pm.bw_avail,
            pm.bw_used, pm.ports_free, pm.job_count, pm.tg_count,
            pm.feasible, pm.node_ok)
        victims = make_victim_state(*varrays)
        penalty = (BATCH_JOB_ANTI_AFFINITY_PENALTY if self.batch
                   else SERVICE_JOB_ANTI_AFFINITY_PENALTY)
        # Plain greedy config: the preemption program is its own
        # compiled entry point — kernel variants do not apply here.
        config = PlacementConfig(anti_affinity_penalty=penalty)
        key = host_prng_key(self.rng.getrandbits(31))
        # The preemption dispatch shares the device-path breaker: a
        # persistently failing preempt program (e.g. device OOM from
        # the extra victim tensors) must become one routing decision,
        # not a fresh dispatch-failure latency per red-pressure eval.
        from ..admission import get_breaker
        from ..utils import metrics as _metrics

        breaker = get_breaker()
        if not breaker.acquire():
            _metrics.incr_counter(
                ("scheduler", "preempt_breaker_rejected"), len(pending))
            fail_all(pending, pm)
            return
        _t_solve = time.monotonic()
        try:
            choices, scores, counts = preempt_placement_program_jit(
                state, victims, asks, key,
                np.float32(self.eval.priority), config)
        except Exception:  # noqa: BLE001 - degrade to plain failure
            # The device path is sick (the cluster may be red for that
            # very reason): these asks simply stay failed/blocked — the
            # no-preemption outcome, never a half-staged eviction. The
            # breaker counts the failure like any dense dispatch.
            breaker.record_failure()
            self.logger.warning(
                "preemption dispatch failed; %d placements stay "
                "unplaced", len(pending), exc_info=True)
            _metrics.incr_counter(
                ("scheduler", "preempt_dispatch_failed"), len(pending))
            fail_all(pending, pm)
            return
        breaker.record_success((time.monotonic() - _t_solve) * 1000.0)
        choices = np.asarray(choices)
        scores = np.asarray(scores)
        counts = np.asarray(counts)
        trace.record_span(
            self.eval.id, trace.STAGE_PREEMPT_SELECT, _t0,
            ann={"asks": len(pending), "candidates": n_candidates},
            trace_id=self.eval.trace_id)

        net_indexes: Dict[str, NetworkIndex] = {}
        consumed: Dict[int, int] = {}
        staged_total = 0
        placed_total = 0
        for j, missing in enumerate(pending):
            name = missing.task_group.name
            if self.failed_tg_allocs and name in self.failed_tg_allocs:
                self.failed_tg_allocs[name].coalesced_failures += 1
                continue
            choice = int(choices[j])
            node = pm.nodes[choice] if 0 <= choice < pm.n_real else None
            metrics = AllocMetric()
            metrics.nodes_evaluated = pm.n_real
            metrics.nodes_available = pm.nodes_by_dc
            if node is None:
                self._record_placement_failure(missing, pm, metrics,
                                               tg_indices)
                continue
            cnt = int(counts[j])
            taken = []
            if cnt > 0:
                lst = victim_lists.get(choice, [])
                start = consumed.get(choice, 0)
                taken = lst[start:start + cnt]
                consumed[choice] = start + len(taken)
            staged = 0
            for victim in taken:
                if chaos.enabled and chaos.fire(
                        "preempt.victim_lost", eval_id=self.eval.id,
                        alloc=victim.id) == "drop":
                    # The victim vanished between selection and commit:
                    # its freed capacity was already counted on device,
                    # so the plan under-frees — the applier's exact
                    # verification rejects the node and forces a replan.
                    continue
                self.plan.append_preemption(
                    victim, consts.ALLOC_DESIRED_EVICT, ALLOC_PREEMPTED)
                staged += 1
            metrics.score_node(node, "preempt", float(scores[j]))
            task_resources = _offer_networks(
                self.rng, missing, node, net_indexes, pm)
            if task_resources is None:
                # Port collision on the chosen node: back the victims
                # out — an eviction must never commit without the
                # placement it was freeing room for.
                self.plan.pop_preemptions(node.id, staged)
                self._record_placement_failure(missing, pm, metrics,
                                               tg_indices)
                continue
            self.plan.append_alloc(_build_allocation(
                self, missing, node, task_resources, metrics))
            staged_total += staged
            placed_total += 1
        note_preemption(staged_total, placed_total)

    def _note_quality(self, kernel, matrix, ask_res, committed) -> None:
        note_quality(self.logger, self.job, kernel, matrix, ask_res,
                     committed)

    def _repay_cohort(self) -> None:
        """Un-announce this eval's place() call: the dispatch pipeline
        told the batcher a dispatch was coming (add_cohort), but this
        eval took a host path instead — without the repayment the
        batcher's window would stretch COHORT_WAIT_MAX for a request
        that never arrives."""
        if getattr(self.planner, "announced_cohort", False):
            from .batcher import get_batcher

            self.planner.announced_cohort = False
            get_batcher().cohort_cancel(1)

    # ------------------------------------------------------------------

    def _record_placement_failure(
        self, missing: AllocTuple, matrix, metrics, tg_indices: Dict[str, int]
    ) -> None:
        name = missing.task_group.name
        gi = tg_indices[name]
        infeasible = int(matrix.n_real - matrix.feasible[: matrix.n_real, gi].sum())
        metrics.nodes_filtered = infeasible
        metrics.nodes_exhausted = matrix.n_real - infeasible
        if self.failed_tg_allocs is None:
            self.failed_tg_allocs = {}
        self.failed_tg_allocs[name] = metrics
        # Feed the blocked-eval machinery per-class eligibility from the mask.
        elig = self.ctx.eligibility
        for i, node in enumerate(matrix.nodes):
            if node.computed_class:
                elig.set_task_group_eligibility(
                    bool(matrix.feasible[i, gi]), name, node.computed_class
                )

def note_quality(logger, job, kernel, matrix, ask_res, committed) -> None:
    """Quality scoreboard entry (kernels/quality.py) for one dense
    plan's committed claims — shared by the per-eval scheduler and the
    scheduler executive so --kernel-ab and stats() score both drivers
    on the same axes. Scoring must never fail an eval."""
    from ..kernels.quality import (
        get_board,
        quality_from_arrays,
        reference_ask,
    )

    try:
        if not get_board().should_sample(kernel):
            return
        util = np.asarray(matrix.util).copy()
        if committed:
            js = np.asarray([j for j, _r in committed])
            rows = np.asarray([r for _j, r in committed])
            np.add.at(util, rows, np.asarray(ask_res)[js])
        q = quality_from_arrays(util, matrix.capacity,
                                matrix.node_ok,
                                reference_ask(job))
        get_board().note_plan(kernel, q["fragmentation"],
                              q["binpack_score"])
    except Exception:  # noqa: BLE001 - scoring must never fail an eval
        logger.warning("placement-quality scoring failed",
                       exc_info=True)


def dense_diff_system_allocs(state, job, nodes, tainted, allocs,
                             terminal_allocs):
    """diff_system_allocs (scheduler/util.go:62) with the place set
    feasibility-gated up front: the host version materializes one
    AllocTuple (and a stub Allocation) per required slot on EVERY ready
    node, then the placement loop filters the infeasible ones one
    python iteration at a time — at 10k nodes with rack-scoped system
    jobs that is ~9k tuples built and discarded per eval. Here the
    class-vectorized constraint mask picks the candidate rows first and
    only those materialize; the infeasible remainder is returned as
    per-task-group counts for the caller's metric/queued bookkeeping.

    Returns (DiffResult, prefiltered) where prefiltered maps
    tg name -> [count, first_infeasible_node]."""
    from ..models.matrix import node_feasibility, ready_class_index
    from .util import DiffResult, diff_allocs, materialize_task_groups

    groups = job.task_groups
    class_ids, class_reps = ready_class_index(state, nodes, job.datacenters)
    feasible = node_feasibility(state, job, groups, nodes,
                                class_ids, class_reps)
    gi_by_name = {tg.name: gi for gi, tg in enumerate(groups)}
    required = materialize_task_groups(job)
    result = DiffResult()
    prefiltered: Dict[str, list] = {}

    def gate_place(tuples, row):
        """Feasibility-gate one node's place tuples."""
        kept = []
        for tup in tuples:
            if feasible[row, gi_by_name[tup.task_group.name]]:
                kept.append(tup)
            else:
                ent = prefiltered.get(tup.task_group.name)
                if ent is None:
                    prefiltered[tup.task_group.name] = [1, nodes[row]]
                else:
                    ent[0] += 1
        return kept

    node_row = {n.id: i for i, n in enumerate(nodes)}
    # Nodes holding this job's allocs: the faithful per-node diff
    # (stop/lost/update/ignore need the alloc-level comparisons).
    node_allocs: Dict[str, List[Allocation]] = {}
    for alloc in allocs:
        node_allocs.setdefault(alloc.node_id, []).append(alloc)
    for node_id, nallocs in node_allocs.items():
        diff = diff_allocs(job, tainted, required, nallocs, terminal_allocs)
        if node_id in tainted:
            diff.place = []
        else:
            row = node_row.get(node_id)
            for tup in diff.place:
                if tup.alloc is None or tup.alloc.node_id != node_id:
                    tup.alloc = Allocation(node_id=node_id)
            diff.place = (gate_place(diff.place, row)
                          if row is not None else diff.place)
        # A tainted node invalidates the job there: migrations -> stops.
        diff.stop.extend(diff.migrate)
        diff.migrate = []
        result.append(diff)

    # Nodes WITHOUT allocs place every required slot; candidates and
    # the infeasible tally come from array ops, python only touches
    # the (usually few) feasible rows.
    has_alloc = np.zeros(len(nodes), bool)
    for node_id in node_allocs:
        row = node_row.get(node_id)
        if row is not None:
            has_alloc[row] = True
    candidates = ~has_alloc
    if tainted:
        for node_id in tainted:
            row = node_row.get(node_id)
            if row is not None:
                candidates[row] = False
    cand_feasible = candidates[:, None] & feasible
    any_rows = np.flatnonzero(cand_feasible.any(axis=1))
    for i in any_rows:
        node_id = nodes[i].id
        for name, tg in required.items():
            if not cand_feasible[i, gi_by_name[tg.name]]:
                continue
            talloc = terminal_allocs.get(name)
            if talloc is None or talloc.node_id != node_id:
                talloc = Allocation(node_id=node_id)
            result.place.append(AllocTuple(name, tg, talloc))
    # Infeasible tallies + first offender per TG, without materializing.
    slots_per_tg = {tg.name: 0 for tg in groups}
    for _name, tg in required.items():
        slots_per_tg[tg.name] += 1
    for tg in groups:
        gi = gi_by_name[tg.name]
        bad = candidates & ~feasible[:, gi]
        n_bad = int(bad.sum()) * slots_per_tg[tg.name]
        if not n_bad:
            continue
        ent = prefiltered.get(tg.name)
        if ent is None:
            prefiltered[tg.name] = [n_bad, nodes[int(np.argmax(bad))]]
        else:
            ent[0] += n_bad
    return result, prefiltered


class DenseSystemScheduler(SystemScheduler):
    """SystemScheduler whose diff and placement loops are vectorized
    passes.

    The host loop (system_sched.go:255) builds a one-node iterator
    stack per pinned placement; here the whole placement set is checked
    against a single ClusterMatrix: constraint feasibility comes from
    the [N, G] mask, resource fit is a vectorized AllocsFit over the
    pinned rows, and in-eval utilization accumulates per task group so
    multi-TG system jobs see their own earlier placements. The diff is
    feasibility-gated up front (dense_diff_system_allocs), so the
    pinned matrix and the plan only ever see candidate nodes."""

    def _diff_system(self, tainted, allocs, terminal_allocs):
        """Feasibility-gated diff (see dense_diff_system_allocs). A
        deregistered job (job=None: every alloc diffs into stop) takes
        the host diff — there is nothing to gate without constraints."""
        if self.job is None:
            return super()._diff_system(tainted, allocs, terminal_allocs)
        return dense_diff_system_allocs(
            self.state, self.job, self.nodes, tainted, allocs,
            terminal_allocs)

    def _compute_placements(self, place: List[AllocTuple]) -> None:
        from ..models.matrix import ClusterMatrix

        # Matrix only the PINNED nodes: system placements are fixed to
        # their node up front (diffSystemAllocs), so feasibility/fit for
        # the other N-P nodes would be wasted work — at 10k nodes with
        # rack-scoped jobs that's a 200x smaller matrix per eval.
        pinned_ids = []
        seen = set()
        for missing in place:
            nid = missing.alloc.node_id
            if nid not in seen:
                seen.add(nid)
                pinned_ids.append(nid)
        by_id = {n.id: n for n in self.nodes}
        pinned_nodes = [by_id[nid] for nid in pinned_ids if nid in by_id]
        _t0 = time.monotonic()
        matrix = ClusterMatrix(self.state, self.job, self.plan,
                               nodes=pinned_nodes)
        matrix.nodes_by_dc = self.nodes_by_dc
        node_index = {n.id: i for i, n in enumerate(matrix.nodes)}
        tg_by_name = {tg.name: i for i, tg in enumerate(self.job.task_groups)}

        placements = [tg_by_name[m.task_group.name] for m in place]
        resources, bw, ports, _tg_index, _active, _jdh, _tdh = \
            matrix.build_asks(placements)
        trace.record_span(self.eval.id, trace.STAGE_MATRIX_BUILD, _t0,
                          ann={"placements": len(place), "pinned": True},
                          trace_id=self.eval.trace_id)

        util = matrix.util.copy()
        bw_used = matrix.bw_used.copy()
        ports_free = matrix.ports_free.copy()

        rows = np.empty(len(place), np.int64)
        for j, missing in enumerate(place):
            row = node_index.get(missing.alloc.node_id)
            if row is None:
                raise RuntimeError(
                    f"could not find node {missing.alloc.node_id!r}")
            rows[j] = row

        gis = np.asarray(placements)
        feasible = matrix.feasible[rows, gis]
        # Vectorized AllocsFit per task group so same-node placements of
        # different groups accumulate (G passes, each all-numpy). The
        # ask arrays from build_asks are per-placement rows; every row
        # of one group carries that group's ask.
        fits = np.zeros(len(place), bool)
        for gi in sorted(set(placements)):
            sel = gis == gi
            j0 = int(np.flatnonzero(sel)[0])
            ask_res, ask_bw, ask_ports = resources[j0], bw[j0], ports[j0]
            r = rows[sel]
            ok = (
                feasible[sel]
                & np.all(util[r] + ask_res <= matrix.capacity[r], axis=1)
                & (bw_used[r] + ask_bw <= matrix.bw_avail[r])
                & (ports_free[r] >= ask_ports)
            )
            fits[sel] = ok
            acc = r[ok]
            np.add.at(util, acc, ask_res)
            np.add.at(bw_used, acc, ask_bw)
            np.add.at(ports_free, acc, -ask_ports)

        net_indexes: Dict[str, NetworkIndex] = {}
        # Successful pinned placements all carry the identical metric
        # record (one node evaluated, same availability): share ONE
        # object across the plan — the store's upsert copies it per
        # alloc, so sharing here is invisible downstream, and a system
        # storm materializes ~N of these per eval.
        success_metrics: Optional[AllocMetric] = None

        for j, missing in enumerate(place):
            name = missing.task_group.name
            node = matrix.nodes[rows[j]]

            if not fits[j]:
                # Failure paths mutate their metric record, so those
                # stay per-placement, like the host path where every
                # stack.select starts fresh (stack.go Select → ctx
                # reset); the pinned node is the one node evaluated.
                metrics = AllocMetric()
                metrics.nodes_available = self.nodes_by_dc
                metrics.evaluate_node()
                if not feasible[j]:
                    # Constraint mismatch: the alloc was never really
                    # "queued" on this node (host path's nodes_filtered
                    # branch, system_sched.go undo accounting).
                    metrics.filter_node(node, "constraint")
                    self.queued_allocs[name] -= 1
                    if (
                        self.eval.annotate_plan
                        and self.plan.annotations is not None
                        and name in self.plan.annotations.desired_tg_updates
                    ):
                        self.plan.annotations.desired_tg_updates[name].place -= 1
                else:
                    metrics.exhausted_node(node, "resources")
                # Record the first failure per TG, coalesce the rest —
                # for filtered AND exhausted alike (system_sched.go:261).
                if self.failed_tg_allocs and name in self.failed_tg_allocs:
                    self.failed_tg_allocs[name].coalesced_failures += 1
                else:
                    if self.failed_tg_allocs is None:
                        self.failed_tg_allocs = {}
                    self.failed_tg_allocs[name] = metrics
                continue

            task_resources = self._offer_networks_on(
                missing, node, net_indexes, matrix)
            if task_resources is None:
                # Dense port-count approximation missed a collision:
                # fall back to the exact host path for this placement.
                super()._compute_placements([missing])
                continue

            if success_metrics is None:
                success_metrics = AllocMetric()
                success_metrics.nodes_available = self.nodes_by_dc
                success_metrics.evaluate_node()
            self.plan.append_alloc(_build_allocation(
                self, missing, node, task_resources, success_metrics))

    def _offer_networks_on(self, missing: AllocTuple, node, net_indexes,
                           matrix):
        """Exact per-task network offers on the pinned node (same logic
        as the generic dense path)."""
        has_networks = any(
            t.resources is not None and t.resources.networks
            for t in missing.task_group.tasks
        )
        if not has_networks:
            return {
                t.name: t.resources.copy()
                for t in missing.task_group.tasks
            }
        return _offer_networks(self.rng, missing, node, net_indexes, matrix)
