"""TPU-backed scheduler factories ("service-tpu", "batch-tpu").

The north-star design (BASELINE.json): identical control flow to the
GenericScheduler — same reconciliation, same blocked-eval/rolling
semantics, same plan shape — but computePlacements runs as one dense
JAX program instead of per-node iterators. In-place updates and
sticky-disk preferences stay host-side (SURVEY.md section 7 hard
parts); exact port numbers are assigned host-side on the chosen nodes;
the plan applier re-verifies every node so kernel approximations cost
retries, not correctness.
"""

from __future__ import annotations

import logging
import random
from typing import Dict, List, Optional

import numpy as np

from ..structs import (
    Allocation,
    AllocMetric,
    NetworkIndex,
    NetworkResource,
    Resources,
    consts,
)
from ..utils.ids import generate_uuid
from .generic import GenericScheduler
from .util import AllocTuple


class BatchedTPUScheduler(GenericScheduler):
    """GenericScheduler whose bulk placement loop runs on the TPU."""

    def _compute_placements(self, place: List[AllocTuple]) -> None:
        import jax

        from ..models.matrix import ClusterMatrix
        from ..ops.binpack import (
            PlacementConfig,
            make_asks,
            make_node_state,
            placement_program_jit,
        )
        from .stack import (
            BATCH_JOB_ANTI_AFFINITY_PENALTY,
            SERVICE_JOB_ANTI_AFFINITY_PENALTY,
        )

        # Sticky-disk placements keep the host path (they pin to one node).
        sticky: List[AllocTuple] = []
        bulk: List[AllocTuple] = []
        for missing in place:
            if self._find_preferred_node(missing) is not None:
                sticky.append(missing)
            else:
                bulk.append(missing)
        if sticky:
            super()._compute_placements(sticky)
        # A TG that already failed (e.g. in the sticky host path) only
        # coalesces from here on — same invariant as the host loop
        # (generic_sched.go:444-447).
        remaining: List[AllocTuple] = []
        for missing in bulk:
            if self.failed_tg_allocs and missing.task_group.name in self.failed_tg_allocs:
                self.failed_tg_allocs[missing.task_group.name].coalesced_failures += 1
            else:
                remaining.append(missing)
        bulk = remaining
        if not bulk:
            return

        matrix = ClusterMatrix(self.state, self.job, self.plan)
        tg_indices = {tg.name: i for i, tg in enumerate(self.job.task_groups)}
        placements = [tg_indices[m.task_group.name] for m in bulk]

        state = make_node_state(
            matrix.capacity, matrix.sched_capacity, matrix.util,
            matrix.bw_avail, matrix.bw_used, matrix.ports_free,
            matrix.job_count, matrix.tg_count, matrix.feasible, matrix.node_ok,
        )
        asks = make_asks(*matrix.build_asks(placements))
        penalty = (
            BATCH_JOB_ANTI_AFFINITY_PENALTY
            if self.batch
            else SERVICE_JOB_ANTI_AFFINITY_PENALTY
        )
        config = PlacementConfig(anti_affinity_penalty=penalty)
        key = jax.random.PRNGKey(self.rng.getrandbits(31))

        choices, scores, _ = placement_program_jit(state, asks, key, config)
        choices = np.asarray(choices)
        scores = np.asarray(scores)

        # Host-side exact port assignment per chosen node, incremental.
        net_indexes: Dict[str, NetworkIndex] = {}

        for j, missing in enumerate(bulk):
            # Coalesce once the TG has failed, even if the kernel found a
            # node for a later ask of that TG (host-loop invariant).
            if self.failed_tg_allocs and missing.task_group.name in self.failed_tg_allocs:
                self.failed_tg_allocs[missing.task_group.name].coalesced_failures += 1
                continue

            choice = int(choices[j])
            node = matrix.nodes[choice] if 0 <= choice < matrix.n_real else None

            metrics = AllocMetric()
            metrics.nodes_evaluated = matrix.n_real
            metrics.nodes_available = matrix.nodes_by_dc

            if node is None:
                self._record_placement_failure(
                    missing, matrix, metrics, tg_indices
                )
                continue

            metrics.score_node(node, "binpack", float(scores[j]))
            task_resources = self._offer_networks(
                missing, node, net_indexes, matrix
            )
            if task_resources is None:
                # Dense port-count approximation missed a real collision:
                # fall back to the exact host path for this placement.
                super()._compute_placements([missing])
                continue

            alloc = Allocation(
                id=generate_uuid(),
                eval_id=self.eval.id,
                name=missing.name,
                job_id=self.job.id,
                task_group=missing.task_group.name,
                metrics=metrics,
                node_id=node.id,
                task_resources=task_resources,
                desired_status=consts.ALLOC_DESIRED_RUN,
                client_status=consts.ALLOC_CLIENT_PENDING,
                shared_resources=Resources(
                    disk_mb=missing.task_group.ephemeral_disk.size_mb
                ),
            )
            if missing.alloc is not None:
                alloc.previous_allocation = missing.alloc.id
            self.plan.append_alloc(alloc)

    # ------------------------------------------------------------------

    def _record_placement_failure(
        self, missing: AllocTuple, matrix, metrics, tg_indices: Dict[str, int]
    ) -> None:
        name = missing.task_group.name
        gi = tg_indices[name]
        infeasible = int(matrix.n_real - matrix.feasible[: matrix.n_real, gi].sum())
        metrics.nodes_filtered = infeasible
        metrics.nodes_exhausted = matrix.n_real - infeasible
        if self.failed_tg_allocs is None:
            self.failed_tg_allocs = {}
        self.failed_tg_allocs[name] = metrics
        # Feed the blocked-eval machinery per-class eligibility from the mask.
        elig = self.ctx.eligibility
        for i, node in enumerate(matrix.nodes):
            if node.computed_class:
                elig.set_task_group_eligibility(
                    bool(matrix.feasible[i, gi]), name, node.computed_class
                )

    def _offer_networks(self, missing: AllocTuple, node, net_indexes, matrix):
        """Exact per-task network offers on the kernel-chosen node.
        Returns {task: Resources} or None if a port can't be assigned."""
        idx = net_indexes.get(node.id)
        if idx is None:
            idx = NetworkIndex()
            idx.set_node(node)
            idx.add_allocs(matrix._proposed_allocs(node.id))
            net_indexes[node.id] = idx

        task_resources: Dict[str, Resources] = {}
        for task in missing.task_group.tasks:
            resources = task.resources.copy()
            if resources.networks:
                ask = resources.networks[0]
                offer, err = idx.assign_network(ask, self.rng)
                if offer is None:
                    # Drop the partially-updated index; it is rebuilt
                    # from the plan on next use.
                    net_indexes.pop(node.id, None)
                    return None
                idx.add_reserved(offer)
                resources.networks = [offer]
            task_resources[task.name] = resources
        return task_resources
