"""Feasibility iterators and checkers.

Reference: scheduler/feasible.go — StaticIterator:35, RandomIterator:83,
DriverChecker:93, ProposedAllocConstraintIterator:150,
ConstraintChecker:247, resolveConstraintTarget:291, checkConstraint:327,
FeasibilityWrapper:457.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Set

from ..structs import Constraint, Job, Node, TaskGroup, consts
from ..utils.version import parse_constraints, parse_version
from .context import (
    CLASS_ELIGIBLE,
    CLASS_ESCAPED,
    CLASS_INELIGIBLE,
    CLASS_UNKNOWN,
    EvalContext,
)


class StaticIterator:
    """Yields nodes in a fixed order with wrap-around: after a Reset the
    iterator continues from its offset, visiting each node at most once
    per pass (feasible.go:51-72)."""

    def __init__(self, ctx: EvalContext, nodes: Optional[List[Node]]):
        self.ctx = ctx
        self.nodes = nodes or []
        self.offset = 0
        self.seen = 0

    def next(self) -> Optional[Node]:
        n = len(self.nodes)
        if self.offset == n or self.seen == n:
            if self.seen != n:
                self.offset = 0
            else:
                return None
        option = self.nodes[self.offset]
        self.offset += 1
        self.seen += 1
        self.ctx.metrics.evaluate_node()
        return option

    def reset(self) -> None:
        self.seen = 0

    def set_nodes(self, nodes: List[Node]) -> None:
        self.nodes = nodes
        self.offset = 0
        self.seen = 0


def new_random_iterator(ctx: EvalContext, nodes: Optional[List[Node]]) -> StaticIterator:
    """Shuffled source: reduces collisions between concurrent schedulers
    and load-balances across eligible nodes."""
    nodes = list(nodes or [])
    ctx.rng.shuffle(nodes)
    return StaticIterator(ctx, nodes)


class DriverChecker:
    """Node must advertise every required driver as attribute
    'driver.<name>' parsing to a true boolean."""

    def __init__(self, ctx: EvalContext, drivers: Optional[Set[str]] = None):
        self.ctx = ctx
        self.drivers = drivers or set()

    def set_drivers(self, drivers: Set[str]) -> None:
        self.drivers = drivers

    def feasible(self, option: Node) -> bool:
        if self._has_drivers(option):
            return True
        self.ctx.metrics.filter_node(option, "missing drivers")
        return False

    def _has_drivers(self, option: Node) -> bool:
        for driver in self.drivers:
            value = option.attributes.get(f"driver.{driver}")
            if value is None:
                return False
            if str(value).strip().lower() not in ("1", "t", "true"):
                return False
        return True


class ConstraintChecker:
    def __init__(self, ctx: EvalContext, constraints: Optional[List[Constraint]] = None):
        self.ctx = ctx
        self.constraints = constraints or []

    def set_constraints(self, constraints: List[Constraint]) -> None:
        self.constraints = constraints

    def feasible(self, option: Node) -> bool:
        for constraint in self.constraints:
            if not self._meets(constraint, option):
                self.ctx.metrics.filter_node(option, str(constraint))
                return False
        return True

    def _meets(self, constraint: Constraint, option: Node) -> bool:
        lval, ok = resolve_constraint_target(constraint.ltarget, option)
        if not ok:
            return False
        rval, ok = resolve_constraint_target(constraint.rtarget, option)
        if not ok:
            return False
        return check_constraint(self.ctx, constraint.operand, lval, rval)


def resolve_constraint_target(target: str, node: Node):
    """Interpolate ${node.*}/${attr.*}/${meta.*} against the node;
    plain strings are literals. Returns (value, ok)."""
    if not target.startswith("${"):
        return target, True
    if target == "${node.unique.id}":
        return node.id, True
    if target == "${node.datacenter}":
        return node.datacenter, True
    if target == "${node.unique.name}":
        return node.name, True
    if target == "${node.class}":
        return node.node_class, True
    if target.startswith("${attr."):
        key = target[len("${attr.") : -1]
        if key in node.attributes:
            return node.attributes[key], True
        return None, False
    if target.startswith("${meta."):
        key = target[len("${meta.") : -1]
        if key in node.meta:
            return node.meta[key], True
        return None, False
    return None, False


def check_constraint(ctx: EvalContext, operand: str, lval, rval) -> bool:
    if operand == consts.CONSTRAINT_DISTINCT_HOSTS:
        # Handled by ProposedAllocConstraintIterator, pass here.
        return True
    if operand in ("=", "==", "is"):
        return lval == rval
    if operand in ("!=", "not"):
        return lval != rval
    if operand in ("<", "<=", ">", ">="):
        return _check_lexical(operand, lval, rval)
    if operand == consts.CONSTRAINT_VERSION:
        return _check_version(ctx, lval, rval)
    if operand == consts.CONSTRAINT_REGEX:
        return _check_regexp(ctx, lval, rval)
    return False


def _check_lexical(op: str, lval, rval) -> bool:
    if not isinstance(lval, str) or not isinstance(rval, str):
        return False
    if op == "<":
        return lval < rval
    if op == "<=":
        return lval <= rval
    if op == ">":
        return lval > rval
    return lval >= rval


def _check_version(ctx: EvalContext, lval, rval) -> bool:
    if isinstance(lval, int):
        lval = str(lval)
    if not isinstance(lval, str) or not isinstance(rval, str):
        return False
    version = parse_version(lval)
    if version is None:
        return False
    constraints = ctx.constraint_cache.get(rval)
    if constraints is None:
        constraints = parse_constraints(rval)
        if constraints is None:
            return False
        ctx.constraint_cache[rval] = constraints
    return constraints.check(version)


def _check_regexp(ctx: EvalContext, lval, rval) -> bool:
    if not isinstance(lval, str) or not isinstance(rval, str):
        return False
    compiled = ctx.regexp_cache.get(rval)
    if compiled is None:
        try:
            compiled = re.compile(rval)
        except re.error:
            return False
        ctx.regexp_cache[rval] = compiled
    return compiled.search(lval) is not None


class ProposedAllocConstraintIterator:
    """Applies constraints affected by proposed placements: currently
    distinct_hosts (feasible.go:150-242)."""

    def __init__(self, ctx: EvalContext, source):
        self.ctx = ctx
        self.source = source
        self.tg: Optional[TaskGroup] = None
        self.job: Optional[Job] = None
        self.tg_distinct_hosts = False
        self.job_distinct_hosts = False

    def set_task_group(self, tg: TaskGroup) -> None:
        self.tg = tg
        self.tg_distinct_hosts = self._has_distinct_hosts(tg.constraints)

    def set_job(self, job: Job) -> None:
        self.job = job
        self.job_distinct_hosts = self._has_distinct_hosts(job.constraints)

    @staticmethod
    def _has_distinct_hosts(constraints: List[Constraint]) -> bool:
        return any(c.operand == consts.CONSTRAINT_DISTINCT_HOSTS for c in constraints)

    def next(self) -> Optional[Node]:
        while True:
            option = self.source.next()
            if option is None or not (self.job_distinct_hosts or self.tg_distinct_hosts):
                return option
            if not self._satisfies_distinct_hosts(option):
                self.ctx.metrics.filter_node(option, consts.CONSTRAINT_DISTINCT_HOSTS)
                continue
            return option

    def _satisfies_distinct_hosts(self, option: Node) -> bool:
        proposed = self.ctx.proposed_allocs(option.id)
        for alloc in proposed:
            job_collision = alloc.job_id == self.job.id
            task_collision = alloc.task_group == self.tg.name
            if (self.job_distinct_hosts and job_collision) or (
                job_collision and task_collision
            ):
                return False
        return True

    def reset(self) -> None:
        self.source.reset()


class FeasibilityWrapper:
    """Runs job- and TG-level feasibility checks, memoized per computed
    node class via EvalEligibility (feasible.go:457-568)."""

    def __init__(self, ctx: EvalContext, source, job_checkers, tg_checkers):
        self.ctx = ctx
        self.source = source
        self.job_checkers = job_checkers
        self.tg_checkers = tg_checkers
        self.tg = ""

    def set_task_group(self, tg: str) -> None:
        self.tg = tg

    def reset(self) -> None:
        self.source.reset()

    def next(self) -> Optional[Node]:
        elig = self.ctx.eligibility
        metrics = self.ctx.metrics
        while True:
            option = self.source.next()
            if option is None:
                return None

            job_escaped = job_unknown = False
            status = elig.job_status(option.computed_class)
            if status == CLASS_INELIGIBLE:
                metrics.filter_node(option, "computed class ineligible")
                continue
            elif status == CLASS_ESCAPED:
                job_escaped = True
            elif status == CLASS_UNKNOWN:
                job_unknown = True

            failed = False
            for check in self.job_checkers:
                if not check.feasible(option):
                    if not job_escaped:
                        elig.set_job_eligibility(False, option.computed_class)
                    failed = True
                    break
            if failed:
                continue
            if not job_escaped and job_unknown:
                elig.set_job_eligibility(True, option.computed_class)

            tg_escaped = tg_unknown = False
            status = elig.task_group_status(self.tg, option.computed_class)
            if status == CLASS_INELIGIBLE:
                metrics.filter_node(option, "computed class ineligible")
                continue
            elif status == CLASS_ELIGIBLE:
                return option
            elif status == CLASS_ESCAPED:
                tg_escaped = True
            elif status == CLASS_UNKNOWN:
                tg_unknown = True

            failed = False
            for check in self.tg_checkers:
                if not check.feasible(option):
                    if not tg_escaped:
                        elig.set_task_group_eligibility(
                            False, self.tg, option.computed_class
                        )
                    failed = True
                    break
            if failed:
                continue
            if not tg_escaped and tg_unknown:
                elig.set_task_group_eligibility(True, self.tg, option.computed_class)

            return option
