"""GenericScheduler: service and batch jobs.

Reference: scheduler/generic_sched.go:59 (GenericScheduler),
:103 (Process), :183 (process), :281 (filterCompleteAllocs),
:349 (computeJobAllocs), :432 (computePlacements),
:507 (findPreferredNode).
"""

from __future__ import annotations

import logging
import random
import time
from typing import Dict, List, Optional

from ..structs import (
    AllocMetric,
    Allocation,
    Evaluation,
    Job,
    Plan,
    PlanResult,
    Resources,
    consts,
)
from ..utils.ids import generate_uuid
from .context import EvalContext
from .stack import GenericStack
from .util import (
    ALLOC_LOST,
    ALLOC_MIGRATING,
    ALLOC_NOT_NEEDED,
    ALLOC_UPDATING,
    AllocTuple,
    SetStatusError,
    adjust_queued_allocations,
    desired_updates,
    diff_allocs,
    evict_and_place,
    inplace_update,
    mark_lost_and_place,
    materialize_task_groups,
    progress_made,
    ready_nodes_in_dcs,
    retry_max,
    set_status,
    tainted_nodes,
    update_non_terminal_allocs_to_lost,
)

MAX_SERVICE_SCHEDULE_ATTEMPTS = 5
MAX_BATCH_SCHEDULE_ATTEMPTS = 2

BLOCKED_EVAL_MAX_PLAN_DESC = "created due to placement conflicts"
BLOCKED_EVAL_FAILED_PLACEMENTS = "created to place remaining allocations"


class GenericScheduler:
    def __init__(self, logger, state, planner, batch: bool,
                 rng: Optional[random.Random] = None):
        self.logger = logger or logging.getLogger("nomad_tpu.scheduler")
        self.state = state
        self.planner = planner
        self.batch = batch
        self.rng = rng or random.Random()

        self.eval: Optional[Evaluation] = None
        self.job: Optional[Job] = None
        self.plan: Optional[Plan] = None
        self.plan_result: Optional[PlanResult] = None
        self.ctx: Optional[EvalContext] = None
        self.stack: Optional[GenericStack] = None

        self.limit_reached = False
        self.next_eval: Optional[Evaluation] = None
        self.blocked: Optional[Evaluation] = None
        self.failed_tg_allocs: Optional[Dict[str, AllocMetric]] = None
        self.queued_allocs: Optional[Dict[str, int]] = None
        # Migration-budget bookkeeping (nomad_tpu/migrate): slots this
        # attempt holds (released when the attempt's submit finishes)
        # and the follow-up eval minted for deferred displaced allocs.
        self._migrate_permits = 0
        self._migration_eval: Optional[Evaluation] = None

    # ------------------------------------------------------------------

    def process_eval(self, eval: Evaluation) -> None:
        """Handle a single evaluation end to end."""
        self.eval = eval

        if eval.triggered_by not in (
            consts.EVAL_TRIGGER_JOB_REGISTER,
            consts.EVAL_TRIGGER_NODE_UPDATE,
            consts.EVAL_TRIGGER_JOB_DEREGISTER,
            consts.EVAL_TRIGGER_ROLLING_UPDATE,
            consts.EVAL_TRIGGER_PERIODIC_JOB,
            consts.EVAL_TRIGGER_MAX_PLANS,
            consts.EVAL_TRIGGER_MIGRATION,
            consts.EVAL_TRIGGER_PREEMPTION,
            consts.EVAL_TRIGGER_DEFRAG,
        ):
            desc = f"scheduler cannot handle '{eval.triggered_by}' evaluation reason"
            set_status(
                self.logger, self.planner, self.eval, self.next_eval, self.blocked,
                self.failed_tg_allocs, consts.EVAL_STATUS_FAILED, desc,
                self.queued_allocs,
            )
            return

        limit = MAX_BATCH_SCHEDULE_ATTEMPTS if self.batch else MAX_SERVICE_SCHEDULE_ATTEMPTS
        try:
            retry_max(limit, self._process, lambda: progress_made(self.plan_result))
        except SetStatusError as err:
            # No forward progress: leave a blocked eval to retry when
            # resources change, then record the failure.
            self._create_blocked_eval(plan_failure=True)
            set_status(
                self.logger, self.planner, self.eval, self.next_eval, self.blocked,
                self.failed_tg_allocs, err.eval_status, str(err), self.queued_allocs,
            )
            return

        # A blocked eval that still couldn't place everything goes back to
        # the blocked tracker with refreshed class eligibility.
        if (
            self.eval.status == consts.EVAL_STATUS_BLOCKED
            and self.failed_tg_allocs
        ):
            e = self.ctx.eligibility
            new_eval = self.eval.copy()
            new_eval.escaped_computed_class = e.has_escaped()
            new_eval.class_eligibility = e.get_classes()
            self.planner.reblock_eval(new_eval)
            return

        set_status(
            self.logger, self.planner, self.eval, self.next_eval, self.blocked,
            self.failed_tg_allocs, consts.EVAL_STATUS_COMPLETE, "",
            self.queued_allocs,
        )

    # ------------------------------------------------------------------

    def _create_blocked_eval(self, plan_failure: bool) -> None:
        e = self.ctx.eligibility
        escaped = e.has_escaped()
        class_eligibility = {} if escaped else e.get_classes()
        self.blocked = self.eval.create_blocked_eval(class_eligibility, escaped)
        if plan_failure:
            self.blocked.triggered_by = consts.EVAL_TRIGGER_MAX_PLANS
            self.blocked.status_description = BLOCKED_EVAL_MAX_PLAN_DESC
        else:
            self.blocked.status_description = BLOCKED_EVAL_FAILED_PLACEMENTS
        self.planner.create_eval(self.blocked)

    def _process(self) -> bool:
        """One scheduling attempt; returns True when done. Migration-
        budget slots claimed by the attempt (nomad_tpu/migrate) are
        held until its plan submit finishes — success or failure, the
        displaced allocs are no longer in flight HERE once the attempt
        ends, and a retry re-claims against fresh state."""
        self._migrate_permits = 0
        try:
            return self._process_attempt()
        finally:
            if self._migrate_permits:
                from ..migrate import get_governor

                get_governor().release(self._migrate_permits)
                self._migrate_permits = 0

    def _process_attempt(self) -> bool:
        self.job = self.state.job_by_id(self.eval.job_id)
        self.queued_allocs = {}

        self.plan = self.eval.make_plan(self.job)
        self.failed_tg_allocs = None
        self.ctx = EvalContext(self.state, self.plan, self.logger, rng=self.rng)
        self.stack = GenericStack(self.batch, self.ctx)
        if self.job is not None:
            self.stack.set_job(self.job)

        self._compute_job_allocs()

        # Unplaced allocations need a blocked eval to retry on capacity
        # changes; reuse the current one if we're already blocked.
        if (
            self.eval.status != consts.EVAL_STATUS_BLOCKED
            and self.failed_tg_allocs
            and self.blocked is None
        ):
            self._create_blocked_eval(plan_failure=False)

        if self.plan.is_no_op() and not self.eval.annotate_plan:
            return True

        # Rolling-update limit reached: schedule the next batch after the
        # stagger period.
        if self.limit_reached and self.next_eval is None:
            self.next_eval = self.eval.next_rolling_eval(self.job.update.stagger)
            self.planner.create_eval(self.next_eval)

        result, new_state = self.planner.submit_plan(self.plan)
        self.plan_result = result

        adjust_queued_allocations(self.logger, result, self.queued_allocs)

        if result is not None and result.node_preemptions:
            self._create_preemption_followups(result)

        if new_state is not None:
            self.state = new_state
            return False

        full_commit, expected, actual = result.full_commit(self.plan)
        if not full_commit:
            self.logger.debug(
                "eval %s: attempted %d placements, %d placed",
                self.eval.id, expected, actual,
            )
            raise RuntimeError("missing state refresh after partial commit")

        return True

    def _create_preemption_followups(self, result: PlanResult) -> None:
        """Every job whose alloc this plan's preemption leg evicted
        gets a replacement eval (triggered_by=preemption) — it usually
        blocks until capacity returns (the cluster was red), but the
        evicted work is never silently forgotten. One eval per job per
        process_eval, however many attempts commit victims."""
        followed = getattr(self, "_preempt_followed", None)
        if followed is None:
            followed = self._preempt_followed = set()
        from ..structs.eval import new_eval

        for victims in result.node_preemptions.values():
            for victim in victims:
                if victim.job_id in followed:
                    continue
                followed.add(victim.job_id)
                job = self.state.job_by_id(victim.job_id)
                if job is None:
                    continue
                self.planner.create_eval(
                    new_eval(job, consts.EVAL_TRIGGER_PREEMPTION))

    # ------------------------------------------------------------------

    def _inplace_update(self, updates: List[AllocTuple]):
        """In-place-vs-destructive routing hook: the host scheduler
        runs the reference's sequential stage-evict-select-pop pass;
        the dense subclass swaps in the batched host-side check
        (scheduler/util.py inplace_update_batched) so only genuinely
        destructive updates reach the device placement path."""
        return inplace_update(
            self.ctx, self.eval, self.job, self.stack, updates)

    def _live_defrag_marks(self) -> set:
        """The eval's defrag-marked alloc ids, IF the wave is still
        live. Expired markers (defrag_wave_expires passed — the loop
        abandoned the wave and released its governor slots) are void:
        staging budget-exempt evictions against slots nobody holds
        would silently exceed migrate_max_parallel, and the solve the
        markers came from is stale regardless. One gate feeds BOTH the
        ignore->migrate promotion and the budget exemption, so they
        can never disagree."""
        ids = self.eval.defrag_alloc_ids
        if not ids:
            return set()
        expires = self.eval.defrag_wave_expires
        if expires and time.time() >= expires:
            self.logger.info(
                "eval %s: defrag wave markers expired; ignoring %d "
                "marked allocs", self.eval.id, len(ids))
            return set()
        return set(ids)

    def _defer_migrations(self) -> None:
        """Mint (once per eval) the follow-up migration eval that
        re-runs this job's reconciliation for the displaced allocs the
        budget deferred. Deliberately NOT placed in the next_eval slot:
        that seat belongs to the rolling-update stagger follow-up, and
        displacing it would collapse the operator's stagger pacing to
        MIGRATE_RETRY_WAIT whenever a drain coincides with a rolling
        deploy — the two follow-ups coexist (the broker dedups per-job
        delivery; a no-op re-reconciliation is cheap)."""
        if self._migration_eval is not None:
            return
        from ..migrate import MIGRATE_RETRY_WAIT

        ev = self.eval.next_migration_eval(MIGRATE_RETRY_WAIT)
        self._migration_eval = ev
        self.planner.create_eval(ev)

    def _filter_complete_allocs(self, allocs: List[Allocation]):
        """Drop terminal allocs; for batch, keep successfully-completed
        work done and replace only failures (generic_sched.go:281)."""

        def should_filter(a: Allocation) -> bool:
            if self.batch:
                if a.desired_status in (
                    consts.ALLOC_DESIRED_STOP,
                    consts.ALLOC_DESIRED_EVICT,
                ):
                    return not a.ran_successfully()
                return a.client_status == consts.ALLOC_CLIENT_FAILED
            return a.terminal_status()

        terminal: Dict[str, Allocation] = {}
        remaining: List[Allocation] = []
        for a in allocs:
            if should_filter(a):
                prev = terminal.get(a.name)
                if prev is None or prev.create_index < a.create_index:
                    terminal[a.name] = a
            else:
                remaining.append(a)

        if self.batch:
            # Keep only the newest alloc per slot name.
            by_name: Dict[str, Allocation] = {}
            for a in remaining:
                cur = by_name.get(a.name)
                if cur is None or cur.create_index < a.create_index:
                    by_name[a.name] = a
            remaining = list(by_name.values())

        return remaining, terminal

    def _compute_job_allocs(self) -> None:
        groups = materialize_task_groups(self.job)
        allocs = self.state.allocs_by_job(self.eval.job_id)
        tainted = tainted_nodes(self.state, allocs)

        update_non_terminal_allocs_to_lost(self.plan, tainted, allocs)

        allocs, terminal_allocs = self._filter_complete_allocs(allocs)

        diff = diff_allocs(self.job, tainted, groups, allocs, terminal_allocs)

        # Continuous defragmentation (nomad_tpu/defrag): a defrag eval
        # marks specific healthy allocs for migration — promote them
        # out of the ignore bucket so they ride the SAME evict-and-
        # place leg as drain migrations (applier-verified eviction +
        # replacement placement in one plan, exactly-once terminal).
        # Allocs the diff already routed elsewhere (update/stop/lost:
        # the cluster moved since the solve snapshot) keep their
        # routing — defrag never overrides reconciliation.
        marked = self._live_defrag_marks()
        if marked:
            keep: List[AllocTuple] = []
            for tup in diff.ignore:
                if (tup.alloc is not None and tup.alloc.id in marked
                        and not tup.alloc.terminal_status()):
                    diff.migrate.append(tup)
                else:
                    keep.append(tup)
            diff.ignore = keep

        self.logger.debug("eval %s job %s: %s", self.eval.id, self.eval.job_id, diff)

        for e in diff.stop:
            self.plan.append_update(e.alloc, consts.ALLOC_DESIRED_STOP, ALLOC_NOT_NEEDED)

        destructive, inplace = self._inplace_update(diff.update)
        diff.update = destructive

        if self.eval.annotate_plan:
            from ..structs import PlanAnnotations

            self.plan.annotations = PlanAnnotations(
                desired_tg_updates=desired_updates(diff, inplace, destructive)
            )

        limit = [len(diff.update) + len(diff.migrate) + len(diff.lost)]
        if self.job is not None and self.job.update is not None and self.job.update.rolling():
            limit = [self.job.update.max_parallel]

        # Drain-storm migration budget (nomad_tpu/migrate): claim
        # in-flight slots for the displaced allocs; whatever the
        # governor defers rides a follow-up migration eval instead of
        # joining this plan — a 100-node drain storm drains in bounded
        # waves instead of thundering-herding the plan queue.
        migrate_now = diff.migrate
        if migrate_now:
            from .. import trace
            from ..migrate import check_migration_chaos, get_governor

            check_migration_chaos(self.eval.id)
            _t0 = time.monotonic()
            # Defrag-marked migrations are budget-EXEMPT here: the
            # defrag loop already claimed their governor slots when it
            # minted the wave (and releases them when this eval goes
            # terminal) — re-claiming would double-count the wave
            # against migrate_max_parallel. They sort first so a
            # partial grant never defers a pre-claimed move. The
            # exemption applies only while the wave's markers are LIVE
            # (_live_defrag_marks): past defrag_wave_expires the loop
            # has released those slots.
            pre_claimed = 0
            marked = self._live_defrag_marks()
            if marked:
                pre = [t for t in migrate_now
                       if t.alloc is not None and t.alloc.id in marked]
                rest = [t for t in migrate_now
                        if t.alloc is None or t.alloc.id not in marked]
                migrate_now = pre + rest
                pre_claimed = len(pre)
            granted = pre_claimed + get_governor().acquire(
                len(migrate_now) - pre_claimed)
            self._migrate_permits += granted - pre_claimed
            deferred = len(migrate_now) - granted
            if deferred:
                migrate_now = migrate_now[:granted]
                self._defer_migrations()
            self.limit_reached = evict_and_place(
                self.ctx, diff, migrate_now, ALLOC_MIGRATING, limit
            )
            trace.record_span(
                self.eval.id, trace.STAGE_MIGRATE_PLACE, _t0,
                ann={"migrations": len(migrate_now),
                     "deferred": deferred},
                trace_id=self.eval.trace_id)
        else:
            self.limit_reached = False
        self.limit_reached = self.limit_reached or evict_and_place(
            self.ctx, diff, diff.update, ALLOC_UPDATING, limit
        )
        self.limit_reached = self.limit_reached or mark_lost_and_place(
            self.ctx, diff, diff.lost, ALLOC_LOST, limit
        )

        if not diff.place:
            if self.job is not None:
                for tg in self.job.task_groups:
                    self.queued_allocs[tg.name] = 0
            return

        for tup in diff.place:
            self.queued_allocs[tup.task_group.name] = (
                self.queued_allocs.get(tup.task_group.name, 0) + 1
            )

        self._compute_placements(diff.place)

    def _compute_placements(self, place: List[AllocTuple]) -> None:
        nodes, by_dc = ready_nodes_in_dcs(self.state, self.job.datacenters)
        self.stack.set_nodes(nodes)

        for missing in place:
            if self.failed_tg_allocs and missing.task_group.name in self.failed_tg_allocs:
                self.failed_tg_allocs[missing.task_group.name].coalesced_failures += 1
                continue

            preferred = self._find_preferred_node(missing)
            if preferred is not None:
                option, _ = self.stack.select_preferring_nodes(
                    missing.task_group, [preferred]
                )
            else:
                option, _ = self.stack.select(missing.task_group)

            self.ctx.metrics.nodes_available = by_dc

            if option is not None:
                alloc = Allocation(
                    id=generate_uuid(),
                    eval_id=self.eval.id,
                    name=missing.name,
                    job_id=self.job.id,
                    task_group=missing.task_group.name,
                    metrics=self.ctx.metrics,
                    node_id=option.node.id,
                    task_resources=option.task_resources,
                    desired_status=consts.ALLOC_DESIRED_RUN,
                    client_status=consts.ALLOC_CLIENT_PENDING,
                    shared_resources=Resources(
                        disk_mb=missing.task_group.ephemeral_disk.size_mb
                    ),
                )
                if missing.alloc is not None:
                    alloc.previous_allocation = missing.alloc.id
                self.plan.append_alloc(alloc)
            else:
                if self.failed_tg_allocs is None:
                    self.failed_tg_allocs = {}
                self.failed_tg_allocs[missing.task_group.name] = self.ctx.metrics

    def _find_preferred_node(self, missing: AllocTuple):
        """Sticky ephemeral disk pins the replacement to its old node;
        a defrag eval prefers the solver's target node for each marked
        alloc (a PREFERENCE: select_preferring_nodes falls back to the
        full node set, so an infeasible target costs nothing)."""
        if missing.alloc is not None and self.eval.defrag_targets:
            target_id = self.eval.defrag_targets.get(missing.alloc.id)
            if target_id:
                node = self.state.node_by_id(target_id)
                if node is not None and node.ready():
                    return node
        if missing.alloc is None or missing.alloc.job is None:
            return None
        tg = missing.alloc.job.lookup_task_group(missing.alloc.task_group)
        if tg is None or tg.ephemeral_disk is None or not tg.ephemeral_disk.sticky:
            return None
        node = self.state.node_by_id(missing.alloc.node_id)
        if node is not None and node.ready():
            return node
        return None
