"""GenericScheduler: service and batch jobs.

Reference: scheduler/generic_sched.go:59 (GenericScheduler),
:103 (Process), :183 (process), :281 (filterCompleteAllocs),
:349 (computeJobAllocs), :432 (computePlacements),
:507 (findPreferredNode).
"""

from __future__ import annotations

import logging
import random
import time
from typing import Dict, List, Optional

from ..structs import (
    AllocMetric,
    Allocation,
    Evaluation,
    Job,
    Plan,
    PlanResult,
    Resources,
    consts,
)
from ..utils.ids import generate_uuid
from .context import EvalContext
from .stack import GenericStack
from .util import (
    ALLOC_GANG_REPLACED,
    ALLOC_LOST,
    ALLOC_MIGRATING,
    ALLOC_NOT_NEEDED,
    ALLOC_UPDATING,
    AllocTuple,
    _append_update_with_client,
    SetStatusError,
    adjust_queued_allocations,
    desired_updates,
    diff_allocs,
    evict_and_place,
    inplace_update,
    mark_lost_and_place,
    materialize_task_groups,
    progress_made,
    ready_nodes_in_dcs,
    retry_max,
    set_status,
    tainted_nodes,
    update_non_terminal_allocs_to_lost,
)

MAX_SERVICE_SCHEDULE_ATTEMPTS = 5
MAX_BATCH_SCHEDULE_ATTEMPTS = 2

BLOCKED_EVAL_MAX_PLAN_DESC = "created due to placement conflicts"
BLOCKED_EVAL_FAILED_PLACEMENTS = "created to place remaining allocations"


class GenericScheduler:
    def __init__(self, logger, state, planner, batch: bool,
                 rng: Optional[random.Random] = None):
        self.logger = logger or logging.getLogger("nomad_tpu.scheduler")
        self.state = state
        self.planner = planner
        self.batch = batch
        self.rng = rng or random.Random()

        self.eval: Optional[Evaluation] = None
        self.job: Optional[Job] = None
        self.plan: Optional[Plan] = None
        self.plan_result: Optional[PlanResult] = None
        self.ctx: Optional[EvalContext] = None
        self.stack: Optional[GenericStack] = None

        self.limit_reached = False
        self.next_eval: Optional[Evaluation] = None
        self.blocked: Optional[Evaluation] = None
        self.failed_tg_allocs: Optional[Dict[str, AllocMetric]] = None
        self.queued_allocs: Optional[Dict[str, int]] = None
        # Migration-budget bookkeeping (nomad_tpu/migrate): slots this
        # attempt holds (released when the attempt's submit finishes)
        # and the follow-up eval minted for deferred displaced allocs.
        self._migrate_permits = 0
        self._migration_eval: Optional[Evaluation] = None

    # ------------------------------------------------------------------

    def process_eval(self, eval: Evaluation) -> None:
        """Handle a single evaluation end to end."""
        self.eval = eval

        if eval.triggered_by not in (
            consts.EVAL_TRIGGER_JOB_REGISTER,
            consts.EVAL_TRIGGER_NODE_UPDATE,
            consts.EVAL_TRIGGER_JOB_DEREGISTER,
            consts.EVAL_TRIGGER_ROLLING_UPDATE,
            consts.EVAL_TRIGGER_PERIODIC_JOB,
            consts.EVAL_TRIGGER_MAX_PLANS,
            consts.EVAL_TRIGGER_MIGRATION,
            consts.EVAL_TRIGGER_PREEMPTION,
            consts.EVAL_TRIGGER_DEFRAG,
        ):
            desc = f"scheduler cannot handle '{eval.triggered_by}' evaluation reason"
            set_status(
                self.logger, self.planner, self.eval, self.next_eval, self.blocked,
                self.failed_tg_allocs, consts.EVAL_STATUS_FAILED, desc,
                self.queued_allocs,
            )
            return

        limit = MAX_BATCH_SCHEDULE_ATTEMPTS if self.batch else MAX_SERVICE_SCHEDULE_ATTEMPTS
        try:
            retry_max(limit, self._process, lambda: progress_made(self.plan_result))
        except SetStatusError as err:
            # No forward progress: leave a blocked eval to retry when
            # resources change, then record the failure.
            self._create_blocked_eval(plan_failure=True)
            set_status(
                self.logger, self.planner, self.eval, self.next_eval, self.blocked,
                self.failed_tg_allocs, err.eval_status, str(err), self.queued_allocs,
            )
            return

        # A blocked eval that still couldn't place everything goes back to
        # the blocked tracker with refreshed class eligibility.
        if (
            self.eval.status == consts.EVAL_STATUS_BLOCKED
            and self.failed_tg_allocs
        ):
            e = self.ctx.eligibility
            new_eval = self.eval.copy()
            new_eval.escaped_computed_class = e.has_escaped()
            new_eval.class_eligibility = e.get_classes()
            self.planner.reblock_eval(new_eval)
            return

        set_status(
            self.logger, self.planner, self.eval, self.next_eval, self.blocked,
            self.failed_tg_allocs, consts.EVAL_STATUS_COMPLETE, "",
            self.queued_allocs,
        )

    # ------------------------------------------------------------------

    def _create_blocked_eval(self, plan_failure: bool) -> None:
        e = self.ctx.eligibility
        escaped = e.has_escaped()
        class_eligibility = {} if escaped else e.get_classes()
        self.blocked = self.eval.create_blocked_eval(class_eligibility, escaped)
        if plan_failure:
            self.blocked.triggered_by = consts.EVAL_TRIGGER_MAX_PLANS
            self.blocked.status_description = BLOCKED_EVAL_MAX_PLAN_DESC
        else:
            self.blocked.status_description = BLOCKED_EVAL_FAILED_PLACEMENTS
        self.planner.create_eval(self.blocked)

    def _process(self) -> bool:
        """One scheduling attempt; returns True when done. Migration-
        budget slots claimed by the attempt (nomad_tpu/migrate) are
        held until its plan submit finishes — success or failure, the
        displaced allocs are no longer in flight HERE once the attempt
        ends, and a retry re-claims against fresh state."""
        self._migrate_permits = 0
        try:
            return self._process_attempt()
        finally:
            if self._migrate_permits:
                from ..migrate import get_governor

                get_governor().release(self._migrate_permits)
                self._migrate_permits = 0

    def _process_attempt(self) -> bool:
        self.job = self.state.job_by_id(self.eval.job_id)
        self.queued_allocs = {}

        self.plan = self.eval.make_plan(self.job)
        self.failed_tg_allocs = None
        self.ctx = EvalContext(self.state, self.plan, self.logger, rng=self.rng)
        self.stack = GenericStack(self.batch, self.ctx)
        if self.job is not None:
            self.stack.set_job(self.job)

        self._compute_job_allocs()

        # Unplaced allocations need a blocked eval to retry on capacity
        # changes; reuse the current one if we're already blocked.
        if (
            self.eval.status != consts.EVAL_STATUS_BLOCKED
            and self.failed_tg_allocs
            and self.blocked is None
        ):
            self._create_blocked_eval(plan_failure=False)

        if self.plan.is_no_op() and not self.eval.annotate_plan:
            return True

        # Rolling-update limit reached: schedule the next batch after the
        # stagger period.
        if self.limit_reached and self.next_eval is None:
            self.next_eval = self.eval.next_rolling_eval(self.job.update.stagger)
            self.planner.create_eval(self.next_eval)

        result, new_state = self.planner.submit_plan(self.plan)
        self.plan_result = result

        adjust_queued_allocations(self.logger, result, self.queued_allocs)

        if result is not None and result.node_preemptions:
            self._create_preemption_followups(result)

        if new_state is not None:
            self.state = new_state
            return False

        full_commit, expected, actual = result.full_commit(self.plan)
        if not full_commit:
            self.logger.debug(
                "eval %s: attempted %d placements, %d placed",
                self.eval.id, expected, actual,
            )
            raise RuntimeError("missing state refresh after partial commit")

        return True

    def _create_preemption_followups(self, result: PlanResult) -> None:
        """Every job whose alloc this plan's preemption leg evicted
        gets a replacement eval (triggered_by=preemption) — it usually
        blocks until capacity returns (the cluster was red), but the
        evicted work is never silently forgotten. One eval per job per
        process_eval, however many attempts commit victims."""
        followed = getattr(self, "_preempt_followed", None)
        if followed is None:
            followed = self._preempt_followed = set()
        from ..structs.eval import new_eval

        for victims in result.node_preemptions.values():
            for victim in victims:
                if victim.job_id in followed:
                    continue
                followed.add(victim.job_id)
                job = self.state.job_by_id(victim.job_id)
                if job is None:
                    continue
                self.planner.create_eval(
                    new_eval(job, consts.EVAL_TRIGGER_PREEMPTION))

    # ------------------------------------------------------------------

    def _inplace_update(self, updates: List[AllocTuple]):
        """In-place-vs-destructive routing hook: the host scheduler
        runs the reference's sequential stage-evict-select-pop pass;
        the dense subclass swaps in the batched host-side check
        (scheduler/util.py inplace_update_batched) so only genuinely
        destructive updates reach the device placement path."""
        return inplace_update(
            self.ctx, self.eval, self.job, self.stack, updates)

    def _live_defrag_marks(self) -> set:
        """The eval's defrag-marked alloc ids, IF the wave is still
        live. Expired markers (defrag_wave_expires passed — the loop
        abandoned the wave and released its governor slots) are void:
        staging budget-exempt evictions against slots nobody holds
        would silently exceed migrate_max_parallel, and the solve the
        markers came from is stale regardless. One gate feeds BOTH the
        ignore->migrate promotion and the budget exemption, so they
        can never disagree."""
        ids = self.eval.defrag_alloc_ids
        if not ids:
            return set()
        expires = self.eval.defrag_wave_expires
        if expires and time.time() >= expires:
            self.logger.info(
                "eval %s: defrag wave markers expired; ignoring %d "
                "marked allocs", self.eval.id, len(ids))
            return set()
        return set(ids)

    def _route_updates(self, updates: List[AllocTuple]):
        """In-place routing with GANG all-or-nothing semantics
        (nomad_tpu/gang): a gang task group's updates go in-place only
        if EVERY member does. A mixed verdict — some members in-place,
        some destructive (a tightened constraint failing on one node,
        a dead node) — would hide the in-place members from
        _promote_gang_replacements, which only reads the diff buckets:
        the gang would re-place a PARTIAL member set, the exact state
        the all-K program exists to reject. On a mixed verdict the
        already-staged in-place rewrites unwind off the plan and every
        member routes destructive, so promotion rebuilds the whole
        gang."""
        from ..gang import gang_spec

        gang_updates: Dict[str, List[AllocTuple]] = {}
        rest: List[AllocTuple] = []
        for tup in updates:
            tg = tup.task_group
            if tg is not None and gang_spec(tg) is not None:
                gang_updates.setdefault(tg.name, []).append(tup)
            else:
                rest.append(tup)
        destructive, inplace = self._inplace_update(rest)
        for name, tuples in gang_updates.items():
            g_destr, g_inplace = self._inplace_update(tuples)
            if not g_destr:
                inplace.extend(g_inplace)
                continue
            # unwind the staged in-place rewrites (same alloc ids)
            staged = {t.alloc.id for t in g_inplace}
            if staged:
                for node_id in list(self.plan.node_allocation):
                    kept = [a for a in self.plan.node_allocation[node_id]
                            if a.id not in staged]
                    if kept:
                        self.plan.node_allocation[node_id] = kept
                    else:
                        del self.plan.node_allocation[node_id]
                self.logger.info(
                    "eval %s: gang %s/%s update split in-place/"
                    "destructive; routing all %d members destructive "
                    "for whole-gang replacement", self.eval.id,
                    self.eval.job_id, name, len(tuples))
            destructive.extend(g_destr)
            destructive.extend(g_inplace)
        return destructive, inplace

    def _promote_gang_replacements(self, diff) -> None:
        """Gang semantics for reconciliation (nomad_tpu/gang): if ANY
        member of a gang task group is being replaced (lost node,
        drained node, destructive update, or a missing slot), the
        WHOLE gang replaces — survivors in the ignore bucket are
        stopped and every member joins diff.place so the gang's
        placement pass runs with the complete member set (the all-K
        program rejects partial sets by construction). Gang members
        are pulled OUT of the migrate/update/lost buckets: the
        migration budget and rolling limits batch work in partial
        waves, and a partially-deferred gang could never place.

        Chaos site ``gang.member_lost`` fires here (drop = one live
        member's node died mid-flight: route it through the lost leg
        and let this promotion rebuild the gang)."""
        from ..gang import gang_task_groups

        gangs = gang_task_groups(self.job)
        if not gangs:
            return
        from ..chaos import chaos

        def of(bucket, name):
            return [t for t in bucket
                    if t.task_group is not None
                    and t.task_group.name == name]

        for tg in gangs:
            ignored = of(diff.ignore, tg.name)
            lost = of(diff.lost, tg.name)
            moving = (of(diff.place, tg.name) + of(diff.migrate, tg.name)
                      + of(diff.update, tg.name))
            if chaos.enabled and not lost and not moving and ignored:
                if chaos.fire("gang.member_lost", eval_id=self.eval.id,
                              job=self.eval.job_id) == "drop":
                    # A member's node died mid-flight: classify it the
                    # way tainted_nodes would have.
                    tup = ignored.pop(0)
                    diff.ignore.remove(tup)
                    diff.lost.append(tup)
                    lost = [tup]
            if not lost and not moving:
                continue  # gang untouched, or fully ignored
            if not ignored and not lost and not of(diff.migrate, tg.name) \
                    and not of(diff.update, tg.name):
                continue  # fresh placement: already the complete set
            self.logger.info(
                "eval %s: gang %s/%s member set disturbed; staging "
                "whole-gang replacement (%d survivors stopped)",
                self.eval.id, self.eval.job_id, tg.name, len(ignored))
            # Survivors + movers stop; every member re-places. Lost
            # members additionally record client LOST.
            for tup in of(diff.migrate, tg.name):
                diff.migrate.remove(tup)
                self.plan.append_update(
                    tup.alloc, consts.ALLOC_DESIRED_STOP,
                    ALLOC_GANG_REPLACED)
                diff.place.append(tup)
            for tup in of(diff.update, tg.name):
                diff.update.remove(tup)
                self.plan.append_update(
                    tup.alloc, consts.ALLOC_DESIRED_STOP,
                    ALLOC_GANG_REPLACED)
                diff.place.append(tup)
            for tup in of(diff.lost, tg.name):
                diff.lost.remove(tup)
                _append_update_with_client(
                    self.plan, tup.alloc, consts.ALLOC_DESIRED_STOP,
                    ALLOC_LOST, consts.ALLOC_CLIENT_LOST)
                diff.place.append(tup)
            for tup in ignored:
                diff.ignore.remove(tup)
                self.plan.append_update(
                    tup.alloc, consts.ALLOC_DESIRED_STOP,
                    ALLOC_GANG_REPLACED)
                diff.place.append(tup)

    def _defer_migrations(self) -> None:
        """Mint (once per eval) the follow-up migration eval that
        re-runs this job's reconciliation for the displaced allocs the
        budget deferred. Deliberately NOT placed in the next_eval slot:
        that seat belongs to the rolling-update stagger follow-up, and
        displacing it would collapse the operator's stagger pacing to
        MIGRATE_RETRY_WAIT whenever a drain coincides with a rolling
        deploy — the two follow-ups coexist (the broker dedups per-job
        delivery; a no-op re-reconciliation is cheap)."""
        if self._migration_eval is not None:
            return
        from ..migrate import MIGRATE_RETRY_WAIT

        ev = self.eval.next_migration_eval(MIGRATE_RETRY_WAIT)
        self._migration_eval = ev
        self.planner.create_eval(ev)

    def _filter_complete_allocs(self, allocs: List[Allocation]):
        """Drop terminal allocs; for batch, keep successfully-completed
        work done and replace only failures (generic_sched.go:281)."""

        def should_filter(a: Allocation) -> bool:
            if self.batch:
                if a.desired_status in (
                    consts.ALLOC_DESIRED_STOP,
                    consts.ALLOC_DESIRED_EVICT,
                ):
                    return not a.ran_successfully()
                return a.client_status == consts.ALLOC_CLIENT_FAILED
            return a.terminal_status()

        terminal: Dict[str, Allocation] = {}
        remaining: List[Allocation] = []
        for a in allocs:
            if should_filter(a):
                prev = terminal.get(a.name)
                if prev is None or prev.create_index < a.create_index:
                    terminal[a.name] = a
            else:
                remaining.append(a)

        if self.batch:
            # Keep only the newest alloc per slot name.
            by_name: Dict[str, Allocation] = {}
            for a in remaining:
                cur = by_name.get(a.name)
                if cur is None or cur.create_index < a.create_index:
                    by_name[a.name] = a
            remaining = list(by_name.values())

        return remaining, terminal

    def _compute_job_allocs(self) -> None:
        groups = materialize_task_groups(self.job)
        allocs = self.state.allocs_by_job(self.eval.job_id)
        tainted = tainted_nodes(self.state, allocs)

        update_non_terminal_allocs_to_lost(self.plan, tainted, allocs)

        allocs, terminal_allocs = self._filter_complete_allocs(allocs)

        diff = diff_allocs(self.job, tainted, groups, allocs, terminal_allocs)

        # Continuous defragmentation (nomad_tpu/defrag): a defrag eval
        # marks specific healthy allocs for migration — promote them
        # out of the ignore bucket so they ride the SAME evict-and-
        # place leg as drain migrations (applier-verified eviction +
        # replacement placement in one plan, exactly-once terminal).
        # Allocs the diff already routed elsewhere (update/stop/lost:
        # the cluster moved since the solve snapshot) keep their
        # routing — defrag never overrides reconciliation.
        marked = self._live_defrag_marks()
        if marked:
            keep: List[AllocTuple] = []
            for tup in diff.ignore:
                if (tup.alloc is not None and tup.alloc.id in marked
                        and not tup.alloc.terminal_status()):
                    diff.migrate.append(tup)
                else:
                    keep.append(tup)
            diff.ignore = keep

        self.logger.debug("eval %s job %s: %s", self.eval.id, self.eval.job_id, diff)

        for e in diff.stop:
            self.plan.append_update(e.alloc, consts.ALLOC_DESIRED_STOP, ALLOC_NOT_NEEDED)

        destructive, inplace = self._route_updates(diff.update)
        diff.update = destructive

        # Whole-gang replacement (nomad_tpu/gang): a gang that loses or
        # must move ANY member cannot keep running at K-1 — survivors
        # are stopped and all K members re-place as one atomic unit.
        # Runs AFTER in-place routing (an env tweak keeps the gang in
        # place) and BEFORE the budget/limit legs (a gang must never be
        # split across migration waves or rolling batches).
        self._promote_gang_replacements(diff)

        if self.eval.annotate_plan:
            from ..structs import PlanAnnotations

            self.plan.annotations = PlanAnnotations(
                desired_tg_updates=desired_updates(diff, inplace, destructive)
            )

        limit = [len(diff.update) + len(diff.migrate) + len(diff.lost)]
        if self.job is not None and self.job.update is not None and self.job.update.rolling():
            limit = [self.job.update.max_parallel]

        # Drain-storm migration budget (nomad_tpu/migrate): claim
        # in-flight slots for the displaced allocs; whatever the
        # governor defers rides a follow-up migration eval instead of
        # joining this plan — a 100-node drain storm drains in bounded
        # waves instead of thundering-herding the plan queue.
        migrate_now = diff.migrate
        if migrate_now:
            from .. import trace
            from ..migrate import check_migration_chaos, get_governor

            check_migration_chaos(self.eval.id)
            _t0 = time.monotonic()
            # Defrag-marked migrations are budget-EXEMPT here: the
            # defrag loop already claimed their governor slots when it
            # minted the wave (and releases them when this eval goes
            # terminal) — re-claiming would double-count the wave
            # against migrate_max_parallel. They sort first so a
            # partial grant never defers a pre-claimed move. The
            # exemption applies only while the wave's markers are LIVE
            # (_live_defrag_marks): past defrag_wave_expires the loop
            # has released those slots.
            pre_claimed = 0
            marked = self._live_defrag_marks()
            if marked:
                pre = [t for t in migrate_now
                       if t.alloc is not None and t.alloc.id in marked]
                rest = [t for t in migrate_now
                        if t.alloc is None or t.alloc.id not in marked]
                migrate_now = pre + rest
                pre_claimed = len(pre)
            granted = pre_claimed + get_governor().acquire(
                len(migrate_now) - pre_claimed)
            self._migrate_permits += granted - pre_claimed
            deferred = len(migrate_now) - granted
            if deferred:
                migrate_now = migrate_now[:granted]
                self._defer_migrations()
            self.limit_reached = evict_and_place(
                self.ctx, diff, migrate_now, ALLOC_MIGRATING, limit
            )
            trace.record_span(
                self.eval.id, trace.STAGE_MIGRATE_PLACE, _t0,
                ann={"migrations": len(migrate_now),
                     "deferred": deferred},
                trace_id=self.eval.trace_id)
        else:
            self.limit_reached = False
        self.limit_reached = self.limit_reached or evict_and_place(
            self.ctx, diff, diff.update, ALLOC_UPDATING, limit
        )
        self.limit_reached = self.limit_reached or mark_lost_and_place(
            self.ctx, diff, diff.lost, ALLOC_LOST, limit
        )

        if not diff.place:
            if self.job is not None:
                for tg in self.job.task_groups:
                    self.queued_allocs[tg.name] = 0
            return

        for tup in diff.place:
            self.queued_allocs[tup.task_group.name] = (
                self.queued_allocs.get(tup.task_group.name, 0) + 1
            )

        self._compute_placements(diff.place)

    def _split_gang_placements(self, place: List[AllocTuple]):
        """(gang sets, rest): gang TGs' tuples grouped per task group
        for the all-or-nothing paths, everything else placed
        one-at-a-time as before."""
        from ..gang import gang_spec

        gang_sets: Dict[str, List[AllocTuple]] = {}
        gang_tgs = {}
        rest: List[AllocTuple] = []
        for missing in place:
            tg = missing.task_group
            if tg is not None and gang_spec(tg) is not None:
                gang_sets.setdefault(tg.name, []).append(missing)
                gang_tgs[tg.name] = tg
            else:
                rest.append(missing)
        return [(gang_tgs[name], tuples)
                for name, tuples in gang_sets.items()], rest

    def _place_gang_host(self, tg, tuples: List[AllocTuple]) -> None:
        """All-or-nothing gang placement through the host iterator
        stack (nomad_tpu/gang/host.py). Stages everything or records
        ONE whole-gang failure for the TG (which feeds the blocked-
        eval machinery like any other placement failure)."""
        from ..gang import note_gang_result
        from ..gang.host import place_gang_host
        from ..structs import AllocMetric

        if self.failed_tg_allocs and tg.name in self.failed_tg_allocs:
            self.failed_tg_allocs[tg.name].coalesced_failures += len(tuples)
            return
        ok = place_gang_host(self, tg, tuples)
        note_gang_result(ok, len(tuples), "host")
        if ok:
            return
        nodes, by_dc = ready_nodes_in_dcs(self.state, self.job.datacenters)
        metrics = AllocMetric()
        metrics.nodes_evaluated = len(nodes)
        metrics.nodes_available = by_dc
        if self.failed_tg_allocs is None:
            self.failed_tg_allocs = {}
        self.failed_tg_allocs[tg.name] = metrics
        # Gang-aware class eligibility: the member selects inside
        # place_gang_host ran the feasibility chain per class (the
        # FeasibilityWrapper populates ctx.eligibility), so infeasible
        # classes are already marked ineligible for the blocked eval;
        # classes it never visited stay unknown, which the blocked
        # tracker treats as eligible — capacity returning ANYWHERE a
        # gang might fit re-runs the all-K pass (unknown-is-eligible,
        # server/blocked.py), never the reverse.

    def _compute_placements(self, place: List[AllocTuple]) -> None:
        gang_sets, place = self._split_gang_placements(place)
        for tg, tuples in gang_sets:
            self._place_gang_host(tg, tuples)
        if not place:
            return
        nodes, by_dc = ready_nodes_in_dcs(self.state, self.job.datacenters)
        self.stack.set_nodes(nodes)

        for missing in place:
            if self.failed_tg_allocs and missing.task_group.name in self.failed_tg_allocs:
                self.failed_tg_allocs[missing.task_group.name].coalesced_failures += 1
                continue

            preferred = self._find_preferred_node(missing)
            if preferred is not None:
                option, _ = self.stack.select_preferring_nodes(
                    missing.task_group, [preferred]
                )
            else:
                option, _ = self.stack.select(missing.task_group)

            self.ctx.metrics.nodes_available = by_dc

            if option is not None:
                alloc = Allocation(
                    id=generate_uuid(),
                    eval_id=self.eval.id,
                    name=missing.name,
                    job_id=self.job.id,
                    task_group=missing.task_group.name,
                    metrics=self.ctx.metrics,
                    node_id=option.node.id,
                    task_resources=option.task_resources,
                    desired_status=consts.ALLOC_DESIRED_RUN,
                    client_status=consts.ALLOC_CLIENT_PENDING,
                    shared_resources=Resources(
                        disk_mb=missing.task_group.ephemeral_disk.size_mb
                    ),
                )
                if missing.alloc is not None:
                    alloc.previous_allocation = missing.alloc.id
                self.plan.append_alloc(alloc)
            else:
                if self.failed_tg_allocs is None:
                    self.failed_tg_allocs = {}
                self.failed_tg_allocs[missing.task_group.name] = self.ctx.metrics

    def _find_preferred_node(self, missing: AllocTuple):
        """Sticky ephemeral disk pins the replacement to its old node;
        a defrag eval prefers the solver's target node for each marked
        alloc (a PREFERENCE: select_preferring_nodes falls back to the
        full node set, so an infeasible target costs nothing)."""
        if missing.alloc is not None and self.eval.defrag_targets:
            target_id = self.eval.defrag_targets.get(missing.alloc.id)
            if target_id:
                node = self.state.node_by_id(target_id)
                if node is not None and node.ready():
                    return node
        if missing.alloc is None or missing.alloc.job is None:
            return None
        tg = missing.alloc.job.lookup_task_group(missing.alloc.task_group)
        if tg is None or tg.ephemeral_disk is None or not tg.ephemeral_disk.sticky:
            return None
        node = self.state.node_by_id(missing.alloc.node_id)
        if node is not None and node.ready():
            return node
        return None
