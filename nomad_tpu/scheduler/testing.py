"""Scheduler test harness: a real state store + recording planner that
applies plans sequentially. Also used in production by the dry-run
`Job.Plan` RPC.

Reference: scheduler/testing.go:38 (Harness), :15 (RejectPlan).
"""

from __future__ import annotations

import logging
import random
import threading
from typing import List, Optional

from ..state import StateStore
from ..structs import Evaluation, Plan, PlanResult, consts
from . import new_scheduler

# ntalint raft-funnel manifest (analysis/protocol.py): the Harness IS
# the raft apply path of the CPU oracle — its sequential submit_plan
# plays the role DevLog/FSM.apply play in a live cluster (and the
# dry-run Job.Plan RPC runs it against a shadow store copy that is
# never the live one). Store mutators inside it are the oracle's
# commit, not a bypass. seed_harness_cluster is the rig-fixture twin:
# registering nodes/jobs/load into a Harness's PRIVATE store is what
# raft-applied registration does to a live one — and keeping it here
# keeps the kernels/ differential rig itself store-mutator-free
# (kernels never touch the state store; they only return plans).
NTA_RAFT_FUNNELS = ("Harness.submit_plan", "seed_harness_cluster")


def seed_harness_cluster(harness: "Harness", nodes=(), allocs=(),
                         jobs=(), drained=()) -> None:
    """Seed a Harness's store for a differential/parity case: nodes,
    pre-existing allocations, jobs, then drain transitions — the
    oracle-side fixture path (see the funnel note above)."""
    for node in nodes:
        harness.state.upsert_node(harness.next_index(), node)
    if allocs:
        harness.state.upsert_allocs(harness.next_index(), list(allocs))
    for job in jobs:
        harness.state.upsert_job(harness.next_index(), job)
    for node_id in drained:
        harness.state.update_node_drain(
            harness.next_index(), node_id, True)


def seed_consolidation_cluster(harness: "Harness", n_nodes: int,
                               factory: str = "service",
                               big_prefix: str = "cbig",
                               small_prefix: str = "csmall"):
    """The shared fragmentation fixture (defrag rig + bench arm): a
    fleet of 1000/1000-capacity nodes running a mixed service workload
    — 600/600 'big' jobs and 300/300 'small' jobs, placed through the
    real scheduler — whose churn-stopped smalls leave the sub-ask
    remainders consolidation exists for. One builder, so the bench
    trajectory and the differential rig can never silently judge
    different workloads. Returns (nodes, jobs); store writes route
    through seed_harness_cluster (the fixture funnel)."""
    from .. import mock
    from ..structs import consts
    from ..structs.eval import new_eval

    nodes = []
    for _ in range(n_nodes):
        node = mock.node()
        node.resources.cpu = 1000
        node.resources.memory_mb = 1000
        node.reserved = None
        node.compute_class()
        nodes.append(node)

    def mkjob(jid, count, cpu, mem):
        job = mock.job()
        job.id = jid
        job.task_groups[0].count = count
        task = job.task_groups[0].tasks[0]
        task.resources.cpu = cpu
        task.resources.memory_mb = mem
        task.resources.networks = []
        return job

    jobs = [mkjob(f"{big_prefix}{j}", 4, 600, 600)
            for j in range(n_nodes // 8)]
    jobs += [mkjob(f"{small_prefix}{j}", 6, 300, 300)
             for j in range(n_nodes // 5)]
    seed_harness_cluster(harness, nodes=nodes, jobs=jobs)
    for job in jobs:
        harness.process(factory, new_eval(
            harness.state.job_by_id(job.id),
            consts.EVAL_TRIGGER_JOB_REGISTER))
    return nodes, jobs


def churn_stop_small_allocs(harness: "Harness", rng, prob: float,
                            small_prefix: str = "csmall"):
    """One churn sweep over a seed_consolidation_cluster: each live
    small-job alloc client-completes with probability `prob` (seeded
    rng — deterministic per seed), committed through the fixture
    funnel like a live cluster's ALLOC_CLIENT_UPDATE. Returns the
    stopped allocs."""
    from ..structs import consts

    stops = []
    for a in sorted((a for a in harness.state.allocs()
                     if not a.terminal_status()), key=lambda a: a.id):
        if a.job_id.startswith(small_prefix) and rng.random() < prob:
            upd = a.copy()
            upd.desired_status = consts.ALLOC_DESIRED_STOP
            upd.client_status = consts.ALLOC_CLIENT_COMPLETE
            stops.append(upd)
    seed_harness_cluster(harness, allocs=stops)
    return stops


class RejectPlan:
    """Planner that rejects every plan and forces a state refresh —
    exercises the refresh/retry loop."""

    def __init__(self, harness: "Harness"):
        self.harness = harness

    def submit_plan(self, plan: Plan):
        result = PlanResult()
        result.refresh_index = self.harness.next_index()
        return result, self.harness.state

    def update_eval(self, eval: Evaluation) -> None:
        pass

    def create_eval(self, eval: Evaluation) -> None:
        pass

    def reblock_eval(self, eval: Evaluation) -> None:
        pass


class Harness:
    def __init__(self, state: Optional[StateStore] = None,
                 seed: Optional[int] = None):
        self.state = state if state is not None else StateStore()
        self.planner = None  # optional custom planner
        self._plan_lock = threading.Lock()
        self._index_lock = threading.Lock()
        self._next_index = 1
        self.seed = seed

        self.plans: List[Plan] = []
        self.evals: List[Evaluation] = []
        self.create_evals: List[Evaluation] = []
        self.reblock_evals: List[Evaluation] = []

    def next_index(self) -> int:
        with self._index_lock:
            idx = self._next_index
            self._next_index += 1
            return idx

    # ------------------------------------------------------ Planner impl

    def submit_plan(self, plan: Plan):
        with self._plan_lock:
            self.plans.append(plan)
            delegate = self.planner
        if delegate is not None:
            # Delegate OUTSIDE the harness lock: a custom planner may
            # block (a real plan queue), and holding _plan_lock across
            # it would serialize every concurrent eval of the test
            # behind one submit instead of just the bookkeeping append.
            return delegate.submit_plan(plan)
        with self._plan_lock:
            index = self.next_index()
            result = PlanResult(
                node_update=plan.node_update,
                node_allocation=plan.node_allocation,
                node_preemptions=plan.node_preemptions,
                alloc_index=index,
            )

            allocs = []
            for update_list in plan.node_update.values():
                allocs.extend(update_list)
            for victim_list in plan.node_preemptions.values():
                allocs.extend(victim_list)
            for alloc_list in plan.node_allocation.values():
                allocs.extend(alloc_list)

            # Plans strip the job from allocs to avoid re-encoding it;
            # denormalize before inserting (other jobs' preemption
            # victims re-denormalize from their stored record, like
            # the FSM funnel does).
            for alloc in allocs:
                if alloc.job is None:
                    if plan.job is not None and alloc.job_id == plan.job.id:
                        alloc.job = plan.job
                    else:
                        stored = self.state.alloc_by_id(alloc.id)
                        if stored is not None:
                            alloc.job = stored.job
                # Stamp create/modify indexes on the result's allocs the way
                # the Go store mutates shared structs (state_store.go:922):
                # new allocs get this index, existing ones keep theirs —
                # adjust_queued_allocations relies on it.
                existing = self.state.alloc_by_id(alloc.id)
                alloc.create_index = existing.create_index if existing else index
                alloc.modify_index = index

            self.state.upsert_allocs(index, allocs)
            return result, None

    def update_eval(self, eval: Evaluation) -> None:
        with self._plan_lock:
            self.evals.append(eval)
            if self.planner is not None:
                self.planner.update_eval(eval)

    def create_eval(self, eval: Evaluation) -> None:
        with self._plan_lock:
            self.create_evals.append(eval)
            if self.planner is not None:
                self.planner.create_eval(eval)

    def reblock_eval(self, eval: Evaluation) -> None:
        with self._plan_lock:
            old = self.state.eval_by_id(eval.id)
            if old is None:
                raise ValueError("evaluation does not exist to be reblocked")
            if old.status != consts.EVAL_STATUS_BLOCKED:
                raise ValueError(
                    f"evaluation {old.id!r} is not already in a blocked state"
                )
            self.reblock_evals.append(eval)

    # ------------------------------------------------------ driving

    def snapshot(self):
        return self.state.snapshot()

    def process(self, scheduler_name: str, eval: Evaluation) -> None:
        logger = logging.getLogger("nomad_tpu.scheduler.harness")
        rng = random.Random(self.seed) if self.seed is not None else None
        sched = new_scheduler(scheduler_name, logger, self.snapshot(), self, rng=rng)
        sched.process_eval(eval)

    def assert_eval_status(self, status: str) -> None:
        assert len(self.evals) == 1, f"expected 1 eval update, got {self.evals!r}"
        assert self.evals[0].status == status, f"bad status: {self.evals[0]!r}"
