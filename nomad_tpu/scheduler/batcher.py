"""Placement batcher: coalesce concurrent evaluations into one TPU
dispatch.

The north star (BASELINE.json, SURVEY.md §5): evals drained from the
broker batch into a single device program — N workers' placement
requests with the same bucketed shapes ride one
`batched_placement_program` call instead of N serial dispatches. This
is the live-pipeline analog of bench.py's drain-to-batch measurement:
per-dispatch overhead (Python→XLA call, PRNG split, transfer) is paid
once per batch, and the vmapped program keeps the VPU busy.

Requests are grouped by compatibility key (node bucket, ask bucket,
group count, penalty): only same-shaped programs can share a dispatch
(no recompiles). A short accumulation window lets concurrent workers
pile on; a lone request ships immediately after it.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

MAX_BATCH = 64
WINDOW_S = 0.003  # accumulation window once a first request arrives


class _Request:
    __slots__ = ("state", "asks", "key", "event", "choices", "scores",
                 "error")

    def __init__(self, state, asks, key):
        self.state = state
        self.asks = asks
        self.key = key
        self.event = threading.Event()
        self.choices = None
        self.scores = None
        self.error: Optional[BaseException] = None


class PlacementBatcher:
    """Coalesces placement_program calls across scheduler threads."""

    def __init__(self, max_batch: int = MAX_BATCH, window: float = WINDOW_S):
        self.max_batch = max_batch
        self.window = window
        self.logger = logging.getLogger("nomad_tpu.batcher")
        self._lock = threading.Lock()
        self._queues: Dict[Tuple, List[_Request]] = {}
        self._dispatcher_live: Dict[Tuple, bool] = {}
        self.dispatches = 0  # observability: device calls issued
        self.batched_requests = 0  # requests served

    def place(self, state, asks, rng_key, config):
        """Submit one eval's placement; blocks until its batch's device
        dispatch returns. Returns (choices, scores) for THIS request."""
        shape_key = (
            state.util.shape, asks.resources.shape,
            state.feasible.shape[1], config,
        )
        req = _Request(state, asks, rng_key)
        run_dispatch = False
        with self._lock:
            self._queues.setdefault(shape_key, []).append(req)
            if not self._dispatcher_live.get(shape_key):
                # First in: this thread becomes the batch's dispatcher.
                self._dispatcher_live[shape_key] = True
                run_dispatch = True
        if run_dispatch:
            self._dispatch(shape_key, config)
        req.event.wait()
        if req.error is not None:
            raise req.error
        return req.choices, req.scores

    def _dispatch(self, shape_key, config) -> None:
        """Everything — including imports and the queue pop — runs
        under the error handler: a dispatcher that dies without setting
        its requests' events (e.g. a TPU runtime init failure) would
        wedge every worker on that shape forever."""
        batch: List[_Request] = []
        try:
            import time as _time

            import jax

            from ..ops.binpack import batched_placement_program

            # Accumulation window: let concurrent workers join.
            _time.sleep(self.window)
            with self._lock:
                waiting = self._queues.pop(shape_key, [])
                batch = waiting[: self.max_batch]
                leftover = waiting[self.max_batch:]
                if leftover:
                    # Overflow rides the next dispatch; dropping it
                    # would wedge those workers in event.wait().
                    self._queues[shape_key] = leftover
                self._dispatcher_live[shape_key] = False
            if not batch:
                return
            if len(batch) == 1:
                from ..ops.binpack import placement_program_jit

                req = batch[0]
                choices, scores, _ = placement_program_jit(
                    req.state, req.asks, req.key, config)
                req.choices = np.asarray(choices)
                req.scores = np.asarray(scores)
            else:
                states = jax.tree.map(
                    lambda *xs: np.stack(xs), *[r.state for r in batch])
                asks = jax.tree.map(
                    lambda *xs: np.stack(xs), *[r.asks for r in batch])
                keys = np.stack([r.key for r in batch])
                choices, scores, _ = batched_placement_program(
                    states, asks, keys, config)
                choices = np.asarray(choices)
                scores = np.asarray(scores)
                for i, req in enumerate(batch):
                    req.choices = choices[i]
                    req.scores = scores[i]
            self.dispatches += 1
            self.batched_requests += len(batch)
        except BaseException as e:  # noqa: BLE001 - propagate per request
            with self._lock:
                # Died before the pop: the queued requests are this
                # dispatcher's responsibility — fail them too, and
                # clear the live flag WE still hold. After the pop the
                # flag was already released (a newer dispatcher may own
                # it) — touching it then would let two run at once.
                if not batch:
                    batch = self._queues.pop(shape_key, [])
                    self._dispatcher_live[shape_key] = False
            for req in batch:
                req.error = e
        finally:
            for req in batch:
                req.event.set()
            # Anything that arrived during our device call gets its own
            # dispatcher (first of the leftovers may already have
            # claimed it via place()).
            with self._lock:
                if self._queues.get(shape_key) and not self._dispatcher_live.get(shape_key):
                    self._dispatcher_live[shape_key] = True
                    spawn = True
                else:
                    spawn = False
            if spawn:
                threading.Thread(
                    target=self._dispatch, args=(shape_key, config),
                    daemon=True, name="placement-batch").start()

    def stats(self) -> dict:
        return {
            "dispatches": self.dispatches,
            "batched_requests": self.batched_requests,
        }


_global: Optional[PlacementBatcher] = None
_global_lock = threading.Lock()


def get_batcher() -> PlacementBatcher:
    global _global
    with _global_lock:
        if _global is None:
            _global = PlacementBatcher()
        return _global
